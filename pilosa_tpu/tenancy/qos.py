"""Per-tenant QoS: admission quotas + priority shedding.

Tenant = index name.  Two independent quotas, both off by default:

- **qps quota** — a token bucket per tenant (capacity = one second of
  quota, refilled continuously).  An over-rate tenant sheds *before*
  taking an executor slot, so a runaway tenant's requests never queue
  in front of in-quota tenants.
- **slot quota** — a per-tenant cap on concurrently EXECUTING queries,
  strictly below the executor-wide ``max_concurrent``: one tenant can
  never occupy every admission slot.
- **device-seconds quota** (r19) — a cap on the tenant's RECENT
  measured device seconds (the cost ledger's exponentially decayed
  window).  qps counts requests; this counts what they actually cost
  on device, so one tenant's pathological shapes cannot soak the
  device from inside a modest request rate.

A shed raises :class:`TenantThrottledError`, which the API layer maps
to the same 503 + Retry-After contract the saturated executor already
speaks — with a structured ``tenantThrottled{tenant, quota, kind}``
body so the client knows it was ITS quota, not server overload.
"""

from __future__ import annotations

import threading
import time


class TenantThrottledError(Exception):
    """A tenant exceeded its qps or slot quota and was shed (HTTP 503
    + Retry-After with a structured ``tenantThrottled`` body at the
    API edge).  Deliberately NOT an ExecutionError subclass: the
    generic 400 mapping must never swallow a quota shed."""

    def __init__(self, msg: str, tenant: str, quota: float,
                 kind: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.tenant = tenant
        self.quota = quota
        self.kind = kind  # "qps" | "slots" | "device-seconds"
        self.retry_after = retry_after


class TenantQos:
    """Per-tenant admission state.  One lock over tiny dict updates —
    the admit check is a few float ops, far off the dispatch path."""

    def __init__(self, qps_quota: float = 0.0, slot_quota: int = 0,
                 stats=None, device_seconds_quota: float = 0.0,
                 ledger=None):
        from pilosa_tpu.obs import NopStats
        self.qps_quota = float(qps_quota)
        self.slot_quota = int(slot_quota)
        # device-seconds quota needs the measured side: the cost
        # ledger's decayed per-tenant recent-seconds window
        self.device_seconds_quota = float(device_seconds_quota)
        self._ledger = ledger
        self._stats = stats or NopStats()
        self._lock = threading.Lock()
        self._buckets: dict[str, list] = {}   # tenant -> [tokens, ts]
        self._inflight: dict[str, int] = {}
        self._sheds: dict[str, int] = {}

    @property
    def enabled(self) -> bool:
        return (self.qps_quota > 0 or self.slot_quota > 0
                or (self.device_seconds_quota > 0
                    and self._ledger is not None))

    def admit(self, tenant: str) -> None:
        """Admit one query for ``tenant`` or raise
        :class:`TenantThrottledError`.  On success the caller MUST
        pair with :meth:`release` (the slot-quota half is a no-op when
        that quota is off, but release is always safe)."""
        now = time.monotonic()
        with self._lock:
            if self.qps_quota > 0:
                burst = max(1.0, self.qps_quota)
                tok, last = self._buckets.get(tenant, (burst, now))
                tok = min(burst, tok + (now - last) * self.qps_quota)
                if tok < 1.0:
                    self._buckets[tenant] = [tok, now]
                    self._shed(tenant, self.qps_quota, "qps",
                               retry_after=(1.0 - tok) / self.qps_quota)
                self._buckets[tenant] = [tok - 1.0, now]
            if self.slot_quota > 0:
                used = self._inflight.get(tenant, 0)
                if used >= self.slot_quota:
                    self._shed(tenant, self.slot_quota, "slots",
                               retry_after=0.5)
                self._inflight[tenant] = used + 1
            if self.device_seconds_quota > 0 and self._ledger is not None:
                # measured feedback loop (r19): the ledger's decayed
                # recent device seconds — a tenant past its share of
                # actual device time sheds until the window decays
                # back under quota, whatever its request RATE was
                spent = self._ledger.recent_seconds(tenant)
                if spent >= self.device_seconds_quota:
                    self._inflight_undo(tenant)
                    self._shed(tenant, self.device_seconds_quota,
                               "device-seconds", retry_after=1.0)

    def _inflight_undo(self, tenant: str) -> None:
        """Caller holds the lock: back out the slot this admit just
        took before a later quota check sheds (the caller never runs
        its paired release() when admit raises)."""
        if self.slot_quota <= 0:
            return
        left = self._inflight.get(tenant, 0) - 1
        if left > 0:
            self._inflight[tenant] = left
        else:
            self._inflight.pop(tenant, None)

    def release(self, tenant: str) -> None:
        if self.slot_quota <= 0:
            return
        with self._lock:
            left = self._inflight.get(tenant, 0) - 1
            if left > 0:
                self._inflight[tenant] = left
            else:
                self._inflight.pop(tenant, None)

    def _shed(self, tenant: str, quota: float, kind: str,
              retry_after: float) -> None:
        # caller holds self._lock
        self._sheds[tenant] = self._sheds.get(tenant, 0) + 1
        self._stats.count("tenant_shed_total", 1, tenant=tenant)
        raise TenantThrottledError(
            f"tenant {tenant!r} over its {kind} quota ({quota:g}); "
            f"retry later", tenant, quota, kind,
            retry_after=max(0.05, retry_after))

    def sheds(self, tenant: str) -> int:
        return self._sheds.get(tenant, 0)

    def payload(self) -> dict:
        """The /status tenancy block's QoS half."""
        with self._lock:
            return {"qpsQuota": self.qps_quota,
                    "slotQuota": self.slot_quota,
                    "deviceSecondsQuota": self.device_seconds_quota,
                    "inflight": dict(self._inflight),
                    "sheds": dict(self._sheds),
                    "shedTotal": int(sum(self._sheds.values()))}
