"""Residency governor: cost/value eviction ordering + tenant quotas.

The PlaneCache's byte-budget pass was pure approximate-LRU; under a
many-tenant zipfian mix that evicts a hot tenant's expensive-to-rebuild
plane as readily as a cold tenant's cheap page.  The governor keeps
per-entry telemetry — decayed recent hits, bytes, build/page-in seconds
— and orders eviction by *keep-score*:

    keep = recent_hits × nbytes (bytes the entry served)
                       × max(build_seconds, floor)

Entries with no telemetry score 0.0, so ordering degrades to the
existing stamped LRU exactly (the cold-start and governor-less cases
are identical by construction — pinned by ``tests/test_tenancy.py``).

It also owns the per-tenant byte quota (tenant = index name): page-ins
and whole-plane admissions check ``admit_bytes`` before spending HBM on
a tenant already at its cap.
"""

from __future__ import annotations

import time

# below this many seconds, a build is considered free (sidecar-warm
# page-ins land here): the cost factor stops discriminating and the
# ordering is driven by recency-of-use value alone
_COST_FLOOR = 1e-3

# telemetry decay half-life: hit counts halve this often, so "recent
# hits" tracks the serving mix of the last few minutes, not all time
DECAY_SECONDS = 120.0

# telemetry map bound (keys are cache keys — user-controlled count):
# on overflow the coldest half is dropped; affected entries simply
# score 0.0 again (LRU fallback), never an error
_MAX_KEYS = 4096


class ResidencyGovernor:
    """Per-entry cost/value telemetry + per-tenant byte quotas.

    Thread contract mirrors the PlaneCache counters it feeds:
    :meth:`note_hit` runs on the lock-free serving path (plain dict
    increments — racing threads may lose the odd count, which a
    relative ordering never notices); everything that *reads* the
    telemetry for an eviction pass runs under the owning cache's
    lock."""

    def __init__(self, byte_quota: int = 0,
                 decay_seconds: float = DECAY_SECONDS):
        # tenant byte quota (bytes of resident plane/page entries one
        # tenant may hold; 0 = unlimited)
        self.byte_quota = int(byte_quota)
        self.decay_seconds = float(decay_seconds)
        self._hits: dict = {}            # key -> decayed hit count
        self._build_s: dict = {}         # key -> last build/page-in s
        self._last_decay = time.monotonic()

    # -- telemetry feed (lock-free callers) ---------------------------------

    def note_hit(self, key) -> None:
        self._hits[key] = self._hits.get(key, 0.0) + 1.0

    def note_build(self, key, seconds: float) -> None:
        self._build_s[key] = float(seconds)
        if len(self._build_s) > _MAX_KEYS:
            self._prune()

    def note_evict(self, key) -> None:
        # keep the build cost (re-admission of the same key should
        # remember what it costs) but reset its recency value
        self._hits.pop(key, None)

    # -- ordering (caller holds the owning cache's lock) --------------------

    def keep_score(self, key, nbytes: int) -> float:
        """Higher = more worth keeping.  0.0 when the entry has no
        recorded hits — the eviction sort then falls through to its
        LRU-stamp tie-break, i.e. exactly the pre-governor order."""
        self._maybe_decay()
        hits = self._hits.get(key)
        if not hits:
            return 0.0
        cost = max(self._build_s.get(key, 0.0), _COST_FLOOR)
        return hits * float(nbytes) * cost

    def _maybe_decay(self) -> None:
        now = time.monotonic()
        if now - self._last_decay < self.decay_seconds:
            return
        self._last_decay = now
        for k in list(self._hits):
            v = self._hits.get(k, 0.0) * 0.5
            if v < 0.25:
                self._hits.pop(k, None)
            else:
                self._hits[k] = v

    def _prune(self) -> None:
        # drop the cheapest half of the build-cost map; their entries
        # degrade to LRU ordering, never an error
        keep = sorted(self._build_s.items(), key=lambda kv: -kv[1])
        self._build_s = dict(keep[:_MAX_KEYS // 2])

    # -- admission ----------------------------------------------------------

    def admit_bytes(self, resident_bytes: int, want_bytes: int) -> bool:
        """Whether a tenant already holding ``resident_bytes`` may
        spend ``want_bytes`` more of HBM (True with quotas off)."""
        if self.byte_quota <= 0:
            return True
        return resident_bytes + want_bytes <= self.byte_quota
