"""Paged plane residency: partial planes as first-class cache entries.

A plane bigger than the HBM budget (or than its tenant's byte quota)
never materializes whole.  Its shard axis splits into fixed-byte
*pages* — consecutive shard groups sized so one page's slab stays under
``page_bytes`` — and each page is an ordinary :class:`PlaneCache` entry
(key ``("page", index, field, view, page_shards)``) with its OWN row
union and slot map, leased/evicted/delta-overlaid like any whole-view
plane.  The Count serving path answers resident pages on device
(selected-row gather or whole-page scan through the batcher) and covers
non-resident pages with the host oracle (``Fragment.row_cardinalities``
— directory sums, no bit expansion), summing per row across pages:
bit-exact by construction, device-speed in proportion to residency.

Page-ins ride the warm ``.dense`` sidecar path (each fragment expands
once, against the page's full row union, so sidecars are both honored
and written) and deliberately do NOT count as plane *builds* — once
sidecars are warm, a churning cache pages in at near-memcpy speed with
zero full rebuilds, which config32's acceptance bar pins.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from pilosa_tpu.engine.words import WORDS_PER_SHARD


class PlanePager:
    """Page partition + page residency + the non-resident oracle.

    Owns only the paging *mechanics*; policy (eviction order, tenant
    byte quotas) lives in the :class:`ResidencyGovernor` the cache and
    this pager share.  Single-device only — a partial page plane has no
    meaning under a mesh placement (the executor gates construction).
    """

    def __init__(self, cache, governor=None, page_bytes: int = 64 << 20,
                 stats=None, flight=None):
        from pilosa_tpu.obs import NULL_FLIGHT, NopStats
        self.cache = cache
        self.governor = governor
        self.page_bytes = max(1 << 20, int(page_bytes))
        self._stats = stats or NopStats()
        self.flight = flight or NULL_FLIGHT
        self._lock = threading.Lock()
        self.page_ins = 0
        self.page_in_seconds_total = 0.0
        self.oracle_serves = 0
        self.quota_denials = 0
        # per-tenant serving telemetry (tenant = index name):
        # hits = pages answered from residency, misses = page-in or
        # oracle coverage — the tenancy block's per-tenant hit ratio
        self._t_hits: dict[str, int] = {}
        self._t_misses: dict[str, int] = {}
        self._t_page_ins: dict[str, int] = {}

    # -- partition -----------------------------------------------------------

    def partition(self, field, view_name: str,
                  shards: tuple[int, ...]) -> list[tuple[int, ...]] | None:
        """Split ``shards`` into consecutive page groups sized to
        ``page_bytes`` (using the cached whole-plane estimate's
        per-shard slab).  None when the plane fits one page — plain
        whole-plane residency already handles that case."""
        if len(shards) < 2:
            return None
        est = self.cache.plane_bytes(field, view_name, shards)
        slab = max(1, est // len(shards))
        # a page must FIT in the cache (the insert path refuses
        # over-budget entries outright) with room left for a second
        # page — otherwise every "resident" page would be dropped on
        # insert and the warm path degrades to rebuild-per-query.
        # Same clamp against the tenant byte quota when one is set.
        eff = self.page_bytes
        if self.cache.budget > 0:
            eff = min(eff, max(slab, self.cache.budget // 2))
        g = self.governor
        if g is not None and g.byte_quota > 0:
            eff = min(eff, max(slab, g.byte_quota // 2))
        per = max(1, eff // slab)
        if per >= len(shards):
            return None
        return [tuple(shards[i:i + per])
                for i in range(0, len(shards), per)]

    @staticmethod
    def page_key(index: str, field, view_name: str,
                 page_shards: tuple[int, ...]) -> tuple:
        return ("page", index, field.name, view_name, page_shards)

    # -- residency -----------------------------------------------------------

    def resident_page(self, index: str, field, view_name: str,
                      page_shards: tuple[int, ...]):
        """The page's PlaneSet if it can serve from residency: fresh
        as-is, or stale with the write gap absorbed into its delta
        overlay / folded (the same machinery whole planes use — writes
        never force a page rebuild for an overlay-coverable gap).
        None = not resident, or refresh needs a re-read (the entry is
        dropped; the caller pages in against fragment truth)."""
        cache = self.cache
        key = self.page_key(index, field, view_name, page_shards)
        hit = cache._entries.get(key)  # GIL-atomic, lock-free
        if hit is None:
            return None
        if hit[0] == cache._gens_fast(field, view_name, page_shards):
            cache._touch(key)
            cache._lease_fast(key)
            cache.hits += 1
            self._note(self._t_hits, index)
            return hit[1]
        ps = cache._delta_update(key, field, view_name, page_shards, hit)
        if ps is not None:
            with cache._lock:
                cache._lease(key)
            cache.hits += 1
            self._note(self._t_hits, index)
            return ps
        # new rows / journal gap: the page's shape changed under it —
        # drop the entry so the page-in below re-reads fragment truth
        # (a sidecar-warm partial expansion, not a plane build)
        with cache._lock:
            if key in cache._entries and key not in cache._pinned():
                cache._evict_entry(key, "stale")
        return None

    def page_in(self, index: str, field, view_name: str,
                page_shards: tuple[int, ...]):
        """Materialize one page on device and cache it (leased to the
        calling query).  Admission runs the tenant's byte quota first,
        evicting the tenant's OWN coldest unpinned entries to make
        room; None when the quota still can't fit the page — the
        caller serves that page via the oracle instead."""
        cache = self.cache
        key = self.page_key(index, field, view_name, page_shards)
        gens = cache._gens(field, view_name, page_shards)
        row_ids = cache._union_row_ids(field, view_name, page_shards)
        r_pad = 1 << max(0, (max(1, len(row_ids)) - 1).bit_length())
        want = len(page_shards) * r_pad * WORDS_PER_SHARD * 4
        g = self.governor
        if g is not None and g.byte_quota > 0:
            resident = cache.tenant_bytes(index)
            if not g.admit_bytes(resident, want):
                over = resident + want - g.byte_quota
                cache.evict_tenant(index, over, reason="quota")
                if not g.admit_bytes(cache.tenant_bytes(index), want):
                    self.quota_denials += 1
                    self._note(self._t_misses, index)
                    return None
        t0 = time.perf_counter()
        ps = self._build_page(field, view_name, page_shards, row_ids)
        dt = time.perf_counter() - t0
        nbytes = ps.plane.size * 4
        cache._insert_entry(key, gens, ps, nbytes, lease=True)
        if g is not None:
            g.note_build(key, dt)
        self._stats.observe("plane_page_in_seconds", dt)
        self.flight.record("pagein", f"{index}/{field.name}",
                           f"{len(page_shards)} shards", dt)
        with self._lock:
            self.page_ins += 1
            self.page_in_seconds_total += dt
        self._note(self._t_page_ins, index)
        self._note(self._t_misses, index)
        return ps

    def _build_page(self, field, view_name: str,
                    page_shards: tuple[int, ...], row_ids: np.ndarray):
        """Partial-plane expansion over just the page's shards, via
        the sidecar-warm bulk path (each fragment expands once against
        the page's full row union, so ``.dense`` images are honored
        AND written).  Deliberately NOT counted in ``cache.builds`` —
        page-ins are residency churn, not plane rebuilds, and the
        zero-rebuild-once-warm acceptance bar reads that counter."""
        from concurrent.futures import ThreadPoolExecutor
        from functools import partial

        from pilosa_tpu.exec.planes import PAD_SHARD, PlaneSet
        cache = self.cache
        r_pad = 1 << max(0, (max(1, len(row_ids)) - 1).bit_length())
        host = np.zeros((len(page_shards), r_pad, WORDS_PER_SHARD),
                        dtype=np.uint32)
        slot_of = {int(r): i for i, r in enumerate(row_ids)}
        slots = np.arange(len(row_ids), dtype=np.uint64)
        view = field.view(view_name)
        tasks = []
        if view is not None and len(row_ids):
            for si, s in enumerate(page_shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    continue
                tasks.append(partial(
                    frag.expand_rows_into, row_ids, host[si], slots,
                    sidecar=cache.sidecars))
        if tasks:
            with ThreadPoolExecutor(
                    max_workers=cache.BUILD_WORKERS) as pool:
                cache._expand_tasks(pool, tasks)
        return PlaneSet(cache.place(host), page_shards, row_ids, slot_of)

    # -- non-resident oracle -------------------------------------------------

    def oracle_counts(self, field, view_name: str,
                      page_shards: tuple[int, ...],
                      row_ids: list) -> list[int]:
        """Per-row totals over a NON-resident page straight from host
        truth: ``Fragment.row_cardinalities`` directory sums — no bit
        expansion, no device transfer, exact by definition (it is the
        same oracle the plane builds are tested against).  ``None``
        entries in ``row_ids`` (absent rows) count 0."""
        from pilosa_tpu.exec.planes import PAD_SHARD
        totals = [0] * len(row_ids)
        view = field.view(view_name)
        if view is None:
            return totals
        want = [(i, int(r)) for i, r in enumerate(row_ids)
                if r is not None]
        if not want:
            return totals
        want_arr = np.asarray([r for _, r in want], np.uint64)
        for s in page_shards:
            if s == PAD_SHARD:
                continue
            frag = view.fragment(s)
            if frag is None:
                continue
            ids, cards = frag.row_cardinalities()
            if not len(ids):
                continue
            pos = np.searchsorted(ids, want_arr)
            ok = (pos < len(ids))
            pos = np.where(ok, pos, 0)
            match = ok & (ids[pos] == want_arr)
            for j, (i, _r) in enumerate(want):
                if match[j]:
                    totals[i] += int(cards[pos[j]])
        with self._lock:
            self.oracle_serves += 1
        return totals

    # -- telemetry -----------------------------------------------------------

    def _note(self, d: dict, tenant: str) -> None:
        with self._lock:
            d[tenant] = d.get(tenant, 0) + 1

    def tenant_breakdown(self) -> dict:
        """Per-tenant residency from the live cache: resident bytes,
        whole-plane entries, page entries."""
        cache = self.cache
        with cache._lock:
            items = [(k, v[2]) for k, v in cache._entries.items()]
        per: dict[str, dict] = {}
        for k, nb in items:
            d = per.setdefault(k[1], {"residentBytes": 0,
                                      "residentPages": 0,
                                      "residentEntries": 0})
            d["residentBytes"] += nb
            d["residentEntries"] += 1
            if k[0] == "page":
                d["residentPages"] += 1
        return per

    def payload(self) -> dict:
        """The /status tenancy block's paging half.  Also refreshes
        the ``plane_resident_pages`` gauge at scrape time (the
        mesh_stats idiom — the gauge is a snapshot of live cache
        state, not an incrementally maintained counter)."""
        per = self.tenant_breakdown()
        with self._lock:
            hits, misses = dict(self._t_hits), dict(self._t_misses)
            page_ins = dict(self._t_page_ins)
            totals = {"pageIns": self.page_ins,
                      "pageInSeconds": round(self.page_in_seconds_total,
                                             6),
                      "oracleServes": self.oracle_serves,
                      "quotaDenials": self.quota_denials}
        n_pages = sum(d["residentPages"] for d in per.values())
        self._stats.gauge("plane_resident_pages", n_pages)
        for t in set(hits) | set(misses) | set(page_ins):
            d = per.setdefault(t, {"residentBytes": 0,
                                   "residentPages": 0,
                                   "residentEntries": 0})
            h, m = hits.get(t, 0), misses.get(t, 0)
            d["pageHits"] = h
            d["pageMisses"] = m
            d["hitRatio"] = round(h / (h + m), 4) if h + m else 0.0
            d["pageIns"] = page_ins.get(t, 0)
        return {"pageBytes": self.page_bytes,
                "residentPages": n_pages, "tenants": per, **totals}
