"""Multi-tenant HBM economy (ISSUE 17).

Three cooperating layers turn the device plane cache from a static
placement into a managed economy across tenants (tenant = index):

- :mod:`pilosa_tpu.tenancy.paging` — paged plane residency: a plane
  too big for the HBM budget (or constrained by a per-tenant byte
  quota) never materializes whole.  Instead its shard axis splits into
  fixed-byte *pages*, each a partial ``PlaneSet`` cached/leased/evicted
  like any other entry; fused kernels answer the resident pages on
  device while the op-at-a-time host oracle covers the rest, bit-exact.
- :mod:`pilosa_tpu.tenancy.governor` — the eviction/admission policy:
  per-entry hit/cost telemetry turns the byte-budget LRU into a
  cost/value ordering (value = recent hits × bytes scanned, cost =
  rebuild seconds), and per-tenant byte quotas gate page-ins.
- :mod:`pilosa_tpu.tenancy.qos` — per-tenant admission quotas (qps
  token bucket + in-flight slot cap) shedding over-quota tenants with
  a structured ``tenantThrottled`` 503 while other tenants keep their
  floors.
"""

from pilosa_tpu.tenancy.governor import ResidencyGovernor
from pilosa_tpu.tenancy.paging import PlanePager
from pilosa_tpu.tenancy.qos import TenantQos, TenantThrottledError

__all__ = ["ResidencyGovernor", "PlanePager", "TenantQos",
           "TenantThrottledError"]
