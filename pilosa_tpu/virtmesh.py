"""Virtual CPU-mesh provisioning (SURVEY.md §5 simulated-mesh recipe).

Multi-chip TPU hardware is not assumed anywhere: the distribution path is
validated on an n-device *virtual* CPU mesh, the rebuild's analogue of the
reference's in-process multi-node test cluster (``test/cluster.go#
MustRunCluster``).  This image's sitecustomize imports jax early with a
TPU-tunnel PJRT plugin ("axon") registered, so setting env vars is not
enough — the live jax config must be updated and the non-CPU backend
factories dropped *before any backend initializes*.  This is the single
shared implementation of that recipe, used by both ``tests/conftest.py``
and the driver gate ``__graft_entry__.dryrun_multichip``.

This module must stay a leaf: importing it (and the ``pilosa_tpu``
package ``__init__``) must not create any jax device value, or the
default (TPU-tunnel) backend would initialize before the recipe can
retarget the process — see tests/test_import_hygiene.py.
"""

from __future__ import annotations

import os


def force_virtual_cpu_mesh(n_devices: int) -> bool:
    """Best-effort in-process provisioning of an ``n_devices`` virtual CPU
    mesh.  Returns True when a CPU backend with at least ``n_devices``
    devices is usable in this process.

    Mutates env/config only when no backend has initialized yet; if one
    has, reports whether it already satisfies the request so callers can
    fall back (e.g. to a fresh subprocess) without this process's env
    having been polluted.
    """
    import jax
    from jax._src import xla_bridge as _xb

    try:
        initialized = _xb.backends_are_initialized()
    except Exception:
        initialized = True  # unknown — don't risk retargeting a live backend
    if initialized:
        try:
            return (jax.default_backend() == "cpu"
                    and len(jax.devices("cpu")) >= n_devices)
        except Exception:
            return False

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    # replace any pre-existing (possibly smaller) count rather than
    # deferring to it — once the CPU backend initializes with too few
    # devices this process can never be re-provisioned
    kept = [f for f in flags.split()
            if not f.startswith("--xla_force_host_platform_device_count")]
    existing = [f for f in flags.split() if f not in kept]
    count = n_devices
    for f in existing:
        try:
            count = max(count, int(f.split("=", 1)[1]))
        except (IndexError, ValueError):
            pass
    kept.append(f"--xla_force_host_platform_device_count={count}")
    os.environ["XLA_FLAGS"] = " ".join(kept)

    jax.config.update("jax_platforms", "cpu")
    # Keep the 'tpu' platform NAME registered (pallas lowering registration
    # needs it at import time); jax_platforms=cpu prevents it initializing.
    for _name in list(getattr(_xb, "_backend_factories", {})):
        if _name not in ("cpu", "tpu"):
            _xb._backend_factories.pop(_name, None)
    try:
        return len(jax.devices("cpu")) >= n_devices
    except Exception:
        return False
