"""HTTP surface of the backup subsystem.

Reference: the fragment-data export endpoints behind ``ctl/backup.go``
(``/internal/fragment/data`` + translate/attr archives).  These routes
serve CONSISTENT per-fragment images to the backup driver and accept
the restore driver's translate pushes; unlike the ``/internal/*``
cluster surface they work on a single un-clustered node too (a one-box
deployment deserves backups).

Consistency: a fragment image is **generation-bracketed** — read the
generation, serialize, re-read; only equal brackets are served (the
same validation trick the executor's plan cache uses).  A fragment
under concurrent writes retries a bounded number of times, then takes
the fragment lock for one guaranteed-consistent capture.  The served
generation header is therefore always the generation OF the blob.

Every payload carries ``Content-Length`` (``_reply`` always does),
``X-Content-SHA256`` (end-to-end transfer integrity — the driver
verifies while streaming to disk) and, for fragments,
``X-Pilosa-Generation`` + ``X-Pilosa-Checksum`` (the restart-stable
position checksum incremental mode diffs on).
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import time
import zlib

from pilosa_tpu.api.api import ApiError
from pilosa_tpu.api.server import Handler, Router
from pilosa_tpu.store import roaring

# bounded bracketing retries before falling back to a capture under the
# fragment lock (writes in flight keep bumping the generation)
BRACKET_RETRIES = 4


def fragment_checksum(frag) -> str:
    """Restart-stable content checksum: crc32 over the fragment's
    sorted AAE block checksums.  ``Fragment.blocks()`` is generation-
    cached, so an unchanged fragment answers from cache — the property
    that makes incremental backup sweeps cheap."""
    items = sorted(frag.blocks().items())
    buf = b"".join(struct.pack("<qI", b, c & 0xFFFFFFFF)
                   for b, c in items)
    return format(zlib.crc32(buf), "08x")


def capture_fragment(frag) -> tuple[bytes, int, str]:
    """(roaring blob, generation, checksum) — generation-bracketed."""
    for _ in range(BRACKET_RETRIES):
        gen = frag.generation
        blob = roaring.serialize(frag.positions())
        checksum = fragment_checksum(frag)
        if frag.generation == gen:
            return blob, gen, checksum
    # hot fragment: one guaranteed capture under its lock
    with frag.lock:
        gen = frag.generation
        blob = roaring.serialize(frag.positions())
        checksum = fragment_checksum(frag)
    return blob, gen, checksum


def _find_fragment(handler: Handler, index: str, field: str, view: str,
                   shard: str):
    api = handler.server.api
    idx = api.holder.index(index)
    if idx is None:
        raise ApiError(f"index {index!r} not found", 404)
    f = idx.field(field)
    if f is None:
        raise ApiError(f"field {field!r} not found", 404)
    v = f.view(view)
    if v is None:
        raise ApiError(f"view {view!r} not found", 404)
    frag = v.fragment(int(shard))
    if frag is None:
        raise ApiError(f"fragment {shard} not found", 404)
    return frag


# -- handlers ----------------------------------------------------------------


def h_backup_inventory(self: Handler) -> None:
    """Every local fragment holding data, with generation + checksum
    when ``?checksums=1`` (incremental mode's skip detector).  Walks
    the holder directly — works clustered or not."""
    want_sums = "checksums" in self.query
    out = []
    holder = self.server.api.holder
    for iname, idx in list(holder.indexes.items()):
        for fname, f in list(idx.fields.items()):
            for vname, v in list(f.views.items()):
                for shard, frag in list(v.fragments.items()):
                    if not frag.present:
                        continue
                    ent = {"index": iname, "field": fname,
                           "view": vname, "shard": shard}
                    if want_sums:
                        ent["generation"] = frag.generation
                        ent["checksum"] = fragment_checksum(frag)
                    out.append(ent)
    self._reply({"fragments": out})


def h_backup_fragment(self: Handler, index: str, field: str, view: str,
                      shard: str) -> None:
    t0 = time.perf_counter()
    frag = _find_fragment(self, index, field, view, shard)
    # storage quarantine gate (r19): an archive must never capture a
    # corrupt copy — 503 here routes the driver onto its replica
    # fallback, exactly like a dead node mid-backup
    sh = getattr(self.server.api.holder, "storage_health", None)
    if sh is not None and sh.is_quarantined(frag.path):
        raise ApiError(
            f"fragment quarantined (storage corruption): {frag.path} "
            "— back up from a replica", 503, retry_after=2.0)
    blob, gen, checksum = capture_fragment(frag)
    digest = hashlib.sha256(blob).hexdigest()
    stats = getattr(self.server, "stats", None)
    if stats is not None:
        stats.count("backup_bytes_total", len(blob))
        stats.observe("backup_fragment_seconds",
                      time.perf_counter() - t0)
    self._reply(blob, content_type="application/octet-stream",
                headers={"X-Content-SHA256": digest,
                         "X-Pilosa-Generation": str(gen),
                         "X-Pilosa-Checksum": checksum})


def h_backup_schema(self: Handler) -> None:
    body = json.dumps({"schema": self.server.api.schema()}).encode()
    self._reply(body, headers={
        "X-Content-SHA256": hashlib.sha256(body).hexdigest()})


def h_backup_attrs_list(self: Handler) -> None:
    """Attribute stores present on disk: ``[{index, field|null}]``.
    Existence is judged by the ``_attrs.db`` file so listing never
    CREATES empty stores as a side effect."""
    holder = self.server.api.holder
    out = []
    for iname, idx in list(holder.indexes.items()):
        if os.path.exists(os.path.join(idx.path, "_attrs.db")):
            out.append({"index": iname, "field": None})
        for fname, f in list(idx.fields.items()):
            if os.path.exists(os.path.join(f.path, "_attrs.db")):
                out.append({"index": iname, "field": fname})
    self._reply({"stores": out})


def h_backup_attrs(self: Handler, index: str) -> None:
    """Full item dump of one attribute store."""
    holder = self.server.api.holder
    idx = holder.index(index)
    if idx is None:
        raise ApiError(f"index {index!r} not found", 404)
    field = self.query.get("field", [""])[0]
    if field:
        f = idx.field(field)
        if f is None:
            raise ApiError(f"field {field!r} not found", 404)
        store = f.row_attrs
    else:
        store = idx.column_attrs
    items: dict[str, dict] = {}
    for block in sorted(store.blocks()):
        items.update({str(k): v
                      for k, v in store.block_items(block).items()})
    body = json.dumps({"items": items}).encode()
    self._reply(body, headers={
        "X-Content-SHA256": hashlib.sha256(body).hexdigest()})


def h_restore_translate(self: Handler, index: str) -> None:
    """Restore-side translate append: same semantics as
    ``/internal/translate/replicate`` (append-only, offset-deduped)
    but serves un-clustered nodes too — restore of a keyed index must
    not require a cluster."""
    b = self._json_body()
    api = self.server.api
    log = (api.executor.translate.columns(index)
           if not b.get("field")
           else api.executor.translate.rows(index, b["field"]))
    try:
        log.append_replicated(int(b["start_id"]), b["keys"])
    except KeyError as e:
        raise ApiError(str(e), 409)
    stats = getattr(self.server, "stats", None)
    if stats is not None:
        stats.count("restore_keys_total", len(b["keys"]))
    self._reply({"len": len(log)})


def register_backup_routes(router: Router) -> None:
    router.add("GET", "/internal/backup/inventory", h_backup_inventory)
    router.add("GET",
               "/internal/backup/fragment/{index}/{field}/{view}/{shard}",
               h_backup_fragment)
    router.add("GET", "/internal/backup/schema", h_backup_schema)
    router.add("GET", "/internal/backup/attrs", h_backup_attrs_list)
    router.add("GET", "/internal/backup/attrs/{index}", h_backup_attrs)
    router.add("POST", "/internal/backup/translate/{index}",
               h_restore_translate)
