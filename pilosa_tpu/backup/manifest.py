"""Backup manifest: the archive's self-describing table of contents.

Reference: ``ctl/backup.go`` writes a directory of per-fragment files
plus schema/translate data; this rebuild adds an explicit
``manifest.json`` so restore and incremental backup never have to
guess what a directory contains:

- ``formatVersion`` gates forward compatibility (restore refuses
  manifests it does not understand);
- ``placementVersion``/``replicas``/``nodes`` record the SOURCE
  topology (informational — restore re-routes by the TARGET placement,
  that is what makes the restore elastic);
- one entry per fragment with its archive-relative file, size, sha256
  (transport/at-rest integrity), the source fragment's ``generation``
  at capture time (the bracketing label) and its position ``checksum``
  (crc32 over the fragment's AAE block checksums — stable across
  restarts, unlike the in-memory generation counter, so incremental
  diffs survive a source-node reboot);
- translate key logs and attribute stores as sidecar JSON files, also
  digest-pinned.

Archive layout under the output directory::

    manifest.json
    fragments/<index>/<field>/<view>/<shard>      roaring blob
    translate/<index>/_columns.json               {"keys": [...]}
    translate/<index>/<field>.json
    attrs/<index>/_columns.json                   {"items": {...}}
    attrs/<index>/<field>.json

Incremental runs rewrite ``manifest.json`` but keep unchanged fragment
files in place (entries point at the existing file), so one directory
accumulates a consistent latest image.
"""

from __future__ import annotations

import hashlib
import json
import os

FORMAT_VERSION = 1


def frag_key(index: str, field: str, view: str, shard: int) -> str:
    return f"{index}/{field}/{view}/{shard}"


def frag_relpath(index: str, field: str, view: str, shard: int) -> str:
    # mirrors the data-dir layout: unambiguous even though index/field
    # names may themselves contain the separator characters
    return os.path.join("fragments", index, field, view, str(shard))


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


class ManifestError(ValueError):
    """Malformed, missing, or version-incompatible manifest."""


class Manifest:
    def __init__(self, data: dict | None = None):
        d = data or {}
        self.format_version = d.get("formatVersion", FORMAT_VERSION)
        self.created_at = d.get("createdAt", 0.0)
        self.placement_version = d.get("placementVersion", 0.0)
        self.replicas = d.get("replicas", 1)
        self.nodes = d.get("nodes", [])
        self.incremental_of = d.get("incrementalOf")
        self.schema = d.get("schema", [])
        # frag_key -> {index, field, view, shard, generation, checksum,
        #              sha256, bytes, file}
        self.fragments: dict[str, dict] = d.get("fragments", {})
        # "<index>" / "<index>/<field>" -> {file, sha256, entries}
        self.translate: dict[str, dict] = d.get("translate", {})
        self.attrs: dict[str, dict] = d.get("attrs", {})

    # -- (de)serialization ---------------------------------------------------

    def to_json(self) -> dict:
        return {"formatVersion": self.format_version,
                "createdAt": self.created_at,
                "placementVersion": self.placement_version,
                "replicas": self.replicas,
                "nodes": self.nodes,
                "incrementalOf": self.incremental_of,
                "schema": self.schema,
                "fragments": self.fragments,
                "translate": self.translate,
                "attrs": self.attrs}

    def save(self, out_dir: str) -> str:
        """Atomic write (tmp+rename): a crashed backup never leaves a
        half-written manifest shadowing a good prior one."""
        path = os.path.join(out_dir, "manifest.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, out_dir: str) -> "Manifest":
        path = os.path.join(out_dir, "manifest.json")
        try:
            with open(path) as f:
                data = json.load(f)
        except OSError as e:
            raise ManifestError(f"no manifest at {path}: {e}") from e
        except ValueError as e:
            raise ManifestError(f"malformed manifest {path}: {e}") from e
        if data.get("formatVersion") != FORMAT_VERSION:
            raise ManifestError(
                f"manifest format {data.get('formatVersion')!r} not "
                f"supported (this build reads {FORMAT_VERSION})")
        return cls(data)

    @classmethod
    def maybe_load(cls, out_dir: str) -> "Manifest | None":
        """Prior manifest if one exists (the incremental base), else
        None.  A malformed prior manifest raises — silently falling
        back to a full transfer would hide archive corruption."""
        if not os.path.exists(os.path.join(out_dir, "manifest.json")):
            return None
        return cls.load(out_dir)

    # -- incremental diff ----------------------------------------------------

    def diff(self, prior: "Manifest | None") -> dict:
        """Classify this manifest's fragments against a prior one:
        ``{"changed": [keys...], "unchanged": [...], "removed": [...]}``
        — ``changed`` includes fragments absent from the prior archive.
        The change detector is the position checksum (restart-stable);
        generation equality alone is NOT trusted (counters reset to 0
        on fragment reopen)."""
        if prior is None:
            return {"changed": sorted(self.fragments), "unchanged": [],
                    "removed": []}
        changed, unchanged = [], []
        for key, ent in self.fragments.items():
            old = prior.fragments.get(key)
            if old is not None and old.get("checksum") == ent.get("checksum"):
                unchanged.append(key)
            else:
                changed.append(key)
        removed = [k for k in prior.fragments if k not in self.fragments]
        return {"changed": sorted(changed), "unchanged": sorted(unchanged),
                "removed": sorted(removed)}

    # -- integrity -----------------------------------------------------------

    def verify_files(self, out_dir: str) -> None:
        """Recompute every archived file's sha256 against the manifest;
        raises :class:`DigestError` naming the first corrupt file."""
        for key, ent in sorted(self.fragments.items()):
            self._verify_one(out_dir, ent, f"fragment {key}")
        for name, ent in sorted(self.translate.items()):
            self._verify_one(out_dir, ent, f"translate log {name}")
        for name, ent in sorted(self.attrs.items()):
            self._verify_one(out_dir, ent, f"attr store {name}")

    @staticmethod
    def _verify_one(out_dir: str, ent: dict, what: str) -> None:
        path = os.path.join(out_dir, ent["file"])
        try:
            got = sha256_file(path)
        except OSError as e:
            raise DigestError(f"{what}: archive file {ent['file']!r} "
                              f"unreadable: {e}") from e
        if got != ent["sha256"]:
            raise DigestError(
                f"{what}: sha256 mismatch for {ent['file']!r} "
                f"(manifest {ent['sha256'][:12]}…, file {got[:12]}…) — "
                "archive is corrupt; refusing to restore")


class DigestError(ValueError):
    """An archived file does not match its manifest digest."""
