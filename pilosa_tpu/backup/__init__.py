"""Consistent online backup & elastic restore (reference:
``ctl/backup.go`` / ``ctl/restore.go``, SURVEY.md §6).

- :mod:`pilosa_tpu.backup.endpoints` — the ``/internal/backup/*`` HTTP
  surface (generation-bracketed fragment images with digests);
- :mod:`pilosa_tpu.backup.manifest` — the archive's ``manifest.json``
  format, incremental diffing, digest verification;
- :mod:`pilosa_tpu.backup.driver` — the client-side
  :class:`BackupDriver` (parallel pull, replica fallback, incremental)
  and :class:`RestoreDriver` (elastic re-routing by the target
  placement, forced AAE convergence).

CLI: ``python -m pilosa_tpu.cli backup --output DIR`` /
``... restore DIR`` (see the README runbook).
"""

from pilosa_tpu.backup.driver import (BackupDriver, BackupError,
                                      RestoreDriver)
from pilosa_tpu.backup.manifest import DigestError, Manifest, ManifestError

__all__ = ["BackupDriver", "RestoreDriver", "BackupError",
           "Manifest", "ManifestError", "DigestError"]
