"""Backup/restore drivers: consistent online cluster backup, elastic
restore.

Reference: ``ctl/backup.go`` / ``ctl/restore.go`` — a client-side
driver that walks the cluster and pulls every fragment over HTTP, then
pushes an archive into a (possibly differently-sized) fresh cluster.

**Backup** (:class:`BackupDriver`): read the target's cluster state
(single un-clustered nodes degrade to a one-node walk), union the
per-node fragment inventories, and pull every ``(index, field, view,
shard)`` from a live owner — placement-preferred order, any other
reporting holder as replica fallback — with ``workers`` parallel
streams.  Each image is generation-bracketed server-side and digest-
verified while streaming to disk (bounded memory: the client download
helper never buffers a whole body).  ``incremental=True`` diffs the
current inventory checksums against the prior ``manifest.json`` and
re-transfers only fragments whose positions actually changed; the
rewritten manifest keeps pointing at the untouched files, so the
directory always holds one consistent latest image.

**Restore** (:class:`RestoreDriver`): digests verified first (a corrupt
archive fails loudly before touching the target), then schema →
translate key logs → attribute stores → fragments.  Fragments are
re-routed by the TARGET's active placement (node count may differ from
the source — that is the elastic part) and union-merged into every
owner through the same roaring import path writes use; finally one
anti-entropy round is forced so any replica the push could not reach
converges immediately instead of waiting for the periodic sweep.
Restore is idempotent: re-pushing an already-restored archive is a
union of identical bits (changed=0), so a failed run is safely
re-runnable.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from concurrent.futures import ThreadPoolExecutor, as_completed

from pilosa_tpu.api.client import Client, ClientError
from pilosa_tpu.backup.manifest import (FORMAT_VERSION, Manifest,
                                        frag_key, frag_relpath,
                                        sha256_file)
from pilosa_tpu.obs import get_logger
from pilosa_tpu.parallel.placement import shard_nodes

TRANSLATE_PAGE = 100_000


class BackupError(RuntimeError):
    """A backup/restore run could not complete."""


def _run_all(fn, items, workers: int):
    """Run ``fn(item)`` over ``items`` with ``workers`` threads,
    yielding results on the CALLER thread (so callers aggregate
    without locks).  Fails fast: the first exception cancels every
    not-yet-started item instead of letting a doomed run transfer
    everything else first."""
    if workers == 1 or len(items) <= 1:
        for item in items:
            yield fn(item)
        return
    with ThreadPoolExecutor(max_workers=workers) as pool:
        futs = [pool.submit(fn, item) for item in items]
        try:
            for fut in as_completed(futs):
                yield fut.result()
        except BaseException:
            for f in futs:
                f.cancel()
            raise


class _HashingSink:
    """File sink that sha256-hashes every chunk as it lands (digest
    verification without a second pass or a full in-memory body)."""

    def __init__(self, f):
        self._f = f
        self._h = hashlib.sha256()
        self.size = 0

    def write(self, chunk: bytes) -> int:
        self._h.update(chunk)
        self.size += len(chunk)
        return self._f.write(chunk)

    def hexdigest(self) -> str:
        return self._h.hexdigest()


class _ClusterView:
    """Target topology as seen from one entry node: node ids, active
    placement, replica count.  Un-clustered nodes (503 from the
    cluster surface) degrade to a single-node view."""

    def __init__(self, entry_id: str, entry_client: Client,
                 ssl_context=None, timeout: float = 120.0):
        self._ssl = ssl_context
        self._timeout = timeout
        self._clients: dict[str, Client] = {entry_id: entry_client}
        try:
            st = entry_client._json("GET", "/internal/cluster/state")
        except ClientError as e:
            if e.status != 503:
                raise
            self.clustered = False
            self.node_ids = [entry_id]
            self.placement = [entry_id]
            self.placement_version = 0.0
            self.replicas = 1
            return
        self.clustered = True
        self.node_ids = sorted(n["id"] for n in st["nodes"])
        self.placement = sorted(st.get("placement") or self.node_ids)
        self.placement_version = float(st.get("placementVersion", 0.0))
        self.replicas = int(st.get("replicas", 1))

    def client(self, node_id: str) -> Client:
        c = self._clients.get(node_id)
        if c is None:
            host, port = node_id.rsplit(":", 1)
            c = self._clients[node_id] = Client(
                host, int(port), timeout=self._timeout,
                ssl_context=self._ssl)
        return c

    def owners(self, index: str, shard: int) -> list[str]:
        return shard_nodes(index, shard, self.placement, self.replicas)

    def close(self) -> None:
        for c in self._clients.values():
            c.close()


class BackupDriver:
    def __init__(self, host: str, port: int, out_dir: str, *,
                 workers: int = 4, incremental: bool = False,
                 ssl_context=None, logger=None, on_fragment=None):
        self.out_dir = out_dir
        self.workers = max(1, workers)
        self.incremental = incremental
        self.logger = logger or get_logger("pilosa_tpu.backup")
        self.entry_id = f"{host}:{port}"
        self.entry = Client(host, port, timeout=120.0,
                            ssl_context=ssl_context)
        self._ssl = ssl_context
        # test seam: called after every fragment transfer/skip
        self.on_fragment = on_fragment

    def run(self) -> dict:
        t0 = time.perf_counter()
        os.makedirs(self.out_dir, exist_ok=True)
        prior = Manifest.maybe_load(self.out_dir) if self.incremental \
            else None
        view = _ClusterView(self.entry_id, self.entry, self._ssl)
        try:
            schema = self.entry._json(
                "GET", "/internal/backup/schema")["schema"]
            inv, holders = self._inventory(view)
            man = Manifest()
            man.created_at = time.time()
            man.placement_version = view.placement_version
            man.replicas = view.replicas
            man.nodes = list(view.node_ids)
            man.incremental_of = prior.created_at if prior else None
            man.schema = schema

            transferred, skipped = [], []

            def pull(key: str) -> tuple[str, dict, int, int]:
                """Returns (key, entry, bytes transferred, fallbacks)
                — aggregation happens on the caller thread so no
                counter update races under concurrent workers."""
                fr = inv[key]
                old = prior.fragments.get(key) if prior else None
                if (old is not None
                        and self._unchanged(key, fr, old, holders)
                        and os.path.exists(
                            os.path.join(self.out_dir, old["file"]))):
                    out = (key, old, -1, 0)  # -1 = skipped, not pulled
                else:
                    ent, fell = self._pull_fragment(view, fr, holders[key])
                    out = (key, ent, ent["bytes"], fell)
                if self.on_fragment is not None:
                    self.on_fragment(key)
                return out

            fallbacks = 0
            total_bytes = 0
            for key, ent, nbytes, fell in _run_all(
                    pull, sorted(inv), self.workers):
                man.fragments[key] = ent
                if nbytes < 0:
                    skipped.append(key)
                else:
                    transferred.append(key)
                    total_bytes += nbytes
                    fallbacks += fell

            total_bytes += self._backup_translate(man)
            total_bytes += self._backup_attrs(man)
            path = man.save(self.out_dir)
        finally:
            view.close()
        dt = time.perf_counter() - t0
        result = {"manifest": path, "fragments": len(man.fragments),
                  "transferred": sorted(transferred),
                  "skipped": sorted(skipped),
                  "fallbacks": fallbacks, "bytes": total_bytes,
                  "seconds": round(dt, 3),
                  "incremental": prior is not None}
        self.logger.info(
            "backup complete: %d fragments (%d transferred, %d skipped, "
            "%d replica fallbacks), %d bytes in %.2fs -> %s",
            result["fragments"], len(transferred), len(skipped),
            result["fallbacks"], result["bytes"], dt, self.out_dir)
        return result

    # -- walk ----------------------------------------------------------------

    def _inventory(self, view: _ClusterView):
        """Union of per-node fragment inventories.  An unreachable node
        only degrades the walk if NO other node reports (a replica of)
        its fragments — exactly the failure replica fallback covers."""
        inv: dict[str, dict] = {}
        holders: dict[str, list[str]] = {}
        reachable = 0
        for nid in view.node_ids:
            try:
                frags = view.client(nid)._json(
                    "GET", "/internal/backup/inventory?checksums=1"
                )["fragments"]
            except (ClientError, OSError) as e:
                self.logger.warning(
                    "inventory from %s failed (%s); relying on replicas",
                    nid, e)
                continue
            reachable += 1
            for fr in frags:
                key = frag_key(fr["index"], fr["field"], fr["view"],
                               fr["shard"])
                ent = inv.setdefault(key, dict(fr))
                holders.setdefault(key, []).append(nid)
                # every reporting holder's checksum, for the skip
                # decision (replicas mid-repair disagree)
                ent.setdefault("_checksums", set()).add(
                    fr.get("checksum"))
        if reachable == 0:
            raise BackupError("no node's fragment inventory is readable")
        return inv, holders

    @staticmethod
    def _unchanged(key: str, fr: dict, old: dict,
                   holders: dict[str, list[str]]) -> bool:
        """Incremental skip decision: only when EVERY reporting
        holder's checksum matches the prior archived one — replicas
        mid-repair (disagreeing checksums) re-transfer rather than
        risk keeping a stale image."""
        prior = old.get("checksum")
        sums = fr.get("_checksums") or {fr.get("checksum")}
        return prior is not None and sums == {prior}

    def _candidates(self, view: _ClusterView, fr: dict,
                    holder_ids: list[str]) -> list[str]:
        """Source order: placement owners that actually hold the
        fragment (primary first), then any other reporting holder
        (orphans mid-resize still back up)."""
        owners = view.owners(fr["index"], fr["shard"])
        ordered = [n for n in owners if n in holder_ids]
        ordered += [n for n in holder_ids if n not in ordered]
        return ordered

    def _pull_fragment(self, view: _ClusterView, fr: dict,
                       holder_ids: list[str]) -> tuple[dict, int]:
        rel = frag_relpath(fr["index"], fr["field"], fr["view"],
                           fr["shard"])
        dest = os.path.join(self.out_dir, rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        path = (f"/internal/backup/fragment/{fr['index']}/{fr['field']}"
                f"/{fr['view']}/{fr['shard']}")
        last: Exception | None = None
        for i, nid in enumerate(self._candidates(view, fr, holder_ids)):
            tmp = dest + ".tmp"
            try:
                with open(tmp, "wb") as f:
                    sink = _HashingSink(f)
                    headers = view.client(nid).download(path, sink)
                want = headers.get("X-Content-SHA256")
                if want and want != sink.hexdigest():
                    raise BackupError(
                        f"transfer digest mismatch from {nid} for {rel}")
                os.replace(tmp, dest)
                ent = {"index": fr["index"], "field": fr["field"],
                       "view": fr["view"], "shard": fr["shard"],
                       "generation": int(
                           headers.get("X-Pilosa-Generation", -1)),
                       "checksum": headers.get("X-Pilosa-Checksum"),
                       "sha256": sink.hexdigest(), "bytes": sink.size,
                       "file": rel}
                return ent, (1 if i > 0 else 0)
            except (ClientError, OSError, BackupError) as e:
                last = e
                self.logger.warning(
                    "fragment pull %s from %s failed (%s); trying a "
                    "replica", rel, nid, e)
                try:
                    os.remove(tmp)
                except OSError:
                    pass
        raise BackupError(
            f"no live replica could serve fragment {rel}: {last}")

    # -- sidecars ------------------------------------------------------------

    def _backup_translate(self, man: Manifest) -> int:
        try:
            logs = self.entry._json(
                "GET", "/internal/translate/logs")["logs"]
        except ClientError as e:
            raise BackupError(f"translate log listing failed: {e}") from e
        total = 0
        for ent in logs:
            index, field = ent["index"], ent["field"]
            keys: list[str] = []
            while True:
                resp = self.entry._json(
                    "GET", f"/internal/translate/tail?index={index}"
                    f"&field={field or ''}&after={len(keys)}"
                    f"&limit={TRANSLATE_PAGE}")
                if not resp["keys"]:
                    break
                keys.extend(resp["keys"])
                if len(keys) >= resp.get("len", 0):
                    break
            rel = os.path.join("translate", index,
                               f"{field}.json" if field
                               else "_columns.json")
            total += self._write_sidecar(
                rel, {"index": index, "field": field, "keys": keys})
            name = f"{index}/{field}" if field else index
            man.translate[name] = {
                "file": rel,
                "sha256": sha256_file(os.path.join(self.out_dir, rel)),
                "entries": len(keys)}
        return total

    def _backup_attrs(self, man: Manifest) -> int:
        stores = self.entry._json(
            "GET", "/internal/backup/attrs")["stores"]
        total = 0
        for st in stores:
            index, field = st["index"], st["field"]
            qs = f"?field={field}" if field else ""
            items = self.entry._json(
                "GET", f"/internal/backup/attrs/{index}{qs}")["items"]
            rel = os.path.join("attrs", index,
                               f"{field}.json" if field
                               else "_columns.json")
            total += self._write_sidecar(
                rel, {"index": index, "field": field, "items": items})
            name = f"{index}/{field}" if field else index
            man.attrs[name] = {
                "file": rel,
                "sha256": sha256_file(os.path.join(self.out_dir, rel)),
                "entries": len(items)}
        return total

    def _write_sidecar(self, rel: str, obj: dict) -> int:
        dest = os.path.join(self.out_dir, rel)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        body = json.dumps(obj).encode()
        tmp = dest + ".tmp"
        with open(tmp, "wb") as f:
            f.write(body)
        os.replace(tmp, dest)
        return len(body)


class RestoreDriver:
    def __init__(self, host: str, port: int, from_dir: str, *,
                 workers: int = 4, ssl_context=None, logger=None):
        self.from_dir = from_dir
        self.workers = max(1, workers)
        self.logger = logger or get_logger("pilosa_tpu.backup")
        self.entry_id = f"{host}:{port}"
        self.entry = Client(host, port, timeout=120.0,
                            ssl_context=ssl_context)
        self._ssl = ssl_context

    def run(self) -> dict:
        t0 = time.perf_counter()
        man = Manifest.load(self.from_dir)
        if man.format_version != FORMAT_VERSION:
            raise BackupError(
                f"manifest format {man.format_version} unsupported")
        # fail BEFORE touching the target: a corrupt archive must not
        # leave a half-restored cluster behind
        man.verify_files(self.from_dir)
        view = _ClusterView(self.entry_id, self.entry, self._ssl)
        try:
            self._check_fresh(man)
            nodes = self._reachable_nodes(view)
            for nid in nodes:
                view.client(nid)._json("POST", "/internal/schema",
                                       {"schema": man.schema})
            self._restore_translate(view, man, nodes)
            self._restore_attrs(view, man, nodes)
            total_bytes, pushes = self._restore_fragments(view, man)
            repaired = self._force_aae(view, nodes)
        finally:
            view.close()
        dt = time.perf_counter() - t0
        result = {"fragments": len(man.fragments), "pushes": pushes,
                  "bytes": total_bytes, "nodes": len(nodes),
                  "aaeRepaired": repaired, "seconds": round(dt, 3)}
        self.logger.info(
            "restore complete: %d fragments (%d pushes) onto %d nodes, "
            "%d bytes in %.2fs (aae repaired %d blocks)",
            result["fragments"], pushes, len(nodes), total_bytes, dt,
            repaired)
        return result

    def _check_fresh(self, man: Manifest) -> None:
        """Elastic restore targets a FRESH cluster (upstream restore's
        rule): refuse when any archived index already exists."""
        existing = {i["name"] for i in self.entry.schema()}
        overlap = sorted(existing
                         & {i["name"] for i in man.schema})
        if overlap:
            raise BackupError(
                f"restore target already has index(es) {overlap}; "
                "restore requires a fresh cluster")

    def _reachable_nodes(self, view: _ClusterView) -> list[str]:
        nodes = []
        for nid in view.node_ids:
            try:
                view.client(nid)._json("GET", "/status")
                nodes.append(nid)
            except (ClientError, OSError) as e:
                self.logger.warning("restore: node %s unreachable (%s)",
                                    nid, e)
        if not nodes:
            raise BackupError("no restore target node is reachable")
        return nodes

    def _restore_translate(self, view: _ClusterView, man: Manifest,
                           nodes: list[str]) -> None:
        """Key logs restored FIRST (before fragment bits) so keyed
        lookups resolve the moment data lands — and to every node,
        matching the fully-replicated translate-log design."""
        for name, ent in sorted(man.translate.items()):
            with open(os.path.join(self.from_dir, ent["file"])) as f:
                data = json.load(f)
            index, field, keys = data["index"], data["field"], data["keys"]
            for nid in nodes:
                for off in range(0, len(keys), TRANSLATE_PAGE):
                    page = keys[off:off + TRANSLATE_PAGE]
                    view.client(nid)._json(
                        "POST", f"/internal/backup/translate/{index}",
                        {"field": field, "start_id": off + 1,
                         "keys": page})
            self.logger.info("restored translate log %s (%d keys)",
                             name, len(keys))

    def _restore_attrs(self, view: _ClusterView, man: Manifest,
                       nodes: list[str]) -> None:
        for name, ent in sorted(man.attrs.items()):
            with open(os.path.join(self.from_dir, ent["file"])) as f:
                data = json.load(f)
            qs = (f"index={data['index']}"
                  f"&field={data['field'] or ''}")
            for nid in nodes:
                view.client(nid)._json(
                    "POST", f"/internal/attrs/merge?{qs}",
                    {"items": data["items"]})

    def _restore_fragments(self, view: _ClusterView,
                           man: Manifest) -> tuple[int, int]:
        def push(key: str) -> tuple[int, int]:
            """Returns (bytes pushed, pushes) for caller-side
            aggregation.  Bodies are STREAMED from the archive file
            (explicit Content-Length; http.client sends file objects
            in small blocks) — a multi-GB fragment never materializes
            in restore-host memory, matching the backup side's
            bounded-memory download."""
            ent = man.fragments[key]
            path = os.path.join(self.from_dir, ent["file"])
            size = os.path.getsize(path)
            qs = (f"index={ent['index']}&field={ent['field']}"
                  f"&view={ent['view']}&shard={ent['shard']}")
            owners = view.owners(ent["index"], ent["shard"])
            landed = 0
            last: Exception | None = None
            for owner in owners:
                try:
                    with open(path, "rb") as f:
                        view.client(owner)._do(
                            "POST", f"/internal/fragment/merge?{qs}", f,
                            content_type="application/octet-stream",
                            headers={"X-Pilosa-Restore": "1",
                                     "Content-Length": str(size)})
                    landed += 1
                except (ClientError, OSError) as e:
                    last = e
                    self.logger.warning(
                        "restore push %s to %s failed: %s", key, owner, e)
            if landed == 0:
                raise BackupError(
                    f"no owner accepted fragment {key}: {last}")
            # a partially-landed fragment converges via the forced AAE
            # round below (union-merge between the owners that took it)
            return size * landed, landed

        total = pushes = 0
        for nbytes, landed in _run_all(push, sorted(man.fragments),
                                       self.workers):
            total += nbytes
            pushes += landed
        return total, pushes

    def _force_aae(self, view: _ClusterView, nodes: list[str]) -> int:
        """One forced anti-entropy round so replicas a push missed
        converge NOW.  Un-clustered targets (503) have no replicas to
        converge — skipped."""
        repaired = 0
        for nid in nodes:
            try:
                repaired += view.client(nid)._json(
                    "POST", "/internal/aae/run", {})["repaired"]
            except (ClientError, OSError) as e:
                if getattr(e, "status", 0) != 503:
                    self.logger.warning("forced AAE on %s failed: %s",
                                        nid, e)
        return repaired
