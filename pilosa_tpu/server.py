"""Composition root: wire holder + executor + API + HTTP + observability.

Reference: ``server.go`` (SURVEY.md §3.3) — functional options
assembling holder/cluster/executor/handlers/stats/tracing, lifecycle
``Open``/``Close``, and background loops.  Here the wiring input is the
:class:`pilosa_tpu.cli.config.Config` dataclass.
"""

from __future__ import annotations

from pilosa_tpu.api import API, Server as HttpServer
from pilosa_tpu.cli.config import Config
from pilosa_tpu.exec import Executor
from pilosa_tpu.obs import Stats, get_logger
from pilosa_tpu.store import Holder


class PilosaTPUServer:
    def __init__(self, cfg: Config):
        self.cfg = cfg
        self.logger = get_logger(verbose=cfg.verbose,
                                 fmt=cfg.log_format or None)
        if cfg.stats_backend == "statsd":
            # statsd emission rides ON TOP of the in-process registry
            # (subclass): /metrics keeps serving Prometheus text while
            # every count/gauge/timing also emits a UDP statsd packet
            from pilosa_tpu.obs import StatsdStats
            host, _, port = cfg.statsd_address.rpartition(":")
            self.stats = StatsdStats(host or "127.0.0.1",
                                     int(port or 8125))
            self.logger.info("stats: statsd emission to %s",
                             cfg.statsd_address)
        elif cfg.stats_backend not in ("", "prometheus"):
            raise ValueError(
                f"unknown stats_backend {cfg.stats_backend!r} "
                "(expected '', 'prometheus' or 'statsd')")
        else:
            self.stats = Stats()
        self.holder = Holder(cfg.data_dir, fsync=cfg.fsync)
        self.executor: Executor | None = None
        self.api: API | None = None
        self.http: HttpServer | None = None
        self.grpc = None
        self.cluster = None
        self.diagnostics = None
        self.scrubber = None

    def open(self) -> "PilosaTPUServer":
        if self.cfg.faults:
            # arm configured failpoints BEFORE any subsystem opens, so
            # boot-time seams (oplog replay, mmap registration) are
            # already injectable; a bad spec fails the boot loudly
            from pilosa_tpu import fault
            fault.configure(self.cfg.faults, logger=self.logger)
        if self.cfg.jax_coordinator:
            # multi-host pod slice: one process per host joins the jax
            # runtime before any device use; jax.devices() then spans
            # every chip and the mesh placement shards across the full
            # slice with collectives over ICI/DCN (SURVEY.md §3.6)
            import jax
            jax.distributed.initialize(
                coordinator_address=self.cfg.jax_coordinator,
                num_processes=self.cfg.jax_num_processes or None,
                process_id=(self.cfg.jax_process_id
                            if self.cfg.jax_process_id >= 0 else None))
            self.logger.info("jax.distributed: process %d of %d",
                             jax.process_index(), jax.process_count())
        if self.cfg.compilation_cache_dir:
            # persistent XLA compilation cache: a warm restart reloads
            # compiled programs from disk instead of paying the ~1 s
            # first-query compile (BENCH_r05).  Thresholds drop to
            # zero so the handful of serving programs always persist.
            import os as _os

            import jax
            cache_dir = _os.path.expanduser(self.cfg.compilation_cache_dir)
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs",
                              0.0)
            jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                              -1)
            # the cache singleton latches its directory on first use:
            # drop any instance initialized before this config landed
            # (library embedders may have compiled already).  Private
            # API — a jax that moved it degrades to a cold compile,
            # never a failed boot.
            try:
                from jax._src import compilation_cache as _cc
                _cc.reset_cache()
            except (ImportError, AttributeError):
                pass
            self.logger.info("compilation cache: %s", cache_dir)
        from pilosa_tpu.store import syswrap
        syswrap.GLOBAL.set_max(self.cfg.max_map_count)
        # disk-health governor (r19): wire stats + knobs BEFORE the
        # holder opens, so boot-time snapshot verification already
        # quarantines (and counts) through the configured registry
        self.holder.storage_health.configure(
            base=self.cfg.data_dir, stats=self.stats, logger=self.logger,
            min_free_bytes=self.cfg.disk_min_free_bytes,
            probe_seconds=self.cfg.disk_probe_seconds)
        self.holder.open()
        placement = None
        if self.cfg.mesh:
            from pilosa_tpu.parallel import local_placement
            placement = local_placement()
            if placement is not None:
                self.logger.info("mesh: sharding over %d devices",
                                 placement.n_devices)
        self.executor = Executor(
            self.holder, placement=placement, stats=self.stats,
            plane_budget=self.cfg.plane_budget_bytes,
            count_batch_window=self.cfg.count_batch_window,
            max_concurrent=self.cfg.max_concurrent_queries,
            plane_sidecars=self.cfg.plane_sidecars,
            delta_cells=self.cfg.delta_buffer_cells,
            delta_compact_fraction=self.cfg.delta_compact_fraction,
            tree_fusion=self.cfg.tree_fusion,
            dispatch_pipeline_depth=self.cfg.dispatch_pipeline_depth,
            solo_fastlane=self.cfg.solo_fastlane,
            dispatch_watchdog_seconds=self.cfg.dispatch_watchdog_seconds,
            device_health_probe_seconds=(
                self.cfg.device_health_probe_seconds),
            plane_paging=self.cfg.plane_paging,
            plane_page_bytes=self.cfg.plane_page_bytes,
            tenant_byte_quota=self.cfg.tenant_byte_quota,
            tenant_qps_quota=self.cfg.tenant_qps_quota,
            tenant_slot_quota=self.cfg.tenant_slot_quota,
            kernel_tier=self.cfg.kernel_tier,
            dispatch_loop_fusion=self.cfg.dispatch_loop_fusion,
            fused_warmup=self.cfg.fused_warmup)
        self.api = API(self.holder, self.executor,
                       query_timeout=self.cfg.query_timeout,
                       trace_sample_rate=self.cfg.trace_sample_rate,
                       slow_query_threshold=self.cfg.slow_query_threshold)
        from pilosa_tpu.api import tls as tlsmod
        from pilosa_tpu.cli.config import tls_of
        tls_cfg = tls_of(self.cfg)
        ssl_ctx = tlsmod.server_context(tls_cfg)
        if ssl_ctx is not None:
            self.logger.info(
                "tls: serving HTTPS%s; internode calls use TLS",
                " with required client certs"
                if tls_cfg.enable_client_auth else "")
        # construct (binds the socket; resolves port 0) before the
        # cluster needs the advertised address, then serve
        self.http = HttpServer(self.api, self.cfg.host, self.cfg.port,
                               stats=self.stats, logger=self.logger,
                               ssl_context=ssl_ctx)
        if self.cfg.seeds or self.cfg.replicas > 1 or self.cfg.cluster_enabled:
            from pilosa_tpu.cluster import Cluster
            self.cluster = Cluster(self.cfg, self.api, stats=self.stats,
                                   logger=self.logger,
                                   port=self.http.address[1])
            self.api.cluster = self.cluster
        self.http.start()
        if self.cfg.grpc_bind:
            from pilosa_tpu.api.grpc import GrpcServer
            ghost, _, gport = self.cfg.grpc_bind.rpartition(":")
            self.grpc = GrpcServer(
                self.api, ghost or "127.0.0.1", int(gport),
                credentials=tlsmod.grpc_server_credentials(tls_cfg),
            ).start()
            self.logger.info("grpc: listening on %s:%d",
                             ghost or "127.0.0.1", self.grpc.port)
        if self.cluster is not None:
            self.cluster.open()
        # background scrubber (r19): re-verifies every on-disk
        # checksum at the configured byte budget; corrupt fragments
        # quarantine and — in cluster mode — repair from a healthy
        # replica through the AAE data path.  scrub_bytes_per_second=0
        # restores the pre-r19 contract (no thread at all).
        from pilosa_tpu.store.scrub import Scrubber
        self.scrubber = Scrubber(
            self.holder, interval=self.cfg.scrub_interval_seconds,
            bytes_per_second=self.cfg.scrub_bytes_per_second,
            stats=self.stats, logger=self.logger,
            on_corrupt=(self.cluster.repair_quarantined
                        if self.cluster is not None else None)).start()
        self.api.scrubber = self.scrubber
        from pilosa_tpu.obs.diagnostics import Diagnostics
        self.diagnostics = Diagnostics(
            self.holder, self.cluster,
            interval=self.cfg.diagnostics_interval,
            logger=self.logger, stats=self.stats,
            slow_log=self.api.slow_log,
            executor=self.executor).start()
        return self

    def close(self) -> None:
        if self.diagnostics is not None:
            self.diagnostics.close()
        if self.scrubber is not None:
            self.scrubber.close()
        if self.cluster is not None:
            self.cluster.close()
        if self.grpc is not None:
            self.grpc.close()
        if self.http is not None:
            self.http.close()
        if self.executor is not None:
            self.executor.translate.close()
        self.holder.close()

    @property
    def port(self) -> int:
        return self.http.address[1]
