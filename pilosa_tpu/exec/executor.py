"""Executor: PQL AST → jitted TPU kernels over the holder.

Reference: ``executor.go`` (SURVEY.md §3.2, §4.2–§4.5) — per-call
dispatch (``executeCall`` → ``executeIntersect/executeTopN/…``) with a
per-shard map-reduce over cluster nodes.  The TPU rebuild replaces the
fan-out/merge entirely: every resident shard is one slice of a batched
device array (``uint32[n_shards, W]``), one XLA program evaluates the
call tree for all shards at once, and cross-shard reduction is a dense
``sum``/``top_k`` — compiled to ICI collectives when the shard axis is
sharded over a mesh (see ``pilosa_tpu.parallel``), not an HTTP merge.

Key translation happens on ingress (args) and egress (results), as in
the reference (``executor.Execute`` translate steps).
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu import fault
from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels
from pilosa_tpu.engine.words import SHARD_WIDTH, WORDS_PER_SHARD, unpack_columns
from pilosa_tpu.exec.planes import PAD_SHARD, PlaneCache
from pilosa_tpu.exec.result import (ExtractResult, GroupCountsResult,
                                    Pair, PairsResult, RowIdsResult,
                                    RowResult, ValCount)
from pilosa_tpu.obs.ledger import (clear_query_context,
                                   set_query_context)
from pilosa_tpu.obs.tracing import current_trace_id
from pilosa_tpu.pql import parse_cached
from pilosa_tpu.pql.ast import (BETWEEN_OPS, Call, Condition, Query,
                                between_cmp_ops)
from pilosa_tpu.store.field import BSI_TYPES, Field
from pilosa_tpu.store.holder import Holder
from pilosa_tpu.store.index import Index
from pilosa_tpu.store.timeq import (parse_pql_time, view_span,
                                    views_by_time_range)
from pilosa_tpu.store.translate import TranslateStore
from pilosa_tpu.store.view import VIEW_STANDARD

# option keys that are never field names in call args.  Reservation is
# PER CALL: a field named "n" must still work in Set(5, n=777) even
# though TopN reserves n= (the upstream grammar scopes options the same
# way).  RESERVED_KEYS is the superset default for option-heavy calls.
RESERVED_KEYS = frozenset({
    "from", "to", "limit", "offset", "n", "field", "ids", "filter", "column",
    "like", "previous", "aggregate", "sort", "shards", "index",
    "attrName", "attrValue", "columnAttrs", "excludeColumns", "tanimoto",
    "excludeRowAttrs",
})

# uint32[S, W] -> int32[S] set bits per shard (the Limit/Extract
# push-down's shard cutoff; one S-int read instead of the bitmap)
_shard_popcounts = jax.jit(kernels.count)


_CALL_RESERVED = {
    "Row": frozenset({"from", "to", "excludeRowAttrs"}),
    "Range": frozenset({"from", "to"}),
    "Set": frozenset(),
    "Clear": frozenset(),
    "ClearRow": frozenset(),
    "Store": frozenset(),
}


def reserved_for(call_name: str) -> frozenset:
    return _CALL_RESERVED.get(call_name, RESERVED_KEYS)


def _field_arg(call: Call):
    """Per-call-scoped field_arg with query-error (not 500) semantics."""
    try:
        return call.field_arg(reserved_for(call.name))
    except ValueError as e:
        raise ExecutionError(str(e))

_BITMAP_CALLS = frozenset({
    "Row", "Intersect", "Union", "Difference", "Xor", "Not", "All", "Range",
    "Shift", "UnionRows", "ConstRow", "Limit",
})

_SCALAR_TO_KEY = {"<": "lt", "<=": "le", ">": "gt", ">=": "ge",
                  "==": "eq", "!=": "ne"}

# eager word-wise kernels by the canonical op token
# (pql.ast.BOOL_CALLS names → tokens; exec.tree.fold_bool_call folds)
_EAGER_OPS = {"or": kernels.union, "and": kernels.intersect,
              "andnot": kernels.difference, "xor": kernels.xor}


def _bsi_signature(options) -> tuple:
    """Everything a baked BSI predicate depends on.  A cached plan
    resolved its offsets (``to_stored(value) - base``) and saturation
    verdicts against these options, so validity must drop the plan
    when ANY of them changes — comparing ``bit_depth`` alone misses a
    drop + recreate with the same depth but a different
    base/scale/epoch, which would serve skewed predicates forever on
    entries that skip the generation compare."""
    return (options.type, options.bit_depth, options.base,
            options.scale, options.epoch, options.time_unit)


def _is_device_oom(e: Exception) -> bool:
    """XLA device-memory exhaustion, by status string.  jax wraps the
    status as XlaRuntimeError/JaxRuntimeError on direct dispatch, but
    an async execution that fails on device surfaces at the host READ
    as a plain ValueError carrying the same RESOURCE_EXHAUSTED text
    (axon backend under 32-way concurrency, config14 r5).  The type
    gate stays: an ExecutionError merely QUOTING user input (e.g. PQL
    ``RESOURCE_EXHAUSTED()``) must not trigger a cache-dropping
    recovery."""
    return ("RESOURCE_EXHAUSTED" in str(e)
            and type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError",
                                     "ValueError"))


def _lex_gt(mat: np.ndarray, prev: tuple) -> np.ndarray:
    """Rows of ``mat`` strictly greater than ``prev`` in lexicographic
    order (GroupBy ``previous=`` paging, vectorized)."""
    gt = np.zeros(len(mat), bool)
    eq = np.ones(len(mat), bool)
    for lvl, p in enumerate(prev):
        col = mat[:, lvl]
        gt |= eq & (col > p)
        eq &= col == p
    return gt


class ExecutionError(Exception):
    pass


class ExecutorSaturatedError(ExecutionError):
    """Admission timed out: every execution slot stayed busy for the
    whole wait budget.  The API edge maps this to HTTP 503 with a
    ``Retry-After`` hint (load shedding, VERDICT advice #6) — overload
    is not a client error and must not surface as 500/400."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = retry_after


class WriteUnavailableError(ExecutionError):
    """A write cannot serve right now: a replica is down and durable
    hinted handoff cannot cover it — handoff disabled
    (``hint_max_age <= 0``), the peer's hint backlog overflowed past
    ``hint_max_age``, or no live replica remains to apply the op at
    all.  The API edge maps this to HTTP 503 + ``Retry-After`` with a
    structured ``writeUnavailable`` body naming the down replica
    (r13; mirrors the 504 timeout treatment) — unavailability is not a
    client error and must not surface as a generic 400/500.

    ``reason`` is one of ``"replica_down"`` (handoff disabled — the
    pre-r13 strict contract), ``"hint_overflow"`` (the boundedness
    rule fired), ``"no_live_replica"`` (every owner of some shard is
    unreachable), or ``"replica_busy"`` (an alive replica shed the op
    pre-execution — saturation is transient, so it is never hinted)."""

    def __init__(self, msg: str, op: str, replica: str | None,
                 reason: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.op = op
        self.replica = replica
        self.reason = reason
        self.retry_after = retry_after


# negative plan-cache entry: this query shape is structurally outside
# the plan cache (not all-Count, time ranges, …) — skip re-walking it
_UNPLANNABLE = object()


@dataclass
class _PlanEntry:
    """One cached serving plan for an all-Count query (r6 tentpole).

    ``kind``:

    - ``"plane"`` — same-field plain-row Count batch: answered by ONE
      whole-plane ``row_counts`` program over the resident plane
      (``row_ids`` are the per-call resolved rows; slots come fresh
      from the PlaneSet each hit).
    - ``"generic"`` — arbitrary fusable Count trees: ``nodes`` (leaf
      indices local to ``leaf_specs``) re-materialize through the
      plane cache each hit.
    - ``"tree"`` — compound boolean trees compiled whole (r16):
      ``tree_specs`` are canonical :class:`exec.tree.TreeSpec`\\ s;
      rows re-resolve to plane slots and extras re-materialize per
      hit, and the anchor plane's delta overlay keeps answers fresh
      under sustained ingest.

    Validity: ``shards`` must equal the current shard set and ``gens``
    must equal the dependency views' generations — a write to any
    source fragment (including creating a row key that planned as a
    zeros leaf) invalidates on the next hit.  Leaf ARRAYS are never
    cached here; they come from the PlaneCache, which revalidates
    independently."""

    kind: str
    shards: tuple
    deps: tuple            # ((field_name | "\x00exists", view_name), ...)
    gens: tuple            # per-dep generation tuples (None = view absent)
    n_calls: int
    nodes: tuple = ()
    leaf_specs: tuple = ()
    field_name: str | None = None
    row_ids: tuple = ()
    # (field_name, _bsi_signature(options)) per BSI field whose
    # predicate masks / saturation verdicts the plan baked: depth can
    # GROW via a write OUTSIDE this entry's shard subset (generations
    # over entry.shards won't see it), and a drop + recreate with the
    # SAME depth but a different base/scale/epoch would silently skew
    # every baked offset on entries that skip the gens compare — so
    # validity re-checks the full predicate-relevant option signature
    bsi_sigs: tuple = ()
    # "plane"/"tree" plans over UNKEYED fields bake nothing a write
    # can stale: row ids are the literal PQL integers and the PlaneSet
    # revalidates its own generations (delta overlays absorb writes,
    # r15).  Such entries skip the per-hit generation compare — under
    # sustained ingest the generations move every batch, and dropping
    # the plan per write put parse+plan back on every request.
    # ``unkeyed_fields`` lists the set fields whose identity (exists,
    # unkeyed, non-BSI) the per-hit validity check re-verifies so a
    # drop + recreate under the same name still kills the entry.
    unkeyed_plane: bool = False
    unkeyed_fields: tuple = ()
    # "tree" entries: canonical specs, one per Count call (r16)
    tree_specs: tuple = ()
    # "bsirange" entries (r20): per call (field_name, op_keys,
    # offsets) — BSI range Counts served through the batcher's
    # bsirange family (plane fetched delta-aware per hit, so the
    # entry survives sustained ingest like the unkeyed-plane kinds;
    # ``bsi_sigs`` pins depth/base so the baked offsets stay valid)
    range_items: tuple = ()


class QueryTimeoutError(ExecutionError):
    """Query deadline exceeded (reference: upstream threads request
    context cancellation through the executor; deadlines are the
    equivalent for a compiled-dispatch engine — checked at block
    boundaries, between calls, before each streamed row block, and —
    r18 — while blocked on the dispatch pipeline, where ``stage``
    names what the query was waiting on when the clock ran out
    (queued/dispatch/readback); it rides the structured 504 body)."""

    def __init__(self, msg: str, stage: str | None = None):
        super().__init__(msg)
        self.stage = stage


class PipelineStalledError(ExecutionError):
    """A dispatch-pipeline window exceeded the watchdog bound and was
    quarantined (r18): the caller's work was failed loudly — naming
    the stalled stage — instead of wedging a serving thread forever
    behind a sick device.  Maps to a structured HTTP 500
    (``pipelineStall`` body) at the public and internal edges."""

    def __init__(self, msg: str, stage: str = "dispatch",
                 elapsed: float = 0.0):
        super().__init__(msg)
        self.stage = stage
        self.elapsed = elapsed


@dataclass
class _Ctx:
    index: Index
    shards: tuple[int, ...]
    translate_output: bool = True
    deadline: float | None = None  # time.monotonic() cutoff

    def check_deadline(self) -> None:
        if self.deadline is not None and time.monotonic() > self.deadline:
            raise QueryTimeoutError("query timeout exceeded")


class Executor:
    MAX_PLANS = 512  # plan-cache entries (user-controlled keys: bounded)
    # admission wait budget before shedding with 503 (class attr so
    # saturation tests shrink it without touching live config)
    SLOT_TIMEOUT_S = 180.0

    def __init__(self, holder: Holder, translate: TranslateStore | None = None,
                 place=None, plane_budget: int | None = None, placement=None,
                 stats=None, tracer=None,
                 count_batch_window: float | str = "adaptive",
                 max_concurrent: int = 8, plane_sidecars: bool = True,
                 delta_cells: int = 65536,
                 delta_compact_fraction: float = 0.5,
                 tree_fusion: bool = True,
                 dispatch_pipeline_depth: int = 2,
                 solo_fastlane: bool = True,
                 dispatch_watchdog_seconds: float = 30.0,
                 device_health_probe_seconds: float = 5.0,
                 plane_paging: bool = True,
                 plane_page_bytes: int = 64 << 20,
                 tenant_byte_quota: int = 0,
                 tenant_qps_quota: float = 0.0,
                 tenant_slot_quota: int = 0,
                 tenant_device_seconds_quota: float = 0.0,
                 cost_observability: bool = True,
                 kernel_tier: str = "xla",
                 dispatch_loop_fusion: bool = False,
                 fused_warmup: bool = False):
        """``placement`` (a :class:`pilosa_tpu.parallel.MeshPlacement`)
        shards every plane's leading axis over the device mesh and pads
        shard lists to the mesh size; without it, planes live on the
        default device.  ``max_concurrent`` bounds simultaneously
        EXECUTING queries (scratch admission; 0 disables) — excess
        clients queue at the executor, not in device memory.
        ``count_batch_window``: ``"adaptive"`` (default) coalesces
        concurrent dense reads with a window that grows under queue
        pressure and shrinks to 0 when solo; a float fixes the window
        (pre-r6 behavior); 0 disables coalescing.
        ``dispatch_pipeline_depth`` (r17): dispatched-but-unread
        collection windows the batcher may run ahead (window N's
        compute overlaps window N-1's readback); <=1 restores serial
        dispatch->read.  ``solo_fastlane`` (r17): width-1 requests
        with no queue pressure dispatch inline on the caller thread
        over donated ping-pong chains instead of forming a window.
        ``dispatch_watchdog_seconds`` (r18): per-stage age bound on
        in-flight batcher windows — a window stalled past it is
        quarantined (items failed with a structured error naming the
        stage, pipeline slot reclaimed, wedged worker superseded);
        0 disables the monitor entirely (pre-r18 contract).
        ``device_health_probe_seconds`` (r18): how long degraded
        serving (per-item fallback execution after consecutive
        dispatch faults / watchdog trips) lasts before one window
        probes the fused pipeline again.

        Tenancy (r17 — tenant = index name): ``plane_paging`` turns
        over-budget plain-Row Count planes into PAGED residency
        (``tenancy.PlanePager`` — only hot shard pages device-resident,
        the host oracle covers the rest, bit-exact); single-device
        only, a mesh placement disables it.  ``plane_page_bytes``
        sizes one page.  ``tenant_byte_quota`` caps one tenant's
        resident plane/page bytes (0 = off); ``tenant_qps_quota`` /
        ``tenant_slot_quota`` shed an over-quota tenant's queries with
        a structured 503 BEFORE they take an executor slot (0 = off).
        ``tenant_device_seconds_quota`` (r19): cap a tenant's RECENT
        measured device seconds (the cost ledger's decayed window,
        ~60s half-life) — sheds by what queries actually COST on
        device, not how many arrived (0 = off).
        ``cost_observability`` (r19): False swaps the cost ledger and
        flight recorder for null objects — the instrumentation-off
        tier the overhead bench (config34) measures against.

        Kernel tier (r24): ``kernel_tier`` routes the hottest fused
        families through hand-written Pallas kernels (``"pallas"``)
        instead of the XLA-compiled oracle tier (``"xla"``, default).
        Selection is per-family and fail-safe: a family whose Pallas
        lowering fails falls back to XLA silently (counted in
        ``pallas_fallback_total``), and XLA remains the bit-exact
        correctness oracle and the governor's degraded-serving path.
        ``dispatch_loop_fusion`` (r24) lets the batcher collapse a
        collection window's same-shape selected-count groups into ONE
        jitted on-device loop dispatch.  ``fused_warmup`` (r24) runs
        the compile-ladder warmer: delta-aware fused programs for a
        newly resident plane shape pre-compile on a background thread
        so the first post-ingest query serves from a warm cache
        (single-device only — disabled under a mesh placement)."""
        self.holder = holder
        self.translate = translate or TranslateStore(
            holder.path, health=getattr(holder, "storage_health", None))
        self.placement = placement
        if placement is not None and place is None:
            place = placement.place
        kw = {"budget_bytes": plane_budget} if plane_budget else {}
        from pilosa_tpu.obs import GLOBAL_TRACER, NopStats
        from pilosa_tpu.tenancy import (PlanePager, ResidencyGovernor,
                                        TenantQos)
        self.stats = stats or NopStats()
        # device-cost ledger + flight recorder (r19): one ledger and
        # one event ring per executor, threaded into every layer that
        # spends device time (planes, pager, fused cache, batcher,
        # governor) — attribution and incident capture are always on.
        # Flight dumps land under the holder's data dir.
        from pilosa_tpu.obs import (NULL_FLIGHT, NULL_LEDGER, CostLedger,
                                    FlightRecorder)
        if cost_observability:
            self.ledger = CostLedger(stats=self.stats)
            self.flight = FlightRecorder(
                dump_dir=f"{holder.path}/_flight", stats=self.stats)
        else:
            self.ledger = NULL_LEDGER
            self.flight = NULL_FLIGHT
        # tenancy (r17): the governor is always attached — with no
        # quotas and no telemetry its eviction ordering degrades to
        # the stamped LRU exactly, so the single-tenant default pays
        # nothing.  The pager is single-device only: a partial page
        # plane has no meaning under a mesh-sharded placement.
        self.governor = ResidencyGovernor(byte_quota=tenant_byte_quota)
        self.planes = PlaneCache(place, placement=placement,
                                 stats=self.stats,
                                 sidecars=plane_sidecars,
                                 delta_cells=delta_cells,
                                 delta_compact_fraction=(
                                     delta_compact_fraction),
                                 governor=self.governor,
                                 flight=self.flight, **kw)
        self.pager = (PlanePager(self.planes, self.governor,
                                 page_bytes=plane_page_bytes,
                                 stats=self.stats, flight=self.flight)
                      if plane_paging and placement is None else None)
        self.qos = TenantQos(tenant_qps_quota, tenant_slot_quota,
                             stats=self.stats,
                             device_seconds_quota=(
                                 tenant_device_seconds_quota),
                             ledger=self.ledger)
        self.tracer = tracer or GLOBAL_TRACER
        from pilosa_tpu.exec.fused import FusedCache
        self.fused = FusedCache(stats=self.stats,
                                mesh_guard=placement is not None,
                                ledger=self.ledger, flight=self.flight,
                                kernel_tier=kernel_tier)
        # compile-ladder warm-up (r24): single-device only — warmed
        # keys carry shard=None, which is exactly the serve-time
        # sharding_key of single-device operands; under a placement
        # the keys would never match, so the warmer stays off.
        self.warmer = None
        if fused_warmup and placement is None:
            from pilosa_tpu.exec.warmup import ProgramWarmer
            self.warmer = ProgramWarmer(self.fused, stats=self.stats,
                                        ledger=self.ledger,
                                        flight=self.flight)
            self.planes.warmer = self.warmer
        # whole-tree compilation (r16): compound boolean Counts gather
        # rows from the resident plane and fold a postfix program in
        # one fused XLA dispatch.  Off (`tree_fusion=False`) restores
        # the pre-r16 op-at-a-time/generic path — the bench baseline
        # and the escape hatch the runbook documents.
        self.tree_fusion = tree_fusion
        from pilosa_tpu.obs.metrics import DEPTH_BUCKETS
        self.stats.set_buckets("tree_fusion_depth", DEPTH_BUCKETS)
        # cross-request coalescing is the DEFAULT serving spine (r6):
        # the adaptive window costs a solo request nothing, and under
        # concurrency every dense family pays one dispatch + one read
        # per collection window instead of one per request
        self.batcher = None
        window = count_batch_window
        if isinstance(window, str):
            w = window.strip().lower()
            if w == "adaptive":
                window = "adaptive"
            elif w in ("", "0", "off", "none", "false"):
                window = 0.0
            else:
                try:
                    window = float(w)
                except ValueError:
                    raise ValueError(
                        f"count_batch_window: expected 'adaptive', a "
                        f"number of seconds, or 'off', got {window!r}")
        if window == "adaptive" or window > 0:
            from pilosa_tpu.exec.batcher import CountBatcher
            self.batcher = CountBatcher(
                self.fused, window_s=window, stats=self.stats,
                pipeline_depth=dispatch_pipeline_depth,
                solo_fastlane=solo_fastlane,
                watchdog_s=dispatch_watchdog_seconds,
                probe_after_s=device_health_probe_seconds,
                placement_key=(getattr(placement, "key", None)
                               if placement is not None else None),
                ledger=self.ledger, flight=self.flight,
                loop_fusion=dispatch_loop_fusion)
        # mesh serving telemetry (ISSUE 16): how many chips the plane
        # axis spans (1 = single-device serving)
        self.stats.gauge(
            "mesh_devices",
            int(getattr(placement, "n_devices", 1)
                * getattr(placement, "words_size", 1))
            if placement is not None else 1)
        # query-plan cache (r6 tentpole): (index, normalized PQL,
        # shards, translate flag) -> planned tree + leaf specs, so a
        # repeated serving shape skips parse AND plan entirely (PQL
        # parse alone measured 1.09 ms/request ≈ 2.4× the device budget
        # at 5k qps, BENCH_r05)
        self._plans: OrderedDict = OrderedDict()
        self._plans_lock = threading.Lock()
        # cross-query OOM recovery (r4 → r5): one recovery at a time
        # through the gate; the in-flight count lets the exclusive
        # stage drain concurrent queries instead of evicting the
        # planes under them
        self._oom_gate = threading.Lock()
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._tls = threading.local()
        # closed (cleared) only while a stage-2 recovery drains to
        # exclusivity: new arrivals park here instead of entering the
        # in-flight count and starving the drain forever
        self._recovery_open = threading.Event()
        self._recovery_open.set()
        self._exec_slots = (threading.BoundedSemaphore(max_concurrent)
                            if max_concurrent else None)
        self.max_concurrent = max_concurrent
        self.slot_timeout_s = self.SLOT_TIMEOUT_S

    @property
    def slots_in_use(self) -> int:
        """Admitted top-level queries currently executing (the
        /metrics ``query_slots_in_use`` gauge)."""
        return self._inflight

    def _query_deadline(self) -> float | None:
        """The serving thread's current query deadline (set by the
        outermost :meth:`execute`) — what every batcher submit
        carries so pipeline waits stay bounded (r18)."""
        return getattr(self._tls, "deadline", None)

    # -- serving-path attribution (r19 satellite) ----------------------------

    def _admission_path(self) -> str:
        """The serving path this query starts on: the fused pipeline,
        the op-at-a-time fallback (no batcher), or degraded-governor
        per-item serving.  Down-stack sites refine it (paged /
        row-directory oracle)."""
        if self.batcher is None:
            return "op-at-a-time fallback"
        if self.batcher.governor.state != "healthy":
            return "degraded governor"
        return "fused"

    def _note_path(self, path: str) -> None:
        self._tls.spath = path

    def serving_path(self) -> str:
        """Which path answered the serving thread's LAST query —
        ``fused`` / ``op-at-a-time fallback`` / ``paged`` /
        ``row-directory oracle`` / ``degraded governor``.  Read by the
        slow-query log so every slow entry names its path."""
        return getattr(self._tls, "spath", "fused")

    def device_health(self) -> dict:
        """The ``/status`` deviceHealth block: the batcher's governor
        state, watchdog knob and quarantine counts (a batcher-less
        executor is trivially healthy — there is no shared pipeline
        to stall)."""
        warm = (self.warmer.payload() if self.warmer is not None
                else {"enabled": False, "shapesWarmed": 0,
                      "programsWarmed": 0, "compileSeconds": 0.0,
                      "pending": 0})
        if self.batcher is None:
            return {"state": "healthy", "stateCode": 0,
                    "watchdogSeconds": 0.0, "quarantinedWindows": 0,
                    "inflightWindows": 0, "consecutiveFaults": 0,
                    "watchdogTrips": 0,
                    "kernelTier": getattr(self.fused, "effective_tier",
                                          "xla"),
                    "warmup": warm}
        payload = self.batcher.health_payload()
        payload["warmup"] = warm
        return payload

    def mesh_status(self) -> dict | None:
        """The ``/status`` ``mesh`` block (ISSUE 16): device count,
        shard axis, per-device resident plane bytes and padded-shard
        count — None when serving single-device."""
        return self.planes.mesh_stats()

    def time_status(self) -> dict:
        """The ``/status`` ``timeViews`` block (r23): resident
        bucketed time planes (index/field/bucket/byte geometry, delta
        overlay state) — which time fields answer range queries at
        device speed versus the span-union fallback."""
        planes = self.planes.time_plane_status()
        return {"planes": planes,
                "residentBytes": sum(p["bytes"] for p in planes),
                "buckets": sum(p["buckets"] for p in planes)}

    def cost_status(self) -> dict:
        """The ``/status`` ``costs`` block (r19): the device-cost
        ledger's rollups — measured device seconds and bytes scanned
        attributed per tenant, per query shape, and per plane (top-K
        with an ``other`` fold), plus compile totals."""
        return self.ledger.payload()

    def tenancy_status(self) -> dict:
        """The ``/status`` ``tenancy`` block (r17): knobs, per-tenant
        residency/hit-ratio/page-in/shed counts, QoS state, eviction
        reasons.  Refreshes the ``plane_resident_pages`` gauge at
        scrape time (pager payload)."""
        planes = self.planes
        out = {"paging": self.pager is not None,
               "tenantByteQuota": self.governor.byte_quota,
               "evictions": planes.evictions,
               "evictionsByReason": dict(planes._evictions_by_reason),
               "qos": self.qos.payload()}
        if self.pager is not None:
            pg = self.pager.payload()
            tenants = pg.pop("tenants")
            out.update(pg)
        else:
            tenants = {}
            with planes._lock:
                for k, v in planes._entries.items():
                    d = tenants.setdefault(
                        k[1], {"residentBytes": 0, "residentPages": 0,
                               "residentEntries": 0})
                    d["residentBytes"] += v[2]
                    d["residentEntries"] += 1
        sheds = out["qos"]["sheds"]
        for t, n in sheds.items():
            tenants.setdefault(
                t, {"residentBytes": 0, "residentPages": 0,
                    "residentEntries": 0})
        for t, d in tenants.items():
            d["sheds"] = sheds.get(t, 0)
        out["tenants"] = tenants
        return out

    # -- in-flight accounting (OOM recovery) --------------------------------

    def _enter_inflight(self) -> None:
        with self._inflight_cv:
            self._inflight += 1

    def _leave_inflight(self) -> None:
        with self._inflight_cv:
            self._inflight -= 1
            self._inflight_cv.notify_all()

    def _drain_to_exclusive(self, timeout: float = 120.0) -> bool:
        """Wait until this query is the only one in flight (other
        queries finish or park at the OOM gate).  Bounded: a hung peer
        must not pin recovery forever — on timeout the retry proceeds
        anyway and may still fail, which is then an honest answer."""
        with self._inflight_cv:
            end = time.monotonic() + timeout
            while self._inflight > 1:
                remaining = end - time.monotonic()
                if remaining <= 0:
                    return False
                self._inflight_cv.wait(remaining)
        return True

    # ------------------------------------------------------------------ api

    def execute(self, index_name: str, query: str | Query,
                shards: list[int] | None = None,
                translate_output: bool = True, tracer=None,
                deadline: float | None = None) -> list:
        """Run every top-level call; returns one result per call
        (reference: ``Executor.Execute`` → ``QueryResponse.Results``).

        ``translate_output=False`` leaves raw IDs in results — used by
        the cluster layer, which merges partials from many nodes first
        and key-translates once at the edge.  ``tracer`` overrides the
        shared tracer (the ``profile=true`` path uses a per-request one
        so concurrent queries' spans don't interleave).  ``deadline``
        (``time.monotonic()`` cutoff) aborts with
        :class:`QueryTimeoutError` at call/block boundaries."""
        index = self.holder.index(index_name)
        if index is None:
            raise ExecutionError(f"index {index_name!r} not found")
        # outermost call only (nested execute — e.g. resolved Limit
        # subtrees — shares the outer query's lease set and in-flight
        # slot): register for OOM-recovery coordination
        depth = getattr(self._tls, "depth", 0)
        timer = None
        qos_held = False
        if depth == 0:
            from pilosa_tpu.obs import StageTimer
            # stage marks double as `stage.*` child spans on the traced
            # query (per-request tracer when given, else the shared one)
            timer = StageTimer(self.stats, tracer=tracer or self.tracer)
            # per-tenant QoS FIRST (r17 tenancy): an over-quota tenant
            # sheds with a structured 503 BEFORE taking an executor
            # slot, so its retries queue at the client — never in
            # front of in-quota tenants' admissions
            if self.qos.enabled:
                self.qos.admit(index_name)  # raises TenantThrottledError
                qos_held = True
            # bounded concurrency FIRST: each executing query holds
            # live device scratch (program temps, per-query outputs);
            # with residency near budget, unbounded client threads
            # multiply scratch past HBM headroom (32 streams OOM'd
            # every thread at 8.5 GB resident, config14 r5).  Queries
            # queue here — the chip serializes execution anyway, so a
            # bounded pool costs no throughput.  Timed: a wedged
            # recovery holding every slot must not refuse service
            # silently forever
            if self._exec_slots is not None:
                t_wait = time.perf_counter()
                acquired = self._exec_slots.acquire(
                    timeout=self.slot_timeout_s)
                self.stats.observe("query_queue_wait_seconds",
                                   time.perf_counter() - t_wait)
                if not acquired:
                    self.stats.count("query_shed_total", 1)
                    if qos_held:
                        self.qos.release(index_name)
                    raise ExecutorSaturatedError(
                        f"executor at max concurrent queries "
                        f"({self.max_concurrent}) for "
                        f"{self.slot_timeout_s:.0f}s; retry later",
                        retry_after=1.0)
            # slot held: from here, ANY setup failure must release it —
            # a leaked slot is permanent, and max_concurrent leaks turn
            # into a total outage behind the 180s-timeout error
            # (ADVICE r5, the admission-slot leak)
            try:
                # park while a stage-2 OOM recovery drains to
                # exclusivity — without this, steady arrivals keep the
                # in-flight count above 1 and the drain can never
                # finish.  AFTER the slot: a thread that waited out a
                # long acquire must still honor a recovery that started
                # meanwhile.  Bounded: a wedged recovery must not
                # refuse service forever
                self._recovery_open.wait(timeout=180.0)
                self._enter_inflight()
                try:
                    self.planes.begin_query()
                except BaseException:
                    self._leave_inflight()
                    raise
            except BaseException:
                if self._exec_slots is not None:
                    self._exec_slots.release()
                if qos_held:
                    self.qos.release(index_name)
                raise
            timer.mark("admit")
            self._tls.stage_timer = timer
            # deadline propagation (r18): remember this query's cutoff
            # on the serving thread so every batcher submit down-stack
            # carries it — wait() then blocks with a BOUNDED timeout
            # instead of forever behind a sick device
            self._tls.deadline = deadline
            # cost-ledger attribution context (r19): tenant + trace on
            # the serving thread — batcher items and fast-lane solo
            # dispatches stamp their charges from this, and the plane
            # cache fills in the plane as the query touches it
            set_query_context(index_name, trace_id=current_trace_id())
            # serving-path tag (r19 satellite): which path answered —
            # refined down-stack (paged / oracle / op-at-a-time), read
            # by the slow-query log after execute returns
            self._tls.spath = self._admission_path()
        self._tls.depth = depth + 1
        try:
            if depth == 0 and fault.ACTIVE:
                # post-admission failpoint: `delay` holds a slot open
                # (how saturation tests wedge the executor), `error`
                # fails the query after admission.  Inside the main
                # try: a raise here must still release the slot.
                fault.fire("exec.execute", index=index_name)
                timer.reset()  # injected delay is no stage's fault
            if isinstance(query, str):
                if depth == 0:
                    # plan-cache fast path: a repeated all-Count serving
                    # shape skips parse AND plan (r6 tentpole)
                    out = self._execute_planned(
                        index, index_name, query, shards, translate_output,
                        tracer, deadline, timer)
                    if out is not None:
                        return out
                # memoized: repeated serving shapes skip the parser (the
                # AST is never mutated in place — rewriters copy first)
                query = parse_cached(query)
                if timer is not None:
                    timer.mark("parse")
            return self._execute_calls(index, index_name, query, shards,
                                       translate_output, tracer, deadline)
        finally:
            self._tls.depth = depth
            if depth == 0:
                self._tls.stage_timer = None
                self._tls.deadline = None
                # ledger context clears here; the serving-path tag
                # survives until the NEXT admission on this thread —
                # the API layer reads it after execute returns
                clear_query_context()
                self.planes.end_query()
                self._leave_inflight()
                if self._exec_slots is not None:
                    self._exec_slots.release()
                if qos_held:
                    self.qos.release(index_name)

    def _execute_calls(self, index, index_name: str, query: Query,
                       shards, translate_output: bool, tracer,
                       deadline: float | None) -> list:
        tracer = tracer or self.tracer
        results = []
        # spans per call + per-call-type latency counters (reference:
        # executor span/stats emission, SURVEY.md §3.3 / §6).
        # Runs of consecutive Count calls execute as ONE fused program
        # with one result read (consecutive only: a write between counts
        # must stay ordered).
        i = 0
        calls = query.calls
        while i < len(calls):
            run_end = i
            while (run_end < len(calls) and calls[run_end].name == "Count"
                   and len(calls[run_end].children) == 1):
                run_end += 1
            if run_end - i > 1:
                ctx = _Ctx(index, self._shards_for(index, shards, calls[i]),
                           translate_output, deadline=deadline)
                ctx.check_deadline()
                with tracer.span("executor.CountBatch",
                                 index=index_name, calls=run_end - i,
                                 shards=len(ctx.shards)):
                    t0 = time.perf_counter()
                    batched = self._with_oom_retry(
                        lambda: self._count_batch(ctx, calls[i:run_end]))
                    self.stats.timing("query_seconds",
                                      time.perf_counter() - t0,
                                      call="CountBatch")
                if batched is not None:
                    results.extend(batched)
                    i = run_end
                    continue
            call = calls[i]
            ctx = _Ctx(index, self._shards_for(index, shards, call),
                       translate_output, deadline=deadline)
            ctx.check_deadline()
            with tracer.span("executor." + call.name,
                             index=index_name,
                             shards=len(ctx.shards)):
                t0 = time.perf_counter()
                results.append(self._with_oom_retry(
                    lambda: self._call(ctx, call)))
                self.stats.timing("query_seconds",
                                  time.perf_counter() - t0, call=call.name)
            i += 1
        return results

    def _count_batch(self, ctx: _Ctx, calls: list[Call]) -> list[int] | None:
        """Plan every Count child, concatenate leaf lists, run one
        program -> int32[K, S], host-finish each row.  Returns None if
        any child is unfusable (caller falls back to per-call)."""
        fast = self._count_batch_plane(ctx, calls)
        if fast is not None:
            return fast
        fast = self._count_batch_bsi(ctx, calls)
        if fast is not None:
            return fast
        fast = self._count_batch_tree(ctx, calls)
        if fast is not None:
            return fast
        from pilosa_tpu.exec.fused import Unfusable, shift_leaves
        nodes, all_leaves = [], []
        try:
            for call in calls:
                leaves: list = []
                node = self._plan(ctx, call.children[0], leaves)
                nodes.append(shift_leaves(node, len(all_leaves)))
                all_leaves.extend(leaves)
        except Unfusable:
            return None
        timer = getattr(self._tls, "stage_timer", None)
        if timer is not None:
            timer.mark("plan")
        return self._dispatch_count_run(tuple(nodes), tuple(all_leaves),
                                        timer)

    def _dispatch_count_run(self, nodes: tuple, leaves: tuple,
                            timer) -> list[int]:
        """One request's planned Count run → per-call totals (the one
        dispatch tail shared by the plan-cached and freshly-planned
        paths).  With the batcher, the whole request is ONE batch item:
        concurrent requests share a dispatch + read."""
        if self.batcher is not None:
            out = self.batcher.submit_many(
                nodes, leaves, deadline=self._query_deadline())
            if timer is not None:
                timer.mark("read")
            return out
        per_shard = self.fused.run_count_batch(nodes, leaves)
        if timer is not None:
            timer.mark("dispatch")
        host = np.asarray(per_shard).astype(np.int64)  # one read
        if timer is not None:
            timer.mark("read")
        return [int(row.sum()) for row in host]

    def _count_batch_plane(self, ctx: _Ctx, calls: list[Call]) \
            -> list[int] | None:
        """Same-field plain-row Count batches execute as ONE whole-plane
        popcount program (``kernels.row_counts`` over the resident
        ``uint32[S, R, W]`` field plane) — one input array, one fused
        reduce, one read.  The generic batch builds K separate per-row
        leaf arrays and K reduce kernels, which measured ~4× slower at
        the 1B-col serving condition (BASELINE.md r3).  Returns None
        when the batch doesn't match (mixed fields, conditions, time
        ranges, over-budget plane, or a tiny slice of a huge row set —
        whole-plane counting would waste bandwidth there).  A plane
        past the HBM budget (or its tenant's byte quota) no longer
        dead-ends: it reroutes to the PAGED residency path (r17) —
        resident shard pages answer on device, the host oracle covers
        the rest, bit-exact."""
        hit = self._plain_row_parse(ctx, calls)
        if hit is None:
            return None
        field, values = hit
        if not self.planes.has_plane(ctx.index.name, field, VIEW_STANDARD,
                                     ctx.shards):
            # admission decision only when the plane isn't resident yet:
            # plane_bytes walks every fragment's row set — O(shards)
            # host work that must stay OFF the per-request path (it
            # capped serving at ~1.1k qps on the 954-shard bench)
            est = self.planes.plane_bytes(field, VIEW_STANDARD,
                                          ctx.shards)
            if self._paging_engaged(est):
                return self._paged_count(ctx, field, values)
            if est > self.planes.budget:
                return None
            r_est = max(1, est // (len(ctx.shards) * WORDS_PER_SHARD * 4))
            if len(calls) * 4 < r_est:
                return None
        row_ids = [self._row_id(ctx, field, v, create=False)
                   for v in values]
        # nowait: while the whole-field plane builds in the background
        # the generic per-row path serves (bounded per-row transfers)
        # instead of this batch stalling on full residency
        ps = self.planes.field_plane_nowait(ctx.index.name, field,
                                            VIEW_STANDARD, ctx.shards)
        if ps is None:
            return None
        return self._plane_count_rows(
            ps, row_ids, getattr(self._tls, "stage_timer", None))

    def _plain_row_parse(self, ctx: _Ctx, calls: list[Call]):
        """``(field, values)`` when every call is ``Count(Row(f=v))``
        over ONE non-BSI field with plain scalar rows (no conditions,
        no time ranges) — the shape both the whole-plane batch and the
        paged path serve.  None otherwise."""
        fname = None
        values = []
        for call in calls:
            child = call.children[0]
            if child.name != "Row" or child.children:
                return None
            hit = _field_arg(child)
            if hit is None:
                return None
            f, v = hit
            if isinstance(v, (Condition, Call)):
                return None
            if ("from" in child.args or "to" in child.args
                    or "_timestamp" in child.args):
                return None
            if fname is None:
                fname = f
            elif f != fname:
                return None
            values.append(v)
        if fname is None:
            return None
        field = self._field(ctx, fname)
        if field.options.type in BSI_TYPES:
            return None
        if not ctx.shards:  # shards=[]: generic path answers zeros
            return None
        return field, values

    # ------------------------------------------------ paged residency (r17)

    def _paging_engaged(self, est: int) -> bool:
        """Whether a plane of ``est`` bytes serves PAGED: a pager
        exists (single-device serving) and the plane exceeds the HBM
        budget or its tenant's byte quota.  Under both limits the
        whole-plane path keeps its exact pre-r17 behavior."""
        if self.pager is None:
            return False
        limit = self.planes.budget
        if self.governor.byte_quota > 0:
            limit = min(limit, self.governor.byte_quota)
        return est > limit

    def _count_batch_paged(self, ctx: _Ctx,
                           calls: list[Call]) -> list[int] | None:
        """Solo-path entry to paged counting: engages only for the
        plain-Row shape on a plane past the budget/quota limit —
        everything else falls through to the existing paths."""
        if self.pager is None or not ctx.shards:
            return None
        hit = self._plain_row_parse(ctx, calls)
        if hit is None:
            return None
        field, values = hit
        if self.planes.has_plane(ctx.index.name, field, VIEW_STANDARD,
                                 ctx.shards):
            return None  # whole plane resident: the normal path serves
        est = self.planes.plane_bytes(field, VIEW_STANDARD, ctx.shards)
        if not self._paging_engaged(est):
            return None
        return self._paged_count(ctx, field, values)

    def _paged_count(self, ctx: _Ctx, field: Field,
                     values: list) -> list[int] | None:
        """Per-call totals for an over-limit plane via paged residency:
        each shard page is either RESIDENT (answered on device — the
        same selected-gather/whole-plane kernels, delta overlays and
        all), PAGED IN on demand (sidecar-warm partial expansion,
        admitted against the tenant's byte quota), or covered by the
        host ORACLE (``row_cardinalities`` directory sums).  Totals sum
        per row across pages — bit-exact regardless of the residency
        mix.  None = the shard axis doesn't split (single page)."""
        pages = self.pager.partition(field, VIEW_STANDARD, ctx.shards)
        if pages is None:
            return None
        self._note_path("paged")
        row_ids = [self._row_id(ctx, field, v, create=False)
                   for v in values]
        timer = getattr(self._tls, "stage_timer", None)
        totals = [0] * len(row_ids)
        for page_shards in pages:
            ps = self.pager.resident_page(ctx.index.name, field,
                                          VIEW_STANDARD, page_shards)
            if ps is None:
                ps = self.pager.page_in(ctx.index.name, field,
                                        VIEW_STANDARD, page_shards)
            if ps is not None:
                part = self._plane_count_rows(ps, row_ids, timer)
            else:
                # quota denied the page-in: host truth answers this
                # page exactly (directory sums, no bit expansion)
                self._note_path("row-directory oracle")
                part = self.pager.oracle_counts(
                    field, VIEW_STANDARD, page_shards, row_ids)
            for i, v in enumerate(part):
                totals[i] += int(v)
        if timer is not None:
            timer.mark("read")
        return totals

    # -------------------------------------------------- BSI range (r20)

    def _bsirange_item(self, ctx: _Ctx, child: Call):
        """Lower ``Count(Row(field op p))`` / the between forms to a
        batcher ``bsirange`` item: ``(field, op_keys, offsets)``.
        None = not a simple BSI range count (compound children, time
        args, non-BSI field, or a saturated predicate whose trivial
        answer the generic path lowers without a kernel)."""
        if child.name not in ("Row", "Range") or child.children:
            return None
        hit = _field_arg(child)
        if hit is None:
            return None
        fname, value = hit
        field = ctx.index.field(str(fname))
        if field is None or field.options.type not in BSI_TYPES:
            return None
        if ("from" in child.args or "to" in child.args
                or "_timestamp" in child.args):
            return None
        cond = (value if isinstance(value, Condition)
                else Condition("==", value))
        if isinstance(cond.value, Call) or (
                cond.op not in _SCALAR_TO_KEY
                and cond.op not in BETWEEN_OPS):
            return None
        opts = field.options
        depth = opts.bit_depth
        bound = (1 << depth) - 1
        if cond.op in BETWEEN_OPS:
            lo_op, hi_op = between_cmp_ops(cond.op)
            pairs = [(lo_op, cond.value[0]), (hi_op, cond.value[1])]
        else:
            pairs = [(_SCALAR_TO_KEY[cond.op], cond.value)]
        op_keys, offsets = [], []
        for op_key, v in pairs:
            offset = field.to_stored(v) - opts.base
            if offset > bound or offset < -bound:
                return None  # saturated: trivial, no kernel needed
            op_keys.append(op_key)
            offsets.append(int(offset))
        return field, tuple(op_keys), tuple(offsets)

    def _bsirange_operands(self, field: Field, offsets: tuple) -> tuple:
        depth = field.options.bit_depth
        ops = []
        for offset in offsets:
            ops.append(jnp.asarray(bsik.predicate_masks(abs(offset),
                                                        depth)))
            ops.append(jnp.asarray(offset < 0))
        return tuple(ops)

    def _count_batch_bsi(self, ctx: _Ctx,
                         calls: list[Call]) -> list[int] | None:
        """A request of simple BSI range Counts through the batcher's
        ``bsirange`` family (r20): every call enqueues into ONE
        collection window, same-plane items across concurrent requests
        co-batch into one fused program (identical predicates dedupe),
        and the plane arrives DELTA-AWARE (``bsi_plane_delta``) — no
        fold, no rebuild under sustained ingest.  None = some call
        isn't this shape (fall through to tree/generic)."""
        if self.batcher is None or not ctx.shards:
            return None
        if len(ctx.shards) > self._REDUCE_SHARD_MAX:
            return None  # device int32 shard reduce must stay exact
        items = []
        for call in calls:
            it = self._bsirange_item(ctx, call.children[0])
            if it is None:
                return None
            field, op_keys, offsets = it
            items.append((field, op_keys, offsets,
                          self._bsirange_operands(field, offsets)))
        return self._run_bsirange_items(
            ctx, items, getattr(self._tls, "stage_timer", None))

    def _run_bsirange_items(self, ctx: _Ctx, items: list,
                            timer) -> list[int]:
        """Dispatch resolved bsirange items — ``(field, op_keys,
        offsets, operands)`` per Count — through the batcher: the one
        place that builds the batcher's spec/sig tuples and decides
        solo (blocking submit → fast lane) vs windowed (enqueue ALL
        before waiting on any).  Planes resolve up front, so a
        failing resolution can never abandon already-enqueued
        neighbors in the window."""
        deadline = self._query_deadline()
        planes: dict[str, object] = {}
        for field, _ops, _offs, _operands in items:
            if field.name not in planes:
                planes[field.name] = self.planes.bsi_plane_delta(
                    ctx.index.name, field, ctx.shards)
        if timer is not None:
            timer.mark("plan")
        if len(items) == 1:
            field, op_keys, offsets, operands = items[0]
            ps = planes[field.name]
            out = [self.batcher.submit_bsirange(
                ps.plane, (op_keys, False), operands,
                (op_keys, offsets, 0), delta=ps.delta,
                deadline=deadline)]
        else:
            handles = []
            for field, op_keys, offsets, operands in items:
                ps = planes[field.name]
                handles.append(self.batcher.enqueue_bsirange(
                    ps.plane, (op_keys, False), operands,
                    (op_keys, offsets, 0), delta=ps.delta,
                    deadline=deadline))
            out = [self.batcher.wait(h) for h in handles]
        if timer is not None:
            timer.mark("read")  # coalesced wait: window+dispatch+read
        return out

    # -------------------------------------------------- whole-tree (r16)

    def _count_batch_tree(self, ctx: _Ctx,
                          calls: list[Call]) -> list[int] | None:
        """Compound Count runs through the whole-tree compiler (r16
        tentpole): every child lowers to a canonical
        :class:`exec.tree.TreeSpec` and the request's trees dispatch
        as batcher items sharing ONE collection window — one gather of
        the slot union per anchor plane, one packed readback joined
        with any concurrent requests' trees.  None = not a tree shape
        or not runnable right now (anchor plane not resident /
        admittable) — callers fall through to the generic fused path,
        which answers identically."""
        from pilosa_tpu.exec import tree as treemod
        from pilosa_tpu.exec.fused import Unfusable
        if not self.tree_fusion or not ctx.shards:
            return None
        if not any(c.children[0].name in treemod.TREE_CALLS
                   for c in calls):
            return None
        try:
            specs = [treemod.lower_count_tree(self, ctx, c.children[0])
                     for c in calls]
        except Unfusable:
            return None
        return self._run_tree_specs(
            ctx, specs, getattr(self._tls, "stage_timer", None))

    def _tree_stats(self, spec) -> None:
        self.stats.observe("tree_fusion_depth", float(spec.depth))
        if spec.cse_hits:
            self.stats.count("tree_cse_hits_total", spec.cse_hits)
        if spec.static_ops:
            self.stats.count("tree_static_ops_total", spec.static_ops)

    def _run_tree_specs(self, ctx: _Ctx, specs, timer) -> list[int] | None:
        """Materialize + dispatch lowered tree specs: row ids resolve
        to plane slots FRESH per hit (so plan-cached specs keep
        serving current truth), extras re-fetch through the plane
        cache, and a delta-dirty anchor plane answers base⊕delta
        inside the same program.  None = an anchor plane isn't
        resident/admittable or a field vanished — admission decisions
        stay on the un-cached path."""
        resolved = []
        for spec in specs:
            hit = self._tree_item(ctx, spec)
            if hit is None:
                return None
            resolved.append(hit)
        for spec in specs:
            self._tree_stats(spec)
        if timer is not None:
            timer.mark("plan")
        if self.batcher is not None:
            if len(resolved) == 1:
                # single tree: the blocking submit rides the solo fast
                # lane when traffic is solo (inline dispatch, no window)
                ps, item = resolved[0]
                out = [self.batcher.submit_tree(
                    ps.plane, *item, delta=ps.delta,
                    deadline=self._query_deadline())]
            else:
                # enqueue ALL trees before waiting on any: the whole
                # request lands in one collection window
                handles = [self.batcher.enqueue_tree(
                    ps.plane, *item, delta=ps.delta,
                    deadline=self._query_deadline())
                           for ps, item in resolved]
                out = [self.batcher.wait(h) for h in handles]
            if timer is not None:
                timer.mark("read")  # coalesced window+dispatch+read
            return out
        # no batcher: one fused program per (plane, overlay) group
        from pilosa_tpu.exec.tree import assemble_items
        groups: dict[tuple, list[int]] = {}
        group_ps: dict[tuple, object] = {}
        for i, (ps, _item) in enumerate(resolved):
            k = (id(ps.plane),
                 id(ps.delta) if ps.delta is not None else 0)
            groups.setdefault(k, []).append(i)
            group_ps[k] = ps
        out = [0] * len(resolved)
        for k, idxs in groups.items():
            ps = group_ps[k]
            slots, progs, extras = assemble_items(
                [resolved[i][1] for i in idxs])
            dev = self.fused.run_tree_counts(ps.plane, slots, progs,
                                             extras, delta=ps.delta)
            if timer is not None:
                timer.mark("dispatch")
            vals = np.asarray(dev).astype(np.int64)
            for j, i in enumerate(idxs):
                out[i] = int(vals[j])
        if timer is not None:
            timer.mark("read")
        return out

    def _tree_item(self, ctx: _Ctx, spec):
        """One spec's runtime form: ``(PlaneSet, (slots, prog,
        extras))`` with PUSH args rewritten against the LIVE slot map
        (absent rows become zero pushes) and extra operands
        materialized.  None = not runnable on the device path right
        now (caller falls back / invalidates)."""
        from pilosa_tpu.engine.kernels import TREE_PUSH, TREE_ZERO
        field = ctx.index.field(spec.field)
        if field is None or field.options.type in BSI_TYPES:
            return None
        if len(ctx.shards) > self._REDUCE_SHARD_MAX:
            return None  # device int32 shard reduce must stay exact
        if not self.planes.has_plane(ctx.index.name, field,
                                     VIEW_STANDARD, ctx.shards):
            # admission mirrors _count_batch_plane: budget walk only
            # when the plane isn't resident, and skip whole-plane
            # residency for a tiny slice of a huge row set
            est = self.planes.plane_bytes(field, VIEW_STANDARD,
                                          ctx.shards)
            if est > self.planes.budget:
                return None
            r_est = max(1, est // (len(ctx.shards) * WORDS_PER_SHARD * 4))
            if max(1, len(spec.rows)) * 4 < r_est:
                return None
        ps = self.planes.field_plane_nowait(ctx.index.name, field,
                                            VIEW_STANDARD, ctx.shards)
        if ps is None:
            return None
        slots: list[int] = []
        slot_arg: list[int | None] = []
        for s in ps.slots_for(spec.rows):
            if s is None:
                slot_arg.append(None)
            else:
                slot_arg.append(len(slots))
                slots.append(s)
        extras = []
        for espec in spec.extras:
            arr = self._tree_extra(ctx, espec)
            if arr is None:
                return None
            extras.append(arr)
        prog: list[tuple] = []
        for op, arg in spec.prog:
            if op == TREE_PUSH:
                new = slot_arg[arg]
                if new is None:  # row has no bits anywhere → empty
                    prog.append((TREE_ZERO, 0))
                    continue
                arg = new
            prog.append((op, arg))
        return ps, (tuple(slots), tuple(prog), tuple(extras))

    def _tree_extra(self, ctx: _Ctx, spec) -> "jax.Array | None":
        """Materialize one extra tree operand (uint32[S, W]): the
        existence row, another set field's row, or a BSI predicate
        bitmap (masks re-derive from the spec's baked offset and the
        CURRENT bit depth — the plan validity rules pin the depth)."""
        kind = spec[0]
        if kind == "exists":
            return self._exists(ctx)
        if kind == "row":
            _, fname, vname, rid = spec
            field = ctx.index.field(fname)
            if field is None or field.options.type in BSI_TYPES:
                return None
            return self.planes.row_words(ctx.index.name, field, vname,
                                         rid, ctx.shards)
        if kind == "trange":
            # time-range leaf inside a compound tree (r23): the words
            # come from the fused bucket-range scan when the time plane
            # resides, else the span oracle — the TREE stays fused
            # either way (this is one extra operand)
            _, fname, rid, frm, to = spec
            field = ctx.index.field(fname)
            if field is None or not field.options.time_quantum:
                return None
            start = parse_pql_time(frm) if frm is not None else None
            end = parse_pql_time(to) if to is not None else None
            words = self._time_range_words(ctx, field, rid, start, end)
            if words is None:
                words = self._time_row_span(ctx, field, rid, start, end)
            return words
        if kind == "constrow":
            return self._const_row_cols(ctx, spec[1])
        fname = spec[1]
        field = ctx.index.field(fname)
        if field is None or field.options.type not in BSI_TYPES:
            return None
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        if kind == "bsi-exists":
            return ps.plane[..., bsik.EXISTS_ROW, :]
        _, _, op_key, offset = spec
        masks = jnp.asarray(bsik.predicate_masks(
            abs(offset), field.options.bit_depth))
        # one cached predicate program per op_key; masks/sign are
        # traced, so any offset of the same comparison reuses it
        return self.fused.run(("bsi", 0, 1, 2, op_key),
                              (ps.plane, masks, jnp.asarray(offset < 0)),
                              "words")

    # int32 cross-shard reduce stays exact while n_shards·2^20 < 2^31
    _REDUCE_SHARD_MAX = (1 << 31) // SHARD_WIDTH - 1

    # selected-row gather beats the whole-plane scan when the request
    # touches at most this fraction of the (padded) row axis: the
    # gather's memory traffic is n_sel/R_pad of the plane, but it
    # cannot dedupe as aggressively as identical whole-plane items
    # (which collapse to ONE scan per window), so the cutover is
    # conservative
    _SELECTED_ROWS_FRACTION = 4  # use gather when n_sel * 4 <= R_pad

    def _plane_count_rows(self, ps, row_ids, timer=None) -> list[int]:
        """Per-call totals for resolved ``row_ids`` (None = absent row
        -> 0) over a resident plane, choosing between the two
        multi-query fused kernels:

        - **selected-row gather** (r12): when the request touches a
          small fraction of a wide plane, one pass over just those
          rows' memory — N answers per gather, coalesced across
          concurrent requests by slot-union in the batcher;
        - **whole-plane row_counts**: otherwise — identical concurrent
          requests dedupe to ONE scan per window, the headline serving
          spine."""
        slots = [ps.slot_of.get(int(r)) if r is not None else None
                 for r in row_ids]
        live = list(dict.fromkeys(s for s in slots if s is not None))
        r_pad = ps.plane.shape[-2]
        if (live and len(ps.shards) <= self._REDUCE_SHARD_MAX
                and len(live) * self._SELECTED_ROWS_FRACTION <= r_pad):
            by_slot = self._plane_selected_totals(ps, tuple(live), timer)
            return [int(by_slot[s]) if s is not None else 0
                    for s in slots]
        totals = self._plane_totals(ps, timer)
        return [int(totals[s]) if s is not None else 0 for s in slots]

    def _plane_selected_totals(self, ps, slots: tuple,
                               timer=None) -> dict:
        """slot -> int64 total for the selected plane rows: one
        row-gather + popcount program, shard axis reduced on device
        (callers gate on ``_REDUCE_SHARD_MAX``), coalesced across
        concurrent requests via the batcher.  A delta-dirty plane
        (``ps.delta``, r15 ingest) answers base⊕delta in the same
        program — writes never force a rebuild here."""
        if self.batcher is not None:
            vals = self.batcher.submit_selected(
                ps.plane, slots, delta=ps.delta,
                deadline=self._query_deadline())
            if timer is not None:
                timer.mark("read")  # coalesced wait: window+dispatch+read
        else:
            out = self.fused.run_selected_counts(ps.plane, slots,
                                                 delta=ps.delta)
            if timer is not None:
                timer.mark("dispatch")
            vals = np.asarray(out).astype(np.int64)[:len(slots)]
            if timer is not None:
                timer.mark("read")
        return dict(zip(slots, (int(v) for v in vals)))

    def _plane_totals(self, ps, timer=None) -> np.ndarray:
        """Whole-plane per-row totals int64[R_pad]: one program + one
        read, coalesced ACROSS concurrent requests via the batcher
        (identical planes dedupe to one computation per window).

        Cross-shard reduce on DEVICE when int32 stays exact
        (n_shards * 2^20 < 2^31): the read shrinks from int32[S, R] to
        int32[R] — on transports with per-read costs the smaller
        payload is the serving hot path.  Wider shard sets keep
        per-shard counts and finish in int64 on host (engine int32
        policy)."""
        small = len(ps.shards) <= self._REDUCE_SHARD_MAX
        delta = ps.delta
        if self.batcher is not None and small:
            totals = self.batcher.submit_rowcounts(
                ps.plane, delta=delta, deadline=self._query_deadline())
            if timer is not None:
                timer.mark("read")  # coalesced wait: window+dispatch+read
            return totals
        if small:
            if delta is not None:
                out = self.fused.run_rowcounts_delta(ps.plane, delta)
            else:
                key = (("countbatch-plane-reduced", ps.plane.shape),
                       "count")
                fn = self.fused._cached(
                    key, lambda: (lambda p: jnp.sum(
                        kernels.row_counts(p), axis=0, dtype=jnp.int32)))
                out = fn(ps.plane)
            if timer is not None:
                timer.mark("dispatch")
            totals = np.asarray(out).astype(np.int64)  # one read
            if timer is not None:
                timer.mark("read")
        else:
            if delta is not None:
                out = self.fused.run_rowcounts_delta(ps.plane, delta,
                                                     reduce=False)
            else:
                key = (("countbatch-plane", ps.plane.shape), "count")
                fn = self.fused._cached(key, lambda: kernels.row_counts)
                out = fn(ps.plane)
            if timer is not None:
                timer.mark("dispatch")
            host = np.asarray(out).astype(np.int64)
            if timer is not None:
                timer.mark("read")
            totals = host.sum(axis=0)
        return totals

    # ---------------------------------------------------------- plan cache

    def invalidate_plans(self, index: str | None = None) -> None:
        """Drop cached plans (all, or one index's) — schema deletions
        must not leave plans resolving against a recreated namesake."""
        with self._plans_lock:
            if index is None:
                self._plans.clear()
                return
            for key in [k for k in self._plans if k[0] == index]:
                del self._plans[key]

    def _execute_planned(self, index, index_name: str, query: str, shards,
                         translate_output: bool, tracer,
                         deadline: float | None, timer) -> list | None:
        """Plan-cache fast path for all-Count queries (the dominant
        serving family).  Returns the results list, or None to fall
        through to the parse path (unplannable shape, stale entry, or
        a plane that isn't resident — admission decisions stay on the
        un-cached path)."""
        # strip() only — whitespace INSIDE the query can be inside a
        # quoted row key, where collapsing it would alias two distinct
        # queries onto one plan (wrong answers, not a perf bug)
        skey = (index_name, query.strip(),
                tuple(shards) if shards is not None else None,
                translate_output)
        with self._plans_lock:
            entry = self._plans.get(skey)
            if entry is not None:
                self._plans.move_to_end(skey)
        if entry is _UNPLANNABLE:
            return None
        if entry is None:
            self.stats.count("plan_cache_misses", 1)
            # build TWICE and require identical generation snapshots:
            # generations are monotonic, so equal snapshots bracket the
            # second walk — a write racing the build (e.g. creating a
            # row the first walk resolved as absent, THEN snapshotting
            # the post-write generations) cannot produce a stale plan
            # that validates as fresh.  Under hot writes we just don't
            # cache this request; the normal path serves it.
            first = self._build_plan(index, query, shards,
                                     translate_output)
            entry = None
            if first is not None:
                second = self._build_plan(index, query, shards,
                                          translate_output)
                if second is not None and second.gens == first.gens:
                    entry = second
            if first is not None and entry is None:
                return None  # racing writes: retry on the next request
            with self._plans_lock:
                self._plans[skey] = (entry if entry is not None
                                     else _UNPLANNABLE)
                while len(self._plans) > self.MAX_PLANS:
                    self._plans.popitem(last=False)
            if entry is None:
                return None
        else:
            self.stats.count("plan_cache_hits", 1)
        # validity: current shard set + dependency generations must
        # match what the plan was built against — a write to any source
        # fragment (or a shard appearing) invalidates here, and the
        # normal path re-plans on the next request.  Unkeyed-plane
        # entries skip the generation compare (nothing in them can
        # stale; the PlaneSet revalidates independently) so the plan
        # cache keeps hitting under sustained ingest.
        if (self._shards_for(index, shards, None) != entry.shards
                or (not entry.unkeyed_plane
                    and self._dep_gens(index, entry.deps,
                                       entry.shards) != entry.gens)
                or (entry.unkeyed_plane
                    # every baked field must still be the unkeyed set
                    # field the plan resolved literal row ids against
                    # — a drop + recreate as keyed/BSI at the same
                    # name would otherwise keep serving those literals
                    and any((pf := index.field(fn)) is None
                            or pf.options.keys
                            or pf.options.type in BSI_TYPES
                            for fn in entry.unkeyed_fields))
                or any((f := index.field(fname)) is None
                       or _bsi_signature(f.options) != sig
                       for fname, sig in entry.bsi_sigs)):
            self._drop_plan(skey, entry)
            return None
        return self._run_plan(index, index_name, entry, translate_output,
                              tracer, deadline, timer)

    def _drop_plan(self, skey, entry) -> None:
        self.stats.count("plan_cache_invalidations", 1)
        with self._plans_lock:
            if self._plans.get(skey) is entry:
                del self._plans[skey]

    def _build_plan(self, index, query: str, shards,
                    translate_output: bool) -> "_PlanEntry | None":
        from pilosa_tpu.exec.fused import Unfusable
        try:
            query_ast = parse_cached(query)
        except Exception:  # noqa: BLE001 — errors surface on normal path
            return None
        calls = query_ast.calls
        if not calls or any(c.name != "Count" or len(c.children) != 1
                            for c in calls):
            return None
        ctx = _Ctx(index, self._shards_for(index, shards, None),
                   translate_output)
        try:
            entry = self._plan_plane_entry(ctx, calls)
            if entry is not None:
                return entry
            entry = self._plan_bsirange_entry(ctx, calls)
            if entry is not None:
                return entry
            entry = self._plan_tree_entry(ctx, calls)
            if entry is not None:
                return entry
            specs: list = []
            deps: dict[tuple, None] = {}
            depths: dict[str, tuple] = {}
            nodes = []
            for call in calls:
                nodes.append(self._plan_spec(ctx, call.children[0],
                                             specs, deps, depths))
        except (Unfusable, ExecutionError):
            # execution errors re-raise identically on the normal path;
            # a later schema change that would make the query plannable
            # is served (correctly) by the normal path forever — a
            # perf-only conservatism
            return None
        deps = tuple(deps)
        return _PlanEntry("generic", ctx.shards, deps,
                          self._dep_gens(index, deps, ctx.shards),
                          len(calls), nodes=tuple(nodes),
                          leaf_specs=tuple(specs),
                          bsi_sigs=tuple(depths.items()))

    def _plan_plane_entry(self, ctx: _Ctx, calls) -> "_PlanEntry | None":
        """Match the same-field plain-row batch shape that
        :meth:`_count_batch_plane` serves with ONE whole-plane program
        (the BENCH headline family)."""
        fname = None
        values = []
        for call in calls:
            child = call.children[0]
            if child.name != "Row" or child.children:
                return None
            hit = _field_arg(child)
            if hit is None:
                return None
            f, v = hit
            if isinstance(v, (Condition, Call)):
                return None
            if ("from" in child.args or "to" in child.args
                    or "_timestamp" in child.args):
                return None
            if fname is None:
                fname = f
            elif f != fname:
                return None
            values.append(v)
        if fname is None or not ctx.shards:
            return None
        field = ctx.index.field(str(fname))
        if field is None or field.options.type in BSI_TYPES:
            return None
        row_ids = tuple(
            int(r) if (r := self._row_id(ctx, field, v,
                                         create=False)) is not None else None
            for v in values)
        deps = ((field.name, VIEW_STANDARD),)
        return _PlanEntry("plane", ctx.shards, deps,
                          self._dep_gens(ctx.index, deps, ctx.shards),
                          len(calls), field_name=field.name,
                          row_ids=row_ids,
                          unkeyed_plane=not field.options.keys,
                          unkeyed_fields=(field.name,))

    def _plan_bsirange_entry(self, ctx: _Ctx,
                             calls) -> "_PlanEntry | None":
        """Match an all-BSI-range-count request (r20): the entry bakes
        only (field, op keys, offsets) — the plane arrives delta-aware
        per hit and the predicate masks re-derive from the pinned
        depth, so the entry SURVIVES sustained ingest (no per-hit
        generation compare; ``bsi_sigs`` re-verifies depth/base)."""
        if self.batcher is None or not ctx.shards:
            return None
        if len(ctx.shards) > self._REDUCE_SHARD_MAX:
            return None
        items = []
        sigs: dict[str, tuple] = {}
        deps: dict[tuple, None] = {}
        for call in calls:
            it = self._bsirange_item(ctx, call.children[0])
            if it is None:
                return None
            field, op_keys, offsets = it
            # operands baked DEVICE-resident (like the generic plan's
            # const leaves): masks depend only on offset and the
            # depth the bsi_sigs check pins, so a cache hit re-binds
            # zero operands
            items.append((field.name, op_keys, offsets,
                          self._bsirange_operands(field, offsets)))
            sigs[field.name] = _bsi_signature(field.options)
            deps[(field.name, field.bsi_view_name)] = None
        deps = tuple(deps)
        return _PlanEntry("bsirange", ctx.shards, deps,
                          self._dep_gens(ctx.index, deps, ctx.shards),
                          len(calls), range_items=tuple(items),
                          bsi_sigs=tuple(sigs.items()),
                          unkeyed_plane=True)

    def _plan_tree_entry(self, ctx: _Ctx, calls) -> "_PlanEntry | None":
        """Tree-shaped plans (r16): every Count child lowers to a
        canonical :class:`exec.tree.TreeSpec` — the plan cache's unit
        for arbitrary compound shapes.  Survival under writes mirrors
        the r15 unkeyed-plane rule: literal-int rows over unkeyed set
        fields re-resolve against planes that absorb writes into
        delta overlays (BSI predicates re-derive from the depth the
        ``bsi_sigs`` check pins; exists/other-field rows re-fetch
        fresh), so such entries skip the per-hit generation compare
        and parse+plan stays off every request under sustained
        ingest.  Keyed rows and data-dependent row sets (UnionRows)
        stay generation-checked."""
        from pilosa_tpu.exec import tree as treemod
        from pilosa_tpu.exec.fused import Unfusable
        if not self.tree_fusion or not ctx.shards:
            return None
        if not any(c.children[0].name in treemod.TREE_CALLS
                   for c in calls):
            return None
        try:
            specs = tuple(treemod.lower_count_tree(self, ctx,
                                                   c.children[0])
                          for c in calls)
        except Unfusable:
            return None
        index = ctx.index
        deps: dict[tuple, None] = {}
        sigs: dict[str, tuple] = {}
        set_fields: dict[str, None] = {}
        survivable = True
        for spec in specs:
            set_fields[spec.field] = None
            deps[(spec.field, VIEW_STANDARD)] = None
            if spec.volatile or spec.keyed_rows:
                survivable = False
            for fname, _depth in spec.bsi_depths:
                f = index.field(fname)
                if f is None:
                    return None
                sigs[fname] = _bsi_signature(f.options)
                deps[(fname, f.bsi_view_name)] = None
            for espec in spec.extras:
                if espec[0] == "exists":
                    deps[("\x00exists", VIEW_STANDARD)] = None
                elif espec[0] == "row":
                    set_fields[espec[1]] = None
                    deps[(espec[1], espec[2])] = None
                elif espec[0] == "trange":
                    # every timestamped write also lands in the
                    # standard view (store.field fan-out), so its
                    # generations are a faithful write proxy for the
                    # bucket views; the cover itself is re-derived per
                    # hit, but new VIEWS appearing (first write in a
                    # fresh period) don't bump generations the entry
                    # tracks — stay generation-checked, not survivable
                    deps[(espec[1], VIEW_STANDARD)] = None
                    survivable = False
                elif espec[0] == "constrow":
                    pass  # literal columns: nothing to depend on
        for fname in set_fields:
            f = index.field(fname)
            if f is None:
                return None
            if f.options.keys:
                survivable = False
        deps = tuple(deps)
        return _PlanEntry("tree", ctx.shards, deps,
                          self._dep_gens(index, deps, ctx.shards),
                          len(calls), tree_specs=specs,
                          bsi_sigs=tuple(sigs.items()),
                          unkeyed_plane=survivable,
                          unkeyed_fields=tuple(set_fields))

    def _dep_gens(self, index, deps: tuple, shards: tuple) -> tuple:
        out = []
        for fname, vname in deps:
            f = (index.existence_field if fname == "\x00exists"
                 else index.field(fname))
            view = f.views.get(vname) if f is not None else None
            out.append(view.generations_fast(shards)
                       if view is not None else None)
        return tuple(out)

    def _plan_spec(self, ctx: _Ctx, call: Call, specs: list,
                   deps: dict, depths: dict):
        """Mirror of :meth:`_plan` that records hashable LEAF SPECS
        instead of arrays — the cached form re-materializes through
        the plane cache on every hit (arrays are never cached here;
        predicate masks, which are pure functions of the query text,
        are)."""
        from pilosa_tpu.exec.fused import Unfusable
        name = call.name

        def leaf(spec) -> tuple:
            specs.append(spec)
            return ("leaf", len(specs) - 1)

        if name in ("Row", "Range"):
            hit = _field_arg(call)
            if hit is None:
                raise ExecutionError(f"{name}: missing field argument")
            fname, value = hit
            field = self._field(ctx, fname)
            if isinstance(value, Condition) \
                    or field.options.type in BSI_TYPES:
                cond = (value if isinstance(value, Condition)
                        else Condition("==", value))
                return self._plan_spec_bsi(ctx, field, cond, specs, deps,
                                           depths, leaf)
            if ("from" in call.args or "to" in call.args
                    or "_timestamp" in call.args):
                raise Unfusable("time-range rows are not plan-cached")
            deps[(field.name, VIEW_STANDARD)] = None
            row_id = self._row_id(ctx, field, value, create=False)
            if row_id is None:
                return leaf(("zeros",))
            return leaf(("row", field.name, VIEW_STANDARD, int(row_id)))
        if name == "All":
            deps[("\x00exists", VIEW_STANDARD)] = None
            return leaf(("exists",))
        from pilosa_tpu.exec.tree import fold_bool_call, is_not_bool

        def exists_spec() -> int:
            deps[("\x00exists", VIEW_STANDARD)] = None
            specs.append(("exists",))
            return len(specs) - 1

        out = fold_bool_call(
            call,
            recurse=lambda c: self._plan_spec(ctx, c, specs, deps,
                                              depths),
            zeros=lambda: leaf(("zeros",)),
            exists=exists_spec,
            combine=lambda op, kids: (op, tuple(k() for k in kids)),
            complement=lambda exists, child:
                (lambda ch: ("not", ch, exists()))(child()))
        if not is_not_bool(out):
            return out
        if name == "Shift":
            if len(call.children) != 1:
                raise ExecutionError("Shift: exactly one child required")
            n = self._shift_n(call)
            return ("shift",
                    self._plan_spec(ctx, call.children[0], specs, deps,
                                    depths), n)
        raise Unfusable(f"{name} is not plan-cached")

    def _plan_spec_bsi(self, ctx: _Ctx, field: Field, cond: Condition,
                       specs: list, deps: dict, depths: dict, leaf):
        if field.options.type not in BSI_TYPES:
            raise ExecutionError(
                f"field {field.name!r}: condition on non-BSI field")
        deps[(field.name, field.bsi_view_name)] = None
        depths[field.name] = _bsi_signature(field.options)
        if cond.op in BETWEEN_OPS:
            lo_op, hi_op = between_cmp_ops(cond.op)
            lo = self._plan_spec_bsi_cmp(field, lo_op, cond.value[0],
                                         specs, leaf)
            hi = self._plan_spec_bsi_cmp(field, hi_op, cond.value[1],
                                         specs, leaf)
            return ("and", (lo, hi))
        return self._plan_spec_bsi_cmp(field, _SCALAR_TO_KEY[cond.op],
                                       cond.value, specs, leaf)

    def _plan_spec_bsi_cmp(self, field: Field, op_key: str, value,
                           specs: list, leaf):
        opts = field.options
        depth = opts.bit_depth
        offset = field.to_stored(value) - opts.base
        bound = (1 << depth) - 1
        if offset > bound or offset < -bound:
            all_hit = ((op_key in ("lt", "le", "ne")) if offset > bound
                       else (op_key in ("gt", "ge", "ne")))
            # depth growth (which shifts the saturation bound) bumps the
            # bsi view's generations, invalidating the entry
            return leaf(("bsi-exists", field.name, bool(all_hit)))
        specs.append(("bsi-plane", field.name))
        i_plane = len(specs) - 1
        specs.append(("const",
                      jnp.asarray(bsik.predicate_masks(abs(offset), depth))))
        i_masks = len(specs) - 1
        specs.append(("const", jnp.asarray(offset < 0)))
        i_neg = len(specs) - 1
        return ("bsi", i_plane, i_masks, i_neg, op_key)

    def _leaves_from_specs(self, ctx: _Ctx, specs: tuple) -> list | None:
        """Materialize plan-cached leaf specs through the plane cache
        (each fetch revalidates its own generations).  None = a spec no
        longer resolves (field gone) — caller invalidates."""
        out: list = []
        bsi_cache: dict = {}
        for spec in specs:
            kind = spec[0]
            if kind == "row":
                _, fname, vname, rid = spec
                field = ctx.index.field(fname)
                if field is None:
                    return None
                out.append(self.planes.row_words(ctx.index.name, field,
                                                 vname, rid, ctx.shards))
            elif kind == "zeros":
                out.append(self._zeros(ctx))
            elif kind == "exists":
                out.append(self._exists(ctx))
            elif kind == "const":
                out.append(spec[1])
            else:  # "bsi-plane" | "bsi-exists"
                fname = spec[1]
                ps = bsi_cache.get(fname)
                if ps is None:
                    field = ctx.index.field(fname)
                    if field is None or field.options.type not in BSI_TYPES:
                        return None
                    ps = self.planes.bsi_plane(ctx.index.name, field,
                                               ctx.shards)
                    bsi_cache[fname] = ps
                if kind == "bsi-plane":
                    out.append(ps.plane)
                else:
                    exists = ps.plane[..., bsik.EXISTS_ROW, :]
                    out.append(exists if spec[2]
                               else jnp.zeros_like(exists))
        return out

    def _run_plan(self, index, index_name: str, entry: "_PlanEntry",
                  translate_output: bool, tracer,
                  deadline: float | None, timer) -> list | None:
        """Run a validated plan; None = not runnable right now (plane
        not resident) — the caller falls through to the normal path,
        keeping admission decisions there."""
        ctx = _Ctx(index, entry.shards, translate_output,
                   deadline=deadline)
        ctx.check_deadline()
        tracer = tracer or self.tracer
        with tracer.span("executor.PlanCached", index=index_name,
                         calls=entry.n_calls, shards=len(ctx.shards)):
            t0 = time.perf_counter()
            out = self._with_oom_retry(
                lambda: self._run_plan_inner(ctx, entry, timer))
            if out is not None:
                self.stats.timing("query_seconds",
                                  time.perf_counter() - t0,
                                  call="CountBatch")
        return out

    def _run_plan_inner(self, ctx: _Ctx, entry: "_PlanEntry",
                        timer) -> list | None:
        if entry.kind == "tree":
            if not self.tree_fusion:  # knob flipped after caching
                return None
            out = self._run_tree_specs(ctx, list(entry.tree_specs),
                                       timer)
            if out is not None and timer is not None:
                timer.mark("assemble")
            return out
        if entry.kind == "bsirange":
            if self.batcher is None:  # knob flipped after caching
                return None
            items = []
            for fname, op_keys, offsets, operands in entry.range_items:
                field = ctx.index.field(fname)
                if field is None:
                    return None
                items.append((field, op_keys, offsets, operands))
            out = self._run_bsirange_items(ctx, items, timer)
            if timer is not None:
                timer.mark("assemble")
            return out
        if entry.kind == "plane":
            field = ctx.index.field(entry.field_name)
            if field is None:
                return None
            # residency only — admission (budget walks) stays on the
            # un-cached path, exactly like _count_batch_plane
            if not self.planes.has_plane(ctx.index.name, field,
                                         VIEW_STANDARD, ctx.shards):
                return None
            ps = self.planes.field_plane_nowait(ctx.index.name, field,
                                                VIEW_STANDARD, ctx.shards)
            if ps is None:
                return None
            if timer is not None:
                timer.mark("plan")
            out = self._plane_count_rows(ps, entry.row_ids, timer)
            if timer is not None:
                timer.mark("assemble")
            return out
        leaves = self._leaves_from_specs(ctx, entry.leaf_specs)
        if leaves is None:
            return None
        if timer is not None:
            timer.mark("plan")
        out = self._dispatch_count_run(entry.nodes, tuple(leaves), timer)
        if timer is not None:
            timer.mark("assemble")
        return out

    def _shards_for(self, index: Index, shards,
                    call: Call | None) -> tuple[int, ...]:
        opts = (call.args.get("shards")
                if call is not None and call.name == "Options" else None)
        if opts is not None:
            out = tuple(int(s) for s in opts)
        elif shards is not None:
            out = tuple(shards)
        else:
            avail = index.available_shards()
            out = tuple(avail) if avail else (0,)
        if self.placement is not None:
            out = self.placement.pad_shards(out)
        return out

    # ------------------------------------------------------------- dispatch

    def _call(self, ctx: _Ctx, call: Call):
        if call.name == "Options":
            if len(call.children) != 1:
                raise ExecutionError("Options: exactly one child required")
            result = self._call(ctx, call.children[0])
            # columnAttrs=true attaches column attribute maps to a row
            # result (reference: QueryRequest.ColumnAttrs)
            if call.args.get("columnAttrs") and isinstance(result, RowResult):
                store = ctx.index.column_attrs
                result.attrs = {int(c): a for c, a in
                                zip(result.columns,
                                    store.attrs_many(result.columns))
                                if a}
            if call.args.get("excludeColumns") and isinstance(result,
                                                             RowResult):
                # reference: QueryRequest.ExcludeColumns — materialize
                # nothing columnar in the response
                result.columns = np.empty(0, np.uint64)
                if result.keys is not None:
                    result.keys = []
            return result
        if call.name in _BITMAP_CALLS:
            words = self._fused_bitmap(ctx, call)
            result = self._to_row_result(ctx, words)
            if call.name == "Row":
                self._attach_row_attrs(ctx, call, result)
            if call.name == "All":
                # All(limit=, offset=) pages the column list (v2 parity)
                offset = int(call.args.get("offset", 0))
                limit = call.args.get("limit")
                if offset or limit is not None:
                    end = None if limit is None else offset + int(limit)
                    result.columns = result.columns[offset:end]
                    if result.keys is not None:
                        result.keys = result.keys[offset:end]
            return result
        handler = getattr(self, "_execute_" + call.name.lower(), None)
        if handler is None:
            raise ExecutionError(f"unknown call {call.name!r}")
        return handler(ctx, call)

    def _with_oom_retry(self, fn):
        """Run ``fn``; on device RESOURCE_EXHAUSTED, recover in stages
        that coordinate across concurrent queries (r5 redesign of the
        r4 evict-all-and-retry, which thrashed under concurrent
        over-budget load: two queries needing disjoint residency would
        ping-pong global eviction, and a second OOM propagated as 500).

        Stage 1 (serialized by the gate): evict only UNPINNED planes —
        entries no in-flight query leases — and retry.  Evicting leased
        planes frees no HBM (the queries' frames hold live refs) and
        forces mid-flight rebuilds, so they stay.

        Stage 2 (still under the gate): drain to exclusivity — wait for
        every other query to finish or park at the gate (parked queries
        leave the in-flight count, so this cannot deadlock; their OOM
        unwound their device refs already), then drop ALL residency and
        run alone.  At most 3 attempts per query, one recovery at a
        time: no retry storm.

        Covers EVERY execute path — fused count batches and bitmap fast
        paths included, not just per-call handlers."""
        try:
            if fault.ACTIVE:
                # `oom` raises the RESOURCE_EXHAUSTED shape this very
                # wrapper classifies — injected device OOM drives the
                # real staged recovery below, not a simulation of it
                fault.fire("exec.oom")
            return fn()
        except Exception as e:  # noqa: BLE001 — filtered below
            if not _is_device_oom(e):
                raise
        import gc
        self.stats.count("device_oom_retries", 1)
        # park OUTSIDE the in-flight count while waiting for the gate:
        # the active recovery may need to drain to exclusivity, and a
        # queue of OOM'd queries still counted in-flight would wedge it
        self._leave_inflight()
        try:
            with self._oom_gate:
                self._enter_inflight()
                try:
                    self.planes.evict_unpinned()
                    gc.collect()
                    try:
                        return fn()
                    except Exception as e:  # noqa: BLE001
                        if not _is_device_oom(e):
                            raise
                    self.stats.count("device_oom_exclusive_retries", 1)
                    self._recovery_open.clear()  # park new arrivals
                    try:
                        self._drain_to_exclusive()
                        # background plane builds hold device memory the
                        # cache can't see yet — join them before the
                        # exclusive retry sizes itself against free HBM
                        self.planes.wait_builds()
                        self.planes.invalidate()
                        gc.collect()
                        return fn()
                    finally:
                        self._recovery_open.set()
                finally:
                    self._leave_inflight()
        finally:
            self._enter_inflight()

    def _attach_row_attrs(self, ctx: _Ctx, call: Call,
                          result: "RowResult") -> None:
        """A plain ``Row(field=row)`` result carries the row's
        attributes (reference: v1 ``Row.Attrs`` in the JSON response;
        suppressed with ``excludeRowAttrs=true``)."""
        if call.args.get("excludeRowAttrs"):
            return
        hit = _field_arg(call)
        if hit is None:
            return
        fname, value = hit
        if isinstance(value, (Condition, Call)):
            return
        field = ctx.index.field(str(fname))
        if field is None or field.options.type in BSI_TYPES:
            return
        if not field.has_row_attrs:  # never CREATE a store on a read
            return
        row_id = self._row_id(ctx, field, value, create=False)
        if row_id is None:
            return
        attrs = field.row_attrs.attrs(int(row_id))
        if attrs:
            result.row_attrs = {str(k): v for k, v in attrs.items()}

    # -- bitmap calls -------------------------------------------------------

    def _fused_bitmap(self, ctx: _Ctx, call: Call, want: str = "words"):
        """Evaluate a bitmap call tree as ONE compiled program (SURVEY.md
        §8 "one compiled function per call-shape"); falls back to the
        eager per-op path for shapes the planner doesn't cover."""
        from pilosa_tpu.exec.fused import Unfusable
        from pilosa_tpu.exec import tree as treemod
        if (self.tree_fusion and ctx.shards
                and call.name in treemod.TREE_CALLS):
            # bitmap-valued compound trees ride the whole-tree program
            # too: one in-program gather from the resident plane, one
            # postfix fold — no per-leaf arrays (r16)
            hit = None
            try:
                spec = treemod.lower_count_tree(self, ctx, call)
                hit = self._tree_item(ctx, spec)
            except Unfusable:
                hit = None
            if hit is not None:
                ps, (slots, prog, extras) = hit
                self._tree_stats(spec)
                words = self.fused.run_tree_words(
                    ps.plane, slots, prog, extras, delta=ps.delta)
                if want == "count":
                    return kernels.count(words)
                return words
        try:
            leaves: list = []
            node = self._plan(ctx, call, leaves)
        except Unfusable:
            words = self._bitmap(ctx, call)
            if want == "count":
                return kernels.count(words)
            return words
        return self.fused.run(node, tuple(leaves), want)

    def _plan(self, ctx: _Ctx, call: Call, leaves: list):
        """Mirror of :meth:`_bitmap` that collects leaf arrays and
        returns a hashable structure tree for the fused compiler."""
        name = call.name

        def leaf(arr) -> tuple:
            leaves.append(arr)
            return ("leaf", len(leaves) - 1)

        if name in ("Row", "Range"):
            return self._plan_row(ctx, call, leaves, leaf)
        if name == "All":
            return leaf(self._exists(ctx))
        from pilosa_tpu.exec.tree import fold_bool_call, is_not_bool

        def exists_leaf() -> int:
            leaves.append(self._exists(ctx))
            return len(leaves) - 1

        out = fold_bool_call(
            call,
            recurse=lambda c: self._plan(ctx, c, leaves),
            zeros=lambda: leaf(self._zeros(ctx)),
            exists=exists_leaf,
            # ONE flat n-ary node — a nested pair per child would
            # recurse once per child in _build/shift_leaves and blow
            # the recursion limit on wide flat Unions
            combine=lambda op, kids: (op, tuple(k() for k in kids)),
            complement=lambda exists, child:
                (lambda ch: ("not", ch, exists()))(child()))
        if not is_not_bool(out):
            return out
        if name == "Shift":
            if len(call.children) != 1:
                raise ExecutionError("Shift: exactly one child required")
            n = self._shift_n(call)
            return ("shift", self._plan(ctx, call.children[0], leaves), n)
        if name == "UnionRows":
            # UnionRows(Rows(f)): OR of every row the Rows call selects
            # (reference: v2 executeUnionRows)
            return leaf(self._union_rows(ctx, call))
        if name == "ConstRow":
            return leaf(self._const_row(ctx, call))
        if name == "Limit":
            # order-based truncation needs a host column pass; keep it
            # out of the fused program (the caller falls back to eager)
            from pilosa_tpu.exec.fused import Unfusable
            raise Unfusable("Limit is host-ordered")
        raise ExecutionError(f"not a bitmap call: {name}")

    def _const_row(self, ctx: _Ctx, call: Call) -> jax.Array:
        """ConstRow(columns=[...]): a literal bitmap (reference: v2
        ``executeConstRow``).  Unknown keys resolve to nothing.
        Columns whose shard is outside the queried shard set drop —
        execution is per-shard over the index's shards, exactly as a
        ConstRow column in a data-less shard drops upstream."""
        cols = call.args.get("columns")
        if cols is None:
            raise ExecutionError("ConstRow: missing columns argument")
        return self._const_row_cols(ctx, cols)

    def _const_row_cols(self, ctx: _Ctx, cols) -> jax.Array:
        host = np.zeros((len(ctx.shards), WORDS_PER_SHARD), np.uint32)
        shard_slot = {s: si for si, s in enumerate(ctx.shards)}
        for c in cols:
            cid = self._col_id(ctx, c, create=False)
            if cid is None:
                continue
            si = shard_slot.get(cid // SHARD_WIDTH)
            if si is None:
                continue
            off = cid % SHARD_WIDTH
            host[si, off >> 5] |= np.uint32(1) << np.uint32(off & 31)
        return self.planes.place(host)

    def _limit_bitmap(self, ctx: _Ctx, call: Call) -> jax.Array:
        """Limit(bitmap, limit=, offset=): truncate the ascending column
        list (reference: v2 ``executeLimitCall``) — inherently ordered,
        so the column set round-trips through the host."""
        if len(call.children) != 1:
            raise ExecutionError("Limit: exactly one bitmap child required")
        offset = int(call.args.get("offset", 0))
        limit = call.args.get("limit")
        if offset < 0 or (limit is not None and int(limit) < 0):
            raise ExecutionError("Limit: limit/offset must be >= 0")
        words = self._fused_bitmap(ctx, call.children[0])
        end = None if limit is None else offset + int(limit)
        if end is None:
            host = np.asarray(words)
            n_shards = len(ctx.shards)
        else:
            # push the truncation down: per-shard popcounts (one tiny
            # device read) say how many leading shards can contain the
            # first offset+limit columns — read and unpack ONLY those.
            # (Unbounded materialization of a 25% row at 1B cols cost
            # ~70 s/call on this host: 125 MB read + 250M-column
            # unpack/concat for a limit=1000 answer — config16 r5.)
            counts = np.asarray(_shard_popcounts(words))
            cum = np.cumsum(counts)
            n_shards = int(np.searchsorted(cum, end)) + 1
            n_shards = min(n_shards, len(ctx.shards))
            host = np.asarray(words[:n_shards])
        parts = [offs.astype(np.uint64) + np.uint64(s * SHARD_WIDTH)
                 for _, s, offs in self._shard_offsets(
                     ctx, host, limit_shards=n_shards)]
        all_cols = (np.concatenate(parts) if parts
                    else np.empty(0, np.uint64))
        sel = all_cols[offset:end]
        out = np.zeros((len(ctx.shards), WORDS_PER_SHARD), np.uint32)
        if len(sel):
            shard_slot = {s: si for si, s in enumerate(ctx.shards)}
            si_arr = np.array([shard_slot[int(c) // SHARD_WIDTH]
                               for c in sel])
            offs = (sel % np.uint64(SHARD_WIDTH)).astype(np.int64)
            np.bitwise_or.at(
                out, (si_arr, offs >> 5),
                (np.uint32(1) << (offs & 31).astype(np.uint32)))
        return self.planes.place(out)

    @staticmethod
    def _shift_n(call: Call) -> int:
        try:
            n = int(call.args.get("n", 1))
        except (TypeError, ValueError):
            raise ExecutionError(f"Shift: bad n {call.args.get('n')!r}")
        if not 0 <= n < SHARD_WIDTH:
            raise ExecutionError(f"Shift: n must be in [0, 2^20), got {n}")
        return n

    def _union_rows(self, ctx: _Ctx, call: Call) -> jax.Array:
        bad = [c.name for c in call.children if c.name != "Rows"]
        if bad:
            raise ExecutionError(
                f"UnionRows: children must be Rows calls, got {bad}")
        rows_calls = call.children
        if not rows_calls:
            raise ExecutionError("UnionRows: Rows children required")
        acc = self._zeros(ctx)
        for rc in rows_calls:
            fname = rc.args.get("_field") or rc.args.get("field")
            field = self._field(ctx, str(fname))
            rows = self._rows_of(ctx, field, rc)
            if len(rows) == 0:
                continue
            # plane over the SELECTED rows only (memory bounded by the
            # selection, not the field's row cardinality)
            ps = self.planes.rows_plane(ctx.index.name, field,
                                        VIEW_STANDARD, rows, ctx.shards)
            mask = np.zeros(ps.plane.shape[-2], dtype=bool)
            mask[:len(rows)] = True
            acc = kernels.union(acc, kernels.union_rows(
                ps.plane, jnp.asarray(mask)))
        return acc

    def _plan_row(self, ctx: _Ctx, call: Call, leaves: list, leaf):
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError(f"{call.name}: missing field argument")
        fname, value = hit
        field = self._field(ctx, fname)
        if isinstance(value, Condition) or field.options.type in BSI_TYPES:
            cond = (value if isinstance(value, Condition)
                    else Condition("==", value))
            return self._plan_bsi(ctx, field, cond, leaves, leaf)
        row_id = self._row_id(ctx, field, value, create=False)
        if row_id is None:
            return leaf(self._zeros(ctx))
        if ("from" in call.args or "to" in call.args
                or "_timestamp" in call.args):
            # time-range rows stay eager (variable view counts would
            # explode the program cache); wrap the result as one leaf
            return leaf(self._time_row(ctx, field, row_id, call))
        return leaf(self.planes.row_words(ctx.index.name, field,
                                          VIEW_STANDARD, row_id, ctx.shards))

    def _plan_bsi(self, ctx: _Ctx, field: Field, cond: Condition,
                  leaves: list, leaf):
        if field.options.type not in BSI_TYPES:
            raise ExecutionError(
                f"field {field.name!r}: condition on non-BSI field")
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        if cond.op in BETWEEN_OPS:
            lo_op, hi_op = between_cmp_ops(cond.op)
            lo = self._plan_bsi_cmp(ctx, field, ps, lo_op, cond.value[0],
                                    leaves, leaf)
            hi = self._plan_bsi_cmp(ctx, field, ps, hi_op, cond.value[1],
                                    leaves, leaf)
            return ("and", (lo, hi))
        return self._plan_bsi_cmp(ctx, field, ps,
                                  _SCALAR_TO_KEY[cond.op], cond.value,
                                  leaves, leaf)

    def _plan_bsi_cmp(self, ctx: _Ctx, field: Field, ps, op_key: str,
                      value, leaves: list, leaf):
        opts = field.options
        depth = opts.bit_depth
        offset = field.to_stored(value) - opts.base
        bound = (1 << depth) - 1
        if offset > bound or offset < -bound:
            # saturated: trivially everything-not-null or nothing
            exists = ps.plane[..., bsik.EXISTS_ROW, :]
            all_hit = (op_key in ("lt", "le", "ne")) if offset > bound \
                else (op_key in ("gt", "ge", "ne"))
            return leaf(exists if all_hit else jnp.zeros_like(exists))
        leaves.append(ps.plane)
        i_plane = len(leaves) - 1
        leaves.append(jnp.asarray(bsik.predicate_masks(abs(offset), depth)))
        i_masks = len(leaves) - 1
        leaves.append(jnp.asarray(offset < 0))
        i_neg = len(leaves) - 1
        return ("bsi", i_plane, i_masks, i_neg, op_key)

    def _bitmap(self, ctx: _Ctx, call: Call) -> jax.Array:
        """Evaluate a bitmap-valued call to uint32[n_shards, W]."""
        name = call.name
        if name == "Row" or name == "Range":  # Range is the legacy alias
            return self._row_bitmap(ctx, call)
        if name == "All":
            return self._exists(ctx)
        from pilosa_tpu.exec.tree import fold_bool_call, is_not_bool
        def eager_fold(op, kids):
            acc = kids[0]()
            for child in kids[1:]:
                acc = _EAGER_OPS[op](acc, child())
            return acc

        out = fold_bool_call(
            call,
            recurse=lambda c: self._bitmap(ctx, c),
            zeros=lambda: self._zeros(ctx),
            exists=lambda: self._exists(ctx),
            combine=eager_fold,
            complement=lambda exists, child: kernels.complement(
                child(), exists()))
        if not is_not_bool(out):
            return out
        kids = call.children
        if name == "Shift":
            if len(kids) != 1:
                raise ExecutionError("Shift: exactly one child required")
            return kernels.shift(self._bitmap(ctx, kids[0]),
                                 self._shift_n(call))
        if name == "UnionRows":
            return self._union_rows(ctx, call)
        if name == "ConstRow":
            return self._const_row(ctx, call)
        if name == "Limit":
            return self._limit_bitmap(ctx, call)
        raise ExecutionError(f"not a bitmap call: {name}")

    def _row_bitmap(self, ctx: _Ctx, call: Call) -> jax.Array:
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError(f"{call.name}: missing field argument")
        fname, value = hit
        field = self._field(ctx, fname)
        if isinstance(value, Condition):
            return self._bsi_condition(ctx, field, value)
        if field.options.type in BSI_TYPES:
            # Row(amount=5) on BSI ≡ amount == 5
            return self._bsi_condition(ctx, field, Condition("==", value))
        row_id = self._row_id(ctx, field, value, create=False)
        if row_id is None:
            return self._zeros(ctx)
        if ("from" in call.args or "to" in call.args
                or "_timestamp" in call.args):
            return self._time_row(ctx, field, row_id, call)
        return self.planes.row_words(ctx.index.name, field, VIEW_STANDARD,
                                     row_id, ctx.shards)

    def _time_row(self, ctx: _Ctx, field: Field, row_id: int,
                  call: Call) -> jax.Array:
        q = field.options.time_quantum
        if not q:
            raise ExecutionError(f"field {field.name!r} is not a time field")
        # legacy positional form: Range(f=1, <from-ts>, <to-ts>)
        frm = call.args.get("from", call.args.get("_timestamp"))
        to = call.args.get("to", call.args.get("_timestamp2"))
        start = parse_pql_time(str(frm)) if frm is not None else None
        end = parse_pql_time(str(to)) if to is not None else None
        words = self._time_range_words(ctx, field, row_id, start, end)
        if words is not None:
            return words
        return self._time_row_span(ctx, field, row_id, start, end)

    def _time_range_words(self, ctx: _Ctx, field: Field, row_id: int,
                          start, end) -> "jax.Array | None":
        """Fused time-range path (r23): answer ``row seen in [start,
        end)`` as ONE OR-scan over the contiguous bucket slot range of
        the field's resident :class:`timeviews.TimePlaneSet` —
        equivalent bit for bit to the mixed-granularity cover union
        (finest views carry every bit; ``tests/test_timeviews.py``
        pins it).  None = not runnable at device speed right now
        (degraded device, plane over budget / not built, too many
        shards) — the caller stays on the op-at-a-time span oracle."""
        if (self.batcher is not None
                and not self.batcher.governor.fastlane_ok()):
            return None
        if len(ctx.shards) > self._REDUCE_SHARD_MAX:
            return None
        tps = self.planes.time_plane_nowait(ctx.index.name, field,
                                            ctx.shards)
        if tps is None:
            return None
        idx = tps.slot_of.get(int(row_id))
        if idx is None:
            return self._zeros(ctx)
        b0, b1 = tps.bucket_range(start, end)
        if b1 <= b0:
            return self._zeros(ctx)
        self.stats.observe("time_range_cover_size", float(b1 - b0))
        return self.fused.run_time_range(
            tps.plane, idx * tps.n_buckets + b0, b1 - b0,
            delta=tps.delta)

    def _time_row_span(self, ctx: _Ctx, field: Field, row_id: int,
                       start, end) -> jax.Array:
        """Op-at-a-time time-range oracle: union one device row fetch
        per minimal-cover view.  Kept as the correctness oracle the
        fused path is pinned against and as the serving fallback when
        the time plane isn't residing (budget, degraded device)."""
        self._note_path("op-at-a-time fallback")
        q = field.options.time_quantum
        # clamp the range to the span actually covered by existing views:
        # an omitted bound would otherwise enumerate views unit-by-unit
        # across the whole calendar
        spans = []
        prefix = VIEW_STANDARD + "_"
        for vname in field.views:
            if vname.startswith(prefix):
                try:
                    spans.append(view_span(vname[len(prefix):]))
                except ValueError:
                    continue
        if not spans:
            return self._zeros(ctx)
        vmin = min(s for s, _ in spans)
        vmax = max(e for _, e in spans)
        start = vmin if start is None else max(start, vmin)
        end = vmax if end is None else min(end, vmax)
        acc = self._zeros(ctx)
        for vname in views_by_time_range(VIEW_STANDARD, start, end, q):
            if field.view(vname) is None:
                continue
            acc = kernels.union(acc, self.planes.row_words(
                ctx.index.name, field, vname, row_id, ctx.shards))
        return acc

    def _time_cover_views(self, field: Field, frm, to) -> list[str]:
        """Existing view names minimally covering a Rows/GroupBy time
        filter, with the oracle's span clamping — the shared answer to
        "which views can contribute rows in [from, to)"."""
        q = field.options.time_quantum
        if not q:
            raise ExecutionError(f"field {field.name!r} is not a time field")
        spans = []
        prefix = VIEW_STANDARD + "_"
        for vname in field.views:
            if vname.startswith(prefix):
                try:
                    spans.append(view_span(vname[len(prefix):]))
                except ValueError:
                    continue
        if not spans:
            return []
        vmin = min(s for s, _ in spans)
        vmax = max(e for _, e in spans)
        start = vmin if frm is None else max(parse_pql_time(str(frm)), vmin)
        end = vmax if to is None else min(parse_pql_time(str(to)), vmax)
        return [vname
                for vname in views_by_time_range(VIEW_STANDARD, start, end, q)
                if field.view(vname) is not None]

    def _bsi_condition(self, ctx: _Ctx, field: Field,
                       cond: Condition) -> jax.Array:
        if field.options.type not in BSI_TYPES:
            raise ExecutionError(
                f"field {field.name!r}: condition on non-BSI field")
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        if cond.op in BETWEEN_OPS:
            lo_op, hi_op = between_cmp_ops(cond.op)
            lo = self._bsi_cmp(field, ps, lo_op, cond.value[0])
            hi = self._bsi_cmp(field, ps, hi_op, cond.value[1])
            return kernels.intersect(lo, hi)
        return self._bsi_cmp(field, ps, _SCALAR_TO_KEY[cond.op], cond.value)

    def _bsi_cmp(self, field: Field, ps, op_key: str, value) -> jax.Array:
        """One signed comparison with out-of-depth predicate saturation
        (everything/nothing cases need no kernel; see
        ``engine.bsi.predicate_masks``)."""
        return self._bsi_cmp_offset(
            field, ps, op_key,
            field.to_stored(value) - field.options.base)

    def _bsi_cmp_offset(self, field: Field, ps, op_key: str,
                        offset: int) -> jax.Array:
        """Comparison against a base-relative stored offset (used by
        Percentile's binary search, which walks stored space directly)."""
        opts = field.options
        depth = opts.bit_depth
        exists = ps.plane[..., bsik.EXISTS_ROW, :]
        bound = (1 << depth) - 1
        if offset > bound:
            if op_key in ("lt", "le", "ne"):
                return exists
            return jnp.zeros_like(exists)
        if offset < -bound:
            if op_key in ("gt", "ge", "ne"):
                return exists
            return jnp.zeros_like(exists)
        masks = bsik.predicate_masks(abs(offset), depth)
        cmp = bsik.range_cmp(ps.plane, jnp.asarray(masks),
                             jnp.asarray(offset < 0))
        return cmp[op_key]

    # -- helpers ------------------------------------------------------------

    def _field(self, ctx: _Ctx, name: str) -> Field:
        field = ctx.index.field(name)
        if field is None:
            raise ExecutionError(
                f"field {name!r} not found in index {ctx.index.name!r}")
        return field

    def _row_id(self, ctx: _Ctx, field: Field, value,
                create: bool) -> int | None:
        if isinstance(value, bool):
            return int(value)
        if isinstance(value, str):
            if not field.options.keys:
                raise ExecutionError(
                    f"field {field.name!r}: string row on unkeyed field")
            log = self.translate.rows(field.index_name, field.name)
            return log.translate([value], create=create)[0]
        # raw mode (translate_output=False): the cluster layer pre-
        # translated keys to IDs at the edge; integer rows are expected
        if field.options.keys and ctx.translate_output:
            raise ExecutionError(
                f"field {field.name!r}: integer row on keyed field")
        return int(value)

    def _col_id(self, ctx: _Ctx, value, create: bool) -> int | None:
        if isinstance(value, str):
            if not ctx.index.keys:
                raise ExecutionError(
                    f"index {ctx.index.name!r}: string column on unkeyed index")
            log = self.translate.columns(ctx.index.name)
            return log.translate([value], create=create)[0]
        if ctx.index.keys and ctx.translate_output:
            raise ExecutionError(
                f"index {ctx.index.name!r}: integer column on keyed index")
        return int(value)

    def _exists(self, ctx: _Ctx) -> jax.Array:
        ef = ctx.index.existence_field
        if ef is None:
            raise ExecutionError(
                f"index {ctx.index.name!r} does not track existence "
                "(required for Not/All)")
        return self.planes.row_words(ctx.index.name, ef, VIEW_STANDARD, 0,
                                     ctx.shards)

    def _zeros(self, ctx: _Ctx) -> jax.Array:
        return self.planes.zeros(len(ctx.shards))

    def _shard_offsets(self, ctx: _Ctx, host: np.ndarray,
                       limit_shards: int | None = None):
        """Unpack a host bitmap (n_shards, W) into non-empty per-shard
        ascending column offsets: [(slot, shard, offsets uint)] — the one
        owner of the words→columns idiom (RowResult/Limit/Extract).
        ``limit_shards`` stops after the first N shard slots (the Limit
        push-down passes a host slice of just those rows)."""
        out = []
        for si, s in enumerate(ctx.shards):
            if limit_shards is not None and si >= limit_shards:
                break
            if s == PAD_SHARD:
                continue
            offs = unpack_columns(host[si])
            if len(offs):
                out.append((si, s, offs))
        return out

    def _to_row_result(self, ctx: _Ctx, words: jax.Array) -> RowResult:
        host = np.asarray(words)
        parts = [offs.astype(np.uint64) + np.uint64(s * SHARD_WIDTH)
                 for _, s, offs in self._shard_offsets(ctx, host)]
        columns = (np.concatenate(parts) if parts
                   else np.empty(0, np.uint64))
        if ctx.index.keys and ctx.translate_output:
            log = self.translate.columns(ctx.index.name)
            return RowResult(keys=log.keys_of(columns))
        return RowResult(columns=columns)

    def _filter_words(self, ctx: _Ctx, call: Call) -> jax.Array | None:
        """Optional bitmap-call filter child (TopN/Sum/Rows/GroupBy)."""
        flt = call.args.get("filter")
        if flt is None and call.children:
            flt = call.children[0]
        if flt is None:
            return None
        if not isinstance(flt, Call):
            raise ExecutionError("filter must be a bitmap call")
        return self._fused_bitmap(ctx, flt)

    # -- scalar / aggregate calls ------------------------------------------

    def _execute_count(self, ctx: _Ctx, call: Call) -> int:
        if len(call.children) != 1:
            raise ExecutionError("Count: exactly one child required")
        # over-budget/over-quota plain-Row planes serve PAGED (r17):
        # resident shard pages on device, host oracle for the rest —
        # without this, a too-big field never reached device speed
        paged = self._count_batch_paged(ctx, [call])
        if paged is not None:
            return paged[0]
        # simple BSI range counts ride the bsirange family (r20):
        # delta-aware plane, same-plane co-batching, solo fast lane
        fast = self._count_batch_bsi(ctx, [call])
        if fast is not None:
            return fast[0]
        # compound boolean trees compile whole (r16): one in-program
        # row gather + postfix fold, windowed with concurrent requests
        fused_tree = self._count_batch_tree(ctx, [call])
        if fused_tree is not None:
            return fused_tree[0]
        if self.batcher is not None:
            # cross-request coalescing: plan here, let the batcher run
            # one program + one read for every concurrent Count
            from pilosa_tpu.exec.fused import Unfusable
            try:
                leaves: list = []
                node = self._plan(ctx, call.children[0], leaves)
                return self.batcher.submit(
                    node, leaves, deadline=self._query_deadline())
            except Unfusable:
                pass
        # fused: bitwise tree + per-shard popcount in one XLA program;
        # the tiny cross-shard total finishes in int64 on host
        per_shard = self._fused_bitmap(ctx, call.children[0], want="count")
        return int(kernels.shard_totals(per_shard))

    def _execute_distinct(self, ctx: _Ctx, call: Call):
        """Distinct(filter?, field=f): sorted distinct values of a BSI
        field among (filtered) columns — device presence-bitmap scatter
        instead of the reference's per-shard value-set walk
        (``executor.go`` v2 ``executeDistinctShard``)."""
        from pilosa_tpu.exec.result import DistinctResult
        field, filter_words = self._agg_args(ctx, call)
        if field.options.bit_depth > 24:
            raise ExecutionError(
                "Distinct: bit depth > 24 not supported (presence array "
                "would exceed 16M entries)")
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        if self.batcher is not None:
            # concurrent identical Distincts share one presence scan
            # through the coalescing window (dedupe, not stacking —
            # the scan is a multi-dispatch block loop)
            pos, neg = self.batcher.submit_distinct(
                ps.plane, filter_words, deadline=self._query_deadline())
        else:
            pos, neg = bsik.distinct_presence(ps.plane, filter_words)
        pos = np.nonzero(np.asarray(pos))[0]
        neg = np.nonzero(np.asarray(neg))[0]
        base = field.options.base
        stored = sorted({int(v) + base for v in pos}
                        | {-int(v) + base for v in neg})
        return DistinctResult([field.from_stored(v) for v in stored])

    def _execute_includescolumn(self, ctx: _Ctx, call: Call) -> bool:
        """IncludesColumn(Row(...), column=c) -> bool (v2 parity)."""
        if len(call.children) != 1:
            raise ExecutionError(
                "IncludesColumn: exactly one bitmap child required")
        column = call.args.get("column")
        if column is None:
            raise ExecutionError("IncludesColumn: missing column argument")
        col_id = self._col_id(ctx, column, create=False)
        if col_id is None:
            return False
        shard, off = col_id // SHARD_WIDTH, col_id % SHARD_WIDTH
        if shard not in ctx.shards:
            return False
        # evaluate only over the owning shard (reference:
        # executeIncludesColumnCall runs on that shard alone)
        one = _Ctx(ctx.index, (shard,), ctx.translate_output)
        words = self._fused_bitmap(one, call.children[0])
        word = int(np.asarray(words[0, off >> 5]))
        return bool((word >> (off & 31)) & 1)

    def _execute_percentile(self, ctx: _Ctx, call: Call) -> ValCount:
        """Percentile(field=f, nth=99.9, filter?): the smallest stored
        value v with count(values <= v) >= nth% of non-null columns.
        The binary search runs ON DEVICE (``lax.while_loop`` in
        ``bsi.percentile_search``): two dispatches + two reads total
        (count, then search with an exact host-computed rank), vs
        ~2·bit_depth round trips for a host-driven search
        (FeatureBase-era Percentile parity)."""
        field, filter_words = self._agg_args(ctx, call)
        nth = call.args.get("nth")
        if nth is None:
            raise ExecutionError("Percentile: missing nth argument")
        nth = float(nth)
        if not 0 <= nth <= 100:
            raise ExecutionError("Percentile: nth must be in [0, 100]")
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        out, total = self.fused.run_percentile(ps.plane, filter_words, nth)
        if total == 0:
            return ValCount(0, 0)
        out = np.asarray(out)
        value = int(out[0]) + field.options.base
        return ValCount(value=field.from_stored(value), count=int(out[1]))

    def _execute_sum(self, ctx: _Ctx, call: Call) -> ValCount:
        field, filter_words = self._agg_args(ctx, call)
        # delta-aware plane (r20): sustained ingest absorbs into the
        # plane's BsiOverlay and the aggregate kernels answer
        # base⊕delta — no fold, no rebuild on the query path
        ps = self.planes.bsi_plane_delta(ctx.index.name, field,
                                         ctx.shards)
        if self.batcher is not None:
            # concurrent same-plane BSI aggregates co-batch into one
            # program + one read per collection window (solo requests
            # ride the fast lane)
            total, cnt = self.batcher.submit_sum(
                ps.plane, filter_words, delta=ps.delta,
                deadline=self._query_deadline())
        else:
            # same compiled one-read program, batch of one (eager
            # bit_counts would pay one dispatch per op + 3 reads)
            flags = (filter_words is not None,)
            filters = ((filter_words,)
                       if filter_words is not None else ())
            out = np.asarray(self.fused.run_sum_plane_batch(
                ps.plane, flags, filters, delta=ps.delta))[0]
            total, cnt = bsik.decode_sum_packed(out)
        value = total + field.options.base * cnt
        return ValCount(value=field.from_stored(value) if cnt else 0,
                        count=cnt)

    def _execute_min(self, ctx: _Ctx, call: Call) -> ValCount:
        return self._min_max(ctx, call, want_min=True)

    def _execute_max(self, ctx: _Ctx, call: Call) -> ValCount:
        return self._min_max(ctx, call, want_min=False)

    def _min_max(self, ctx: _Ctx, call: Call, want_min: bool) -> ValCount:
        field, filter_words = self._agg_args(ctx, call)
        ps = self.planes.bsi_plane_delta(ctx.index.name, field,
                                         ctx.shards)
        if self.batcher is not None:
            per_shard = self.batcher.submit_minmax(
                ps.plane, filter_words, delta=ps.delta,
                deadline=self._query_deadline())
        else:
            flags = (filter_words is not None,)
            filters = ((filter_words,)
                       if filter_words is not None else ())
            out = np.asarray(self.fused.run_minmax_plane_batch(
                ps.plane, flags, filters, delta=ps.delta))[0]
            per_shard = bsik.decode_minmax_packed(out)
        # reduce across the shard axis on host (one tuple per shard;
        # a delta-dirty plane appends one zero-or-live tuple per
        # overlay-touched word column — same combine)
        live = [(mn, mn_c, mx, mx_c)
                for mn, mn_c, mx, mx_c in per_shard
                if (mn_c if want_min else mx_c) > 0]
        if not live:
            return ValCount(0, 0)
        if want_min:
            best = min(mn for mn, *_ in live)
            total = sum(mn_c for mn, mn_c, *_ in live if mn == best)
        else:
            best = max(mx for _, _, mx, _ in live)
            total = sum(mx_c for _, _, mx, mx_c in live if mx == best)
        value = best + field.options.base
        return ValCount(value=field.from_stored(value), count=total)

    def _agg_args(self, ctx: _Ctx, call: Call):
        fname = call.args.get("field") or call.args.get("_field")
        if fname is None:
            raise ExecutionError(f"{call.name}: missing field argument")
        field = self._field(ctx, str(fname))
        if field.options.type not in BSI_TYPES:
            raise ExecutionError(f"{call.name}: field {fname!r} is not BSI")
        return field, self._filter_words(ctx, call)

    # -- TopN ---------------------------------------------------------------

    def _execute_topn(self, ctx: _Ctx, call: Call) -> PairsResult:
        fname = call.args.get("_field") or call.args.get("field")
        if fname is None:
            raise ExecutionError("TopN: missing field argument")
        field = self._field(ctx, str(fname))
        n = call.args.get("n")
        filter_words = self._filter_words(ctx, call)
        # tanimoto= threshold (reference: ``fragment.go#top`` tanimoto
        # arg): keep rows whose tanimoto coefficient against the filter
        # (source) row, 100·|row∧src| / (|src|+|row|−|row∧src|), meets
        # the threshold.  ``_rowCounts=1`` is the internal cluster
        # fan-out mode: return per-row intersection AND full counts plus
        # |src| so the coordinator can apply the threshold on GLOBAL
        # sums (per-node ratios don't merge).
        tanimoto = call.args.get("tanimoto")
        want_partial = bool(call.args.get("_rowCounts"))
        if tanimoto is not None:
            tanimoto = float(tanimoto)
            if not 0 < tanimoto <= 100:
                raise ExecutionError("TopN: tanimoto must be in (0, 100]")
        need_row_counts = want_partial or tanimoto is not None
        if need_row_counts and filter_words is None:
            raise ExecutionError(
                "TopN: tanimoto requires a filter row (source bitmap)")
        # |src| counts even when this node holds no rows of the target
        # field — the coordinator's global tanimoto union needs every
        # node's share of the source row
        src_count = 0
        if need_row_counts:
            src_count = int(kernels.shard_totals(
                kernels.count(filter_words)))
        # Representation choice (SURVEY.md §8 "dense blowup"):
        # 1. dense resident plane when it fits the device budget;
        # 2. no filter → exact counts from host fragment metadata,
        #    no device at all;
        # 3. sparse (container-blocked) residency when 12 B/bit fits —
        #    high-row-cardinality fields stay device-resident and one
        #    gather+segment-sum program answers each filtered TopN
        #    (engine/sparse.py), no per-query re-streaming;
        # 4. last resort: stream fixed-shape row blocks per query.
        row_totals = None
        ps = None
        tried_nowait = False
        if self.planes.has_entry(ctx.index.name, field, VIEW_STANDARD,
                                 ctx.shards):
            # a resident entry (fresh or delta-dirty) serves without
            # the per-request plane_bytes fragment walk — under
            # sustained ingest the generations move every batch and
            # the walk would land on every TopN (the r3 warm-path
            # metadata class)
            ps = self.planes.field_plane_nowait(ctx.index.name, field,
                                                VIEW_STANDARD, ctx.shards)
            tried_nowait = True
        if ps is None:
            est = self.planes.plane_bytes(field, VIEW_STANDARD,
                                          ctx.shards)
            if est <= self.planes.budget and not tried_nowait:
                # nowait: while a big plane builds in the background
                # (serve-while-build, VERDICT r4 weak #6) this query
                # falls through to the streaming path instead of
                # stalling minutes
                ps = self.planes.field_plane_nowait(
                    ctx.index.name, field, VIEW_STANDARD, ctx.shards)
        if ps is not None:
            if ps.n_rows == 0:
                return ({"pairs": [], "srcCount": src_count} if want_partial
                        else PairsResult([]))
            if (self.batcher is not None
                    and len(ctx.shards) <= self._REDUCE_SHARD_MAX):
                # dense TopN joins the coalescing window: concurrent
                # requests over the same resident plane share one
                # program and one read (unfiltered requests dedupe
                # outright; the int32 device reduce needs the same
                # shard bound as _plane_totals).  Both reads enqueue
                # BEFORE either wait, so a tanimoto request pays one
                # collection window, not two in series.  A delta-dirty
                # plane (r15 ingest) answers base⊕delta in-window.
                if need_row_counts:
                    h1 = self.batcher.enqueue_rowcounts(
                        ps.plane, filter_words, delta=ps.delta,
                        deadline=self._query_deadline())
                    h2 = self.batcher.enqueue_rowcounts(
                        ps.plane, delta=ps.delta,
                        deadline=self._query_deadline())
                    totals = self.batcher.wait(h1)[:ps.n_rows]
                    row_totals = self.batcher.wait(h2)[:ps.n_rows]
                else:
                    # single-read TopN (the common shape) goes through
                    # the blocking submit so a solo request rides the
                    # width-1 fast lane (r20 satellite: inline
                    # dispatch, no window formation) — under
                    # concurrency it lands in the window and dedupes
                    # exactly like the enqueue form
                    totals = self.batcher.submit_rowcounts(
                        ps.plane, filter_words, delta=ps.delta,
                        deadline=self._query_deadline())[:ps.n_rows]
            elif ps.delta is not None:
                counts = self.fused.run_rowcounts_delta(
                    ps.plane, ps.delta, filter_words=filter_words,
                    reduce=False)
                totals = kernels.shard_totals(counts)[:ps.n_rows]
                if need_row_counts:
                    row_totals = kernels.shard_totals(
                        self.fused.run_rowcounts_delta(
                            ps.plane, ps.delta,
                            reduce=False))[:ps.n_rows]
            else:
                counts = kernels.row_counts(ps.plane, filter_words)
                totals = kernels.shard_totals(counts)[:ps.n_rows]
                if need_row_counts:
                    row_totals = kernels.shard_totals(
                        kernels.row_counts(ps.plane, None))[:ps.n_rows]
            all_rows = ps.row_ids
        elif filter_words is None:
            # unfiltered: row cardinalities are host truth (directory
            # sums + overlay) — exact, zero device work
            all_rows, totals = self._host_row_cards(ctx, field)
            if len(all_rows) == 0:
                return PairsResult([])
        elif (est > self.planes.budget  # while the dense plane builds,
              # stream — don't ALSO build sparse residency for a field
              # about to be dense-resident
              and self.planes.sparse_bytes(field, VIEW_STANDARD,
                                           ctx.shards)
              <= self.planes.budget):
            from pilosa_tpu.engine import sparse as sparsek
            ss = self.planes.sparse_plane(ctx.index.name, field,
                                          VIEW_STANDARD, ctx.shards)
            if ss.n_rows == 0:
                return ({"pairs": [], "srcCount": src_count} if want_partial
                        else PairsResult([]))
            if (n is not None and tanimoto is None and not want_partial
                    and call.args.get("ids") is None
                    and call.args.get("attrName") is None):
                # plain TopN(n, filter): device top_k, read only k pairs
                # instead of the full (possibly millions-long) counts
                k = min(int(n), ss.n_rows)
                k_pad = min(ss.n_rows_pad,
                            1 << max(0, (k - 1).bit_length()))
                if ss.mesh is not None:
                    vals, slots = sparsek.topn_sparse_meshed(
                        ss.mesh, ss.axis, filter_words, ss.word_idx,
                        ss.mask, ss.row_ptr, k_pad)
                else:
                    vals, slots = sparsek.topn_sparse(
                        filter_words, ss.word_idx, ss.mask, ss.row_ptr,
                        k_pad)
                vals = np.asarray(vals)[:k]
                slots = np.asarray(slots)[:k]
                live = vals > 0
                row_ids = ss.row_ids[slots[live]]
                vals = vals[live]
                if field.options.keys and ctx.translate_output:
                    log = self.translate.rows(ctx.index.name, field.name)
                    return PairsResult(
                        [Pair(key=k_, count=int(c)) for k_, c in
                         zip(log.keys_of(row_ids, strict=False), vals)])
                return PairsResult([Pair(id=int(r), count=int(c))
                                    for r, c in zip(row_ids, vals)])
            if ss.mesh is not None:
                counts = sparsek.sparse_row_counts_meshed(
                    ss.mesh, ss.axis, filter_words, ss.word_idx,
                    ss.mask, ss.row_ptr)
            else:
                counts = sparsek.sparse_row_counts(
                    filter_words, ss.word_idx, ss.mask, ss.row_ptr)
            totals = np.asarray(counts).astype(np.int64)[:ss.n_rows]
            all_rows = ss.row_ids
            if need_row_counts:
                row_totals = ss.row_cards  # host truth, no second pass
        else:
            block = max(64, int(self.planes.budget
                                // (len(ctx.shards) * WORDS_PER_SHARD * 4
                                    * 4)))  # /4: chunk + staging headroom
            parts_rows, parts_totals, parts_row_totals = [], [], []
            for chunk_rows, chunk_plane in self.planes.iter_row_blocks(
                    field, VIEW_STANDARD, ctx.shards, block):
                ctx.check_deadline()  # streaming can run for minutes
                counts = kernels.row_counts(chunk_plane, filter_words)
                parts_totals.append(
                    kernels.shard_totals(counts)[:len(chunk_rows)])
                if need_row_counts:
                    parts_row_totals.append(kernels.shard_totals(
                        kernels.row_counts(chunk_plane, None))
                        [:len(chunk_rows)])
                parts_rows.append(chunk_rows)
            if not parts_rows:
                return ({"pairs": [], "srcCount": src_count} if want_partial
                        else PairsResult([]))
            all_rows = np.concatenate(parts_rows)
            totals = np.concatenate(parts_totals)
            if need_row_counts:
                row_totals = np.concatenate(parts_row_totals)
        ids_arg = call.args.get("ids")
        attr_name = call.args.get("attrName")
        if attr_name is not None:
            # restrict to rows whose attr matches (reference:
            # ``fragment.top`` attrName/attrValue filtering)
            ids_arg = list(ids_arg or []) + field.row_attrs.find_ids(
                str(attr_name), call.args.get("attrValue"))
            if not ids_arg:
                return PairsResult([])
        if ids_arg is not None:
            wanted = {int(r) for r in ids_arg}
            keep = np.array([int(r) in wanted for r in all_rows])
            totals = np.where(keep, totals, 0)
        if want_partial:
            live = row_totals > 0
            return {"pairs": [
                {"id": int(r), "count": int(c), "rowCount": int(rc)}
                for r, c, rc in zip(all_rows[live], totals[live],
                                    row_totals[live])],
                "srcCount": src_count}
        if tanimoto is not None:
            union = src_count + row_totals - totals
            keep = (totals > 0) & (100.0 * totals >= tanimoto * union)
            totals = np.where(keep, totals, 0)
        k = len(all_rows) if n is None else min(int(n), len(all_rows))
        slots = np.argsort(-totals, kind="stable")[:k]
        vals = totals[slots]
        live = vals > 0
        row_ids = all_rows[slots[live]]
        vals = vals[live]
        if field.options.keys and ctx.translate_output:
            log = self.translate.rows(ctx.index.name, field.name)
            return PairsResult([Pair(key=log.key_of(int(r)), count=int(c))
                                for r, c in zip(row_ids, vals)])
        return PairsResult([Pair(id=int(r), count=int(c))
                            for r, c in zip(row_ids, vals)])

    # -- Extract ------------------------------------------------------------

    # Extract materializes per-column values; wrap wide selections in
    # Limit(...) — the cap keeps one call from expanding a billion rows
    MAX_EXTRACT_COLUMNS = 100_000

    def _execute_extract(self, ctx: _Ctx, call: Call) -> ExtractResult:
        """Extract(bitmap, Rows(f), ...): per selected column, each
        field's value(s) (reference: v2 ``executeExtract`` /
        ``ExtractedTable``).  Set-like fields answer with ONE device
        gather program (``kernels.column_bits``) over the resident
        plane; BSI fields read per-column host values."""
        if not call.children:
            raise ExecutionError("Extract: bitmap filter child required")
        flt, *field_calls = call.children
        bad = [c.name for c in field_calls if c.name != "Rows"]
        if bad:
            raise ExecutionError(
                f"Extract: field children must be Rows calls, got {bad}")
        fields = []
        for fc in field_calls:
            fname = fc.args.get("_field") or fc.args.get("field")
            if fname is None:
                raise ExecutionError("Extract: Rows child missing field")
            fields.append(self._field(ctx, str(fname)))

        words = self._fused_bitmap(ctx, flt)
        # per-shard popcounts first (one tiny read): enforce the cap
        # BEFORE materializing anything, then pull only the non-empty
        # shard rows — an Extract filter is sparse by contract, and the
        # full-bitmap read cost ~4 s/call at 954 shards on the tunnel
        counts = np.asarray(_shard_popcounts(words))
        total = int(counts.sum())
        if total > self.MAX_EXTRACT_COLUMNS:
            raise ExecutionError(
                f"Extract: {total} columns selected; cap is "
                f"{self.MAX_EXTRACT_COLUMNS} — narrow the filter or wrap "
                "it in Limit(...)")
        nz = np.nonzero(counts)[0]
        col_parts = []
        if len(nz):
            host_rows = np.asarray(words[jnp.asarray(nz)])
            for j, si in enumerate(nz):
                si = int(si)
                if ctx.shards[si] == PAD_SHARD:
                    continue
                col_parts.append((si, ctx.shards[si],
                                  unpack_columns(host_rows[j])))
        columns = (np.concatenate(
            [offs.astype(np.uint64) + np.uint64(s * SHARD_WIDTH)
             for _, s, offs in col_parts])
            if col_parts else np.empty(0, np.uint64))

        per_field = [self._extract_field(ctx, f, col_parts, len(columns))
                     for f in fields]
        if ctx.index.keys and ctx.translate_output:
            log = self.translate.columns(ctx.index.name)
            col_out = log.keys_of(columns, strict=False)
        else:
            col_out = [int(c) for c in columns]
        return ExtractResult(
            field_specs=[(f.name, f.options.type) for f in fields],
            columns=[(c, [vals[i] for vals in per_field])
                     for i, c in enumerate(col_out)])

    def _extract_field(self, ctx: _Ctx, field: Field, col_parts,
                       n_cols: int) -> list:
        """One field's value per selected column (list of length n_cols).
        col_parts: [(si, shard, offsets ascending)]."""
        opts = field.options
        if opts.type in BSI_TYPES:
            return self._extract_bsi(ctx, field, col_parts, n_cols)
        out: list = [None] * n_cols
        key_log = (self.translate.rows(ctx.index.name, field.name)
                   if opts.keys and ctx.translate_output else None)
        est = self.planes.plane_bytes(field, VIEW_STANDARD, ctx.shards)
        if est > self.planes.budget:
            # huge-cardinality field: per-column inverted check on host
            # (generation-cached CSR scan) instead of a plane build
            view = field.view(VIEW_STANDARD)
            pos = 0
            for _, s, offs in col_parts:
                frag = view.fragment(s) if view is not None else None
                for off in offs:
                    rows = (frag.rows_containing(int(off))
                            if frag is not None else np.empty(0, np.uint64))
                    out[pos] = self._extract_cell(opts, key_log, rows)
                    pos += 1
            return out
        # set-like: membership of each column in every row, one device
        # gather program per shard plane
        ps = self.planes.field_plane(ctx.index.name, field, VIEW_STANDARD,
                                     ctx.shards)
        pos = 0
        for si, s, offs in col_parts:
            k = len(offs)
            if ps.n_rows == 0:
                rows_by_col = [np.empty(0, np.int64)] * k
            else:
                # pow2-pad k: one compiled program per bucket, not per
                # distinct selected-column count (the CountBatcher
                # recompile-storm lesson)
                k_pad = 1 << max(0, (k - 1).bit_length())
                word_idx = np.zeros(k_pad, np.int32)
                bit_idx = np.zeros(k_pad, np.uint32)
                word_idx[:k] = (offs.astype(np.int64) >> 5)
                bit_idx[:k] = (offs.astype(np.int64) & 31)
                key = (("colbits", ps.plane.shape, k_pad), "extract")
                fn = self.fused._cached(
                    key, lambda: kernels.column_bits)
                bits = np.asarray(fn(ps.plane[si:si + 1],
                                     jnp.asarray(word_idx),
                                     jnp.asarray(bit_idx)))[0]  # (R, k_pad)
                rows_by_col = [ps.row_ids[np.nonzero(
                    bits[:ps.n_rows, j])[0]] for j in range(k)]
            for j in range(k):
                out[pos] = self._extract_cell(opts, key_log,
                                              rows_by_col[j])
                pos += 1
        return out

    def _extract_bsi(self, ctx: _Ctx, field: Field, col_parts,
                     n_cols: int) -> list:
        """BSI column values straight off the resident bit-plane: ONE
        ``column_bits_grouped`` program gathers every selected column's
        exists/sign/magnitude bits across all shards (VERDICT r2 #6 —
        the previous form walked ``field.value`` per column on host:
        O(cols·depth) fragment probes at the 100k column cap)."""
        from pilosa_tpu.engine.bsi import EXISTS_ROW, OFFSET_ROW, SIGN_ROW
        opts = field.options
        depth = opts.bit_depth
        ps = self.planes.bsi_plane(ctx.index.name, field, ctx.shards)
        k_max = max((len(offs) for _, _, offs in col_parts), default=0)
        if k_max == 0:
            return [None] * n_cols
        # pow2-pad the per-shard column count: one compiled program per
        # (plane shape, bucket), not per distinct selection size
        k_pad = 1 << max(0, (k_max - 1).bit_length())
        n_sh = ps.plane.shape[0]
        word_idx = np.zeros((n_sh, k_pad), np.int32)
        bit_idx = np.zeros((n_sh, k_pad), np.uint32)
        for si, _, offs in col_parts:
            k = len(offs)
            word_idx[si, :k] = offs.astype(np.int64) >> 5
            bit_idx[si, :k] = offs.astype(np.int64) & 31
        key = (("colbits-grouped", ps.plane.shape, k_pad), "extract")
        fn = self.fused._cached(key, lambda: kernels.column_bits_grouped)
        bits = np.asarray(fn(ps.plane, jnp.asarray(word_idx),
                             jnp.asarray(bit_idx)))  # (S, R, k_pad)
        weights = (np.int64(1) << np.arange(depth, dtype=np.int64))
        out: list = [None] * n_cols
        pos = 0
        for si, _, offs in col_parts:
            k = len(offs)
            b = bits[si, :, :k].astype(np.int64)
            mags = weights @ b[OFFSET_ROW:OFFSET_ROW + depth]
            np.negative(mags, out=mags, where=b[SIGN_ROW] != 0)
            exists = b[EXISTS_ROW] != 0
            for j in range(k):
                if exists[j]:
                    out[pos] = field.from_stored(int(mags[j]) + opts.base)
                pos += 1
        return out

    @staticmethod
    def _extract_cell(opts, key_log, rows):
        """One (column, field) cell from the column's member row ids."""
        if opts.type == "bool":
            return bool(rows[-1]) if len(rows) else None
        if opts.type == "mutex":
            if not len(rows):
                return None
            r = int(rows[0])
            return key_log.key_of(r) if key_log else r
        if key_log is not None:
            return key_log.keys_of(rows, strict=False)
        return [int(r) for r in rows]

    def _host_row_cards(self, ctx: _Ctx, field: Field):
        """Exact per-row cardinalities merged across shards from host
        fragment metadata (directory sums + overlay) — the unfiltered
        TopN answer with zero device work."""
        from pilosa_tpu.exec.planes import merge_row_cards
        view = field.view(VIEW_STANDARD)
        frags = []
        if view is not None:
            for s in ctx.shards:
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is not None:
                    frags.append(frag)
        return merge_row_cards(frags)

    # -- Rows ---------------------------------------------------------------

    def _execute_rows(self, ctx: _Ctx, call: Call) -> RowIdsResult:
        fname = call.args.get("_field") or call.args.get("field")
        if fname is None:
            raise ExecutionError("Rows: missing field argument")
        field = self._field(ctx, str(fname))
        rows = self._rows_of(ctx, field, call)
        if field.options.keys and ctx.translate_output:
            log = self.translate.rows(ctx.index.name, field.name)
            return RowIdsResult(keys=log.keys_of(rows, strict=False))
        return RowIdsResult(rows=rows)

    def _rows_of(self, ctx: _Ctx, field: Field, call: Call) -> np.ndarray:
        """Row IDs with ≥1 bit, honoring column=, from=/to= (time
        fields: only rows seen in the range's minimal view cover),
        previous=, limit=."""
        frm = call.args.get("from")
        to = call.args.get("to")
        if frm is not None or to is not None:
            # time filter: the candidate views are the range's minimal
            # cover instead of the all-time standard view (r23) —
            # GroupBy time filters inherit this via its _rows_of calls
            views = [field.views.get(v)
                     for v in self._time_cover_views(field, frm, to)]
            views = [v for v in views if v is not None]
        else:
            views = ([field.standard_view()]
                     if field.standard_view() is not None else [])
        column = call.args.get("column")
        if column is not None:
            # column filter needs the bits: check membership per shard
            # on host (one column touches at most one shard)
            col_id = self._col_id(ctx, column, create=False)
            if col_id is None:
                return np.empty(0, np.uint64)
            shard, off = col_id // SHARD_WIDTH, col_id % SHARD_WIDTH
            if shard not in ctx.shards:
                return np.empty(0, np.uint64)
            # vectorized inverted check (generation-cached) instead of a
            # per-row contains() loop — 100k-row fields answer in ms
            row_set: set[int] = set()
            for view in views:
                frag = view.fragment(shard)
                if frag is not None:
                    row_set.update(int(r)
                                   for r in frag.rows_containing(off))
            rows = np.array(sorted(row_set), dtype=np.uint64)
        else:
            # live rows come straight from the fragment indexes — no
            # plane materialization or device round trip needed
            row_set = set()
            for view in views:
                for s in ctx.shards:
                    if s == PAD_SHARD:
                        continue
                    frag = view.fragment(s)
                    if frag is not None:
                        row_set.update(frag.row_ids())
            rows = np.array(sorted(row_set), dtype=np.uint64)
        like = call.args.get("like")
        if like is not None:
            # SQL-style pattern over row KEYS (reference: Rows like=,
            # FeatureBase era): % = any run, _ = one char.  One batched
            # key lookup + one compiled regex over all rows (not a
            # per-row key_of + fnmatch pair).
            if not field.options.keys:
                raise ExecutionError("Rows: like= requires a keyed field")
            import fnmatch
            import re
            pattern = (str(like).replace("*", "[*]").replace("?", "[?]")
                       .replace("%", "*").replace("_", "?"))
            rx = re.compile(fnmatch.translate(pattern))
            log = self.translate.rows(ctx.index.name, field.name)
            keys = log.keys_of(rows, strict=False)
            keep = [k is not None and rx.match(k) is not None for k in keys]
            rows = rows[np.array(keep, dtype=bool)] if len(rows) else rows
        prev = call.args.get("previous")
        if prev is not None:
            prev_id = self._row_id(ctx, field, prev, create=False)
            if prev_id is not None:
                rows = rows[rows > prev_id]
        limit = call.args.get("limit")
        if limit is not None:
            rows = rows[: int(limit)]
        return rows

    def _column_bitmap(self, ctx: _Ctx, col_id: int) -> jax.Array:
        host = np.zeros((len(ctx.shards), WORDS_PER_SHARD), dtype=np.uint32)
        shard, off = col_id // SHARD_WIDTH, col_id % SHARD_WIDTH
        for si, s in enumerate(ctx.shards):
            if s == shard:
                host[si, off >> 5] = np.uint32(1) << np.uint32(off & 31)
        return self.planes.place(host)

    # -- GroupBy ------------------------------------------------------------

    _GROUPBY_AGGS = {"Sum": "sum", "Count": None, "Min": "minmax",
                     "Max": "minmax"}

    @staticmethod
    def parse_having(having, agg_name: str | None):
        """``having=Condition(count > 10)`` / ``Condition(sum < 0)``
        (v2 surface: post-aggregate group filtering in
        ``executeGroupBy``).  Returns (metric, Condition)."""
        if not isinstance(having, Call) or having.name != "Condition":
            raise ExecutionError("GroupBy: having= must be Condition(...)")
        conds = [(k, v) for k, v in having.args.items()
                 if isinstance(v, Condition)]
        if len(conds) != 1 or conds[0][0] not in ("count", "sum"):
            raise ExecutionError("GroupBy: having supports exactly one "
                                 "condition on count or sum")
        metric, cond = conds[0]
        if metric == "sum" and agg_name != "Sum":
            raise ExecutionError(
                "GroupBy: having on sum requires aggregate=Sum(...)")
        return metric, cond

    def _execute_groupby(self, ctx: _Ctx, call: Call) -> GroupCountsResult:
        """Whole combination tree in ONE device program (O(1) dispatches
        regardless of level count — ``exec.groupby``), replacing the
        reference's per-combination recursion
        (``executor.go#executeGroupByShard``)."""
        from pilosa_tpu.exec import groupby as gb

        rows_calls = [c for c in call.children if c.name == "Rows"]
        if not rows_calls:
            raise ExecutionError("GroupBy: at least one Rows child required")
        filter_words = None
        flt = call.args.get("filter")
        if isinstance(flt, Call):
            filter_words = self._bitmap(ctx, flt)
        agg = call.args.get("aggregate")
        agg_field = None
        agg_name = None
        minmax_host = False
        if isinstance(agg, Call):
            if agg.name not in self._GROUPBY_AGGS:
                raise ExecutionError(
                    "GroupBy: aggregate must be Sum/Count/Min/Max")
            agg_name = agg.name
            if agg_name != "Count":
                aname = agg.args.get("field") or agg.args.get("_field")
                agg_field = self._field(ctx, str(aname))
                if agg_field.options.type not in BSI_TYPES:
                    raise ExecutionError(
                        f"GroupBy: aggregate field {aname!r} is not BSI")
                if (agg_name in ("Min", "Max")
                        and agg_field.options.bit_depth > gb.MINMAX_MAX_DEPTH):
                    # graceful fallback (r20 satellite): the in-program
                    # signed int32 reconstruction caps at 30 bits, so
                    # deeper fields run the combination counts on
                    # device and finish Min/Max per surviving group on
                    # the exact host path (bit descent + python-int
                    # reconstruction) instead of refusing the query
                    minmax_host = True
        if len(ctx.shards) > gb.MAX_SHARDS:
            raise ExecutionError(
                f"GroupBy: more than {gb.MAX_SHARDS} shards per node "
                "unsupported")

        specs = []  # (field, row_ids, PlaneSet)
        for rc in rows_calls:
            f = self._field(ctx, str(rc.args.get("_field") or
                                     rc.args.get("field")))
            rows = self._rows_of(ctx, f, rc)
            if len(rows) == 0:
                return GroupCountsResult([])  # no combinations possible
            # plane over the selected rows only — GroupBy memory scales
            # with the Rows() selections, not field cardinality
            ps = self.planes.rows_plane(ctx.index.name, f, VIEW_STANDARD,
                                        rows, ctx.shards)
            specs.append((f, rows, ps))
        # delta-aware agg plane (r20): sustained BSI ingest absorbs
        # into the overlay and the GroupBy program merges base⊕delta
        # in-program — no fold on the query path.  The depth>30 host
        # fallback needs a CLEAN plane (its bit descent reads the
        # plane directly), so it folds instead.
        agg_plane = None
        if agg_field is not None:
            agg_plane = (self.planes.bsi_plane(ctx.index.name,
                                               agg_field, ctx.shards)
                         if minmax_host else
                         self.planes.bsi_plane_delta(
                             ctx.index.name, agg_field, ctx.shards))

        having = call.args.get("having")
        having_metric = having_cond = None
        if having is not None:
            having_metric, having_cond = self.parse_having(having, agg_name)

        limit = call.args.get("limit")
        # previous=[rowID, ...] pages past an exact combination
        # (reference: GroupBy previous= paging); groups generate in
        # lexicographic row-id order, so skip while combo <= previous
        prev = call.args.get("previous")
        prev_tuple = (tuple(int(r) for r in prev)
                      if isinstance(prev, list) else None)
        if prev_tuple is not None and len(prev_tuple) != len(specs):
            raise ExecutionError(
                "GroupBy: previous= must list one row per Rows call")

        last_f, last_rows, last_ps = specs[-1]
        last_slots = [last_ps.slot_of[int(r)] for r in last_rows]
        last_rows_arr = np.asarray(last_rows, np.uint64)
        base = agg_field.options.base if agg_field is not None else 0
        # columnar accumulation: per block, fancy-index the surviving
        # (combo, last-row) cells straight into row-id/count/agg arrays.
        # The old per-group object loop was ~60% of warm GroupBy latency
        # at 125k groups (reference builds []GroupCount eagerly in
        # executor.go#executeGroupBy; we materialize objects lazily at
        # the result edge — see GroupCountsResult).
        acc_rows: list[np.ndarray] = []
        acc_counts: list[np.ndarray] = []
        acc_aggs: list[np.ndarray] = []
        acc_mask: list[np.ndarray] = []
        n_levels = len(specs)
        total = 0
        agg_kind = (None if minmax_host
                    else self._GROUPBY_AGGS.get(agg_name))
        run = None
        if (self.batcher is not None
                and len(ctx.shards) <= self._REDUCE_SHARD_MAX):
            # GroupBy blocks ride the window machinery (r20): the
            # flattened block program joins the collection window's
            # dispatch pool + packed readback alongside concurrent
            # Counts/aggregates, and identical concurrent GroupBys
            # (same planes, same combination block) dedupe to ONE
            # program via the digest
            import hashlib
            deadline = self._query_deadline()

            def run(pl, ci, lp, fw, ap, agg, ad):
                # ci arrives as the HOST combo array (see iter_blocks)
                # — the digest costs no device round trip
                meta = (int(ci.shape[0]) if pl else 1,
                        int(lp.shape[1]),
                        int(ap.shape[1]) - 2 if ap is not None else 0)
                digest = hashlib.blake2b(
                    ci.tobytes(), digest_size=8).digest()
                return self.batcher.submit_groupby(
                    pl, ci, lp, fw, ap, agg, meta, digest, delta=ad,
                    deadline=deadline)
        for combo_rows, out in gb.iter_blocks(
                specs, filter_words,
                None if minmax_host else agg_plane, agg_kind,
                limited=limit is not None, run=run,
                agg_delta=(None if minmax_host or agg_plane is None
                           else agg_plane.delta)):
            ctx.check_deadline()  # large combination trees stream
            counts = np.asarray(out["counts"])  # (C, slots)
            slots = np.asarray(last_slots, np.int64)
            sub = counts[:, slots].astype(np.int64)  # (C, L)
            # per-group aggregates computed VECTORIZED over the whole
            # block (the per-group Python bit-descent walked O(depth)
            # ints per group — a 125k-group GroupBy spent seconds there)
            aggs = None
            agg_ok = None
            if agg_name == "Count":
                aggs = sub
            elif agg_name == "Sum":
                pos = np.asarray(out["pos"])[:, slots].astype(np.int64)
                neg = np.asarray(out["neg"])[:, slots].astype(np.int64)
                acnt = np.asarray(out["cnt"])[:, slots].astype(np.int64)
                depth = pos.shape[-1]
                # int64 matmul only while provably exact: the weighted
                # bit sums are bounded by max_count·2^(depth+1) and the
                # base term by |base|·max_cnt.  Past the bound (deep
                # BSI × huge groups) fall back to exact Python big-int
                # accumulation, matching Sum's host-finish policy.
                max_count = int(max(np.abs(pos).max(initial=0),
                                    np.abs(neg).max(initial=0)))
                bound = (max_count << (depth + 1)) + \
                    abs(int(base)) * int(np.abs(acnt).max(initial=0))
                if depth <= 62 and bound < (1 << 62):
                    weights = np.int64(1) << np.arange(depth,
                                                       dtype=np.int64)
                    aggs = (pos - neg) @ weights + base * acnt
                else:
                    aggs = np.empty(pos.shape[:2], dtype=object)
                    for c in range(pos.shape[0]):
                        for li in range(pos.shape[1]):
                            aggs[c, li] = sum(
                                (int(pos[c, li, b]) - int(neg[c, li, b]))
                                << b for b in range(depth)) \
                                + base * int(acnt[c, li])
            elif agg_name in ("Min", "Max") and not minmax_host:
                key = "min" if agg_name == "Min" else "max"
                aggs = (np.asarray(out[key])[:, slots].astype(np.int64)
                        + base)
                agg_ok = np.asarray(out[key + "_cnt"])[:, slots] > 0
            keep = sub > 0
            if having_cond is not None:
                if having_metric == "count":
                    keep = keep & having_cond.matches_array(sub)
                elif aggs is None:
                    keep = np.zeros_like(keep)
                else:
                    # a group with no aggregate value (Min/Max over an
                    # empty cell) cannot pass a sum condition
                    if agg_ok is not None:
                        keep = keep & agg_ok
                    keep = keep & having_cond.matches_array(aggs)
            c_idx, l_idx = np.nonzero(keep)
            if c_idx.size == 0:
                continue
            rows_mat = np.empty((c_idx.size, n_levels), np.uint64)
            if n_levels > 1:
                rows_mat[:, :-1] = combo_rows[c_idx]
            rows_mat[:, -1] = last_rows_arr[l_idx]
            if prev_tuple is not None:
                after = _lex_gt(rows_mat, prev_tuple)
                if not after.all():
                    rows_mat = rows_mat[after]
                    c_idx, l_idx = c_idx[after], l_idx[after]
                    if c_idx.size == 0:
                        continue
            acc_rows.append(rows_mat)
            acc_counts.append(sub[c_idx, l_idx])
            if minmax_host:
                host_vals, host_ok = self._host_group_minmax(
                    ctx, specs, filter_words, agg_plane, rows_mat,
                    want_min=agg_name == "Min")
                acc_aggs.append(host_vals + base)
                acc_mask.append(host_ok)
            elif aggs is not None:
                acc_aggs.append(aggs[c_idx, l_idx])
                acc_mask.append(agg_ok[c_idx, l_idx]
                                if agg_ok is not None
                                else np.ones(c_idx.size, bool))
            total += c_idx.size
            if limit is not None and total >= int(limit):
                break
        if not acc_rows:
            return GroupCountsResult([])
        row_ids = np.concatenate(acc_rows)
        counts = np.concatenate(acc_counts)
        agg_col = np.concatenate(acc_aggs) if acc_aggs else None
        mask_col = np.concatenate(acc_mask) if acc_mask else None
        if limit is not None:
            row_ids = row_ids[: int(limit)]
            counts = counts[: int(limit)]
            if agg_col is not None:
                agg_col = agg_col[: int(limit)]
                mask_col = mask_col[: int(limit)]
        # keyed fields translate ONCE per level over the unique row ids
        # (was one KeyLog lookup per group member)
        row_keys: list = [None] * n_levels
        for lvl, (f, _, _) in enumerate(specs):
            if f.options.keys and ctx.translate_output:
                klog = self.translate.rows(ctx.index.name, f.name)
                uniq, inv = np.unique(row_ids[:, lvl], return_inverse=True)
                # strict=False: an id the translate log has not seen yet
                # falls back to its numeric form (matches the Rows()
                # output path, _execute_rows)
                keys = klog.keys_of(uniq, strict=False)
                row_keys[lvl] = [keys[i] for i in inv]
        return GroupCountsResult(
            fields=[f.name for f, _, _ in specs], row_ids=row_ids,
            row_keys=row_keys if any(k is not None for k in row_keys)
            else None,
            counts=counts, aggs=agg_col, agg_mask=mask_col)

    def _host_group_minmax(self, ctx: _Ctx, specs, filter_words,
                           agg_plane, rows_mat: np.ndarray,
                           want_min: bool):
        """Exact host Min/Max per surviving group for BSI depths past
        ``groupby.MINMAX_MAX_DEPTH`` (r20 satellite): the group's
        column bitmap intersects on device, then the full-depth bit
        descent + python-int reconstruction answers exactly — one
        dispatch per group, the correctness path for depth > 30
        fields, not the serving spine."""
        vals: list = []
        oks = np.zeros(len(rows_mat), bool)
        for g in range(len(rows_mat)):
            words = filter_words
            for lvl, (_f, _rows, ps) in enumerate(specs):
                row = ps.plane[:, ps.slot_of[int(rows_mat[g, lvl])], :]
                words = row if words is None \
                    else kernels.intersect(words, row)
            tuples = bsik.min_max(agg_plane.plane, words)
            live = [(mn, mc, mx, xc) for mn, mc, mx, xc in tuples
                    if (mc if want_min else xc) > 0]
            if not live:
                vals.append(0)
                continue
            vals.append(min(mn for mn, *_ in live) if want_min
                        else max(mx for _, _, mx, _ in live))
            oks[g] = True
        return np.array(vals), oks

    # -- writes -------------------------------------------------------------

    def _execute_set(self, ctx: _Ctx, call: Call) -> bool:
        col = call.args.get("_col")
        if col is None:
            raise ExecutionError("Set: missing column argument")
        col_id = self._col_id(ctx, col, create=True)
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError("Set: missing field=value argument")
        fname, value = hit
        field = self._field(ctx, fname)
        if field.options.type in BSI_TYPES:
            changed = field.set_value(col_id, value)
        else:
            row_id = self._row_id(ctx, field, value, create=True)
            ts = call.args.get("_timestamp")
            changed = field.set_bit(
                row_id, col_id,
                parse_pql_time(ts) if ts is not None else None)
        ctx.index.note_columns(np.array([col_id], np.uint64))
        return changed

    def _execute_clear(self, ctx: _Ctx, call: Call) -> bool:
        col = call.args.get("_col")
        if col is None:
            raise ExecutionError("Clear: missing column argument")
        col_id = self._col_id(ctx, col, create=False)
        if col_id is None:
            return False
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError("Clear: missing field argument")
        fname, value = hit
        field = self._field(ctx, fname)
        if field.options.type in BSI_TYPES:
            return field.clear_value(col_id)
        row_id = self._row_id(ctx, field, value, create=False)
        if row_id is None:
            return False
        return field.clear_bit(row_id, col_id)

    def _execute_clearrow(self, ctx: _Ctx, call: Call) -> bool:
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError("ClearRow: missing field=row argument")
        fname, value = hit
        field = self._field(ctx, fname)
        row_id = self._row_id(ctx, field, value, create=False)
        if row_id is None:
            return False
        view = field.standard_view()
        changed = 0
        if view is not None:
            for s in ctx.shards:
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is not None:
                    changed += frag.clear_row(row_id)
        return changed > 0

    def _execute_setrowattrs(self, ctx: _Ctx, call: Call):
        """SetRowAttrs(f, row, k=v, ...) — reference: row AttrStore write
        (``executor.go#executeSetRowAttrs``)."""
        fname = call.args.get("_field")
        if fname is None:
            raise ExecutionError("SetRowAttrs: missing field")
        field = self._field(ctx, str(fname))
        row = call.args.get("_row")
        if row is None:
            raise ExecutionError("SetRowAttrs: missing row")
        row_id = self._row_id(ctx, field, row, create=True)
        attrs = {k: v for k, v in call.args.items()
                 if not k.startswith("_")}
        field.row_attrs.set_attrs(int(row_id), attrs)
        return None

    def _execute_setcolumnattrs(self, ctx: _Ctx, call: Call):
        col = call.args.get("_col")
        if col is None:
            raise ExecutionError("SetColumnAttrs: missing column")
        col_id = self._col_id(ctx, col, create=True)
        attrs = {k: v for k, v in call.args.items()
                 if not k.startswith("_")}
        ctx.index.column_attrs.set_attrs(int(col_id), attrs)
        return None

    def _execute_store(self, ctx: _Ctx, call: Call) -> bool:
        if len(call.children) != 1:
            raise ExecutionError("Store: exactly one bitmap child required")
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError("Store: missing field=row argument")
        fname, value = hit
        field = self._field(ctx, fname)
        row_id = self._row_id(ctx, field, value, create=True)
        words = np.asarray(self._bitmap(ctx, call.children[0]))
        view = field.standard_view(create=True)
        changed = False
        for si, s in enumerate(ctx.shards):
            if s == PAD_SHARD:
                continue
            frag = view.fragment(s, create=True)
            cols = unpack_columns(words[si]).astype(np.uint32)
            changed |= frag.set_row(row_id, cols)
        return changed
