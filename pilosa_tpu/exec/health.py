"""Device health governor: breaker-style healthy→degraded→probing
state for the execution pipeline (r18).

The dispatch pipeline (exec/batcher.py) is one shared device stream —
a sick device (hung XLA compile, stalled dispatch, flaky readback)
poisons every request riding it.  The governor watches the two fault
signals the batcher produces (consecutive dispatch faults, pipeline
watchdog trips) and flips the batcher into DEGRADED serving: solo fast
lane off, readback pipelining off, every collection window executed
inline per item on the proven op-at-a-time fallback path.  After
``probe_after_s`` of degradation, exactly one window is admitted back
onto the fused pipeline as a PROBE — success returns the governor to
HEALTHY, failure re-degrades and schedules the next probe.

State is exported as the ``device_health_state`` gauge (0 healthy,
1 degraded, 2 probing) and the ``deviceHealth`` block on ``/status``.

The happy path is lock-free: ``admit``/``fastlane_ok``/
``record_success`` read one attribute (GIL-atomic) and return when the
state is HEALTHY with no faults outstanding — the governor must cost
the fused pipeline nothing while the device is well.
"""

from __future__ import annotations

import threading
import time

HEALTHY = "healthy"
DEGRADED = "degraded"
PROBING = "probing"

# gauge encoding for device_health_state (documented in the README
# metrics inventory; the /status block carries the string)
STATE_CODE = {HEALTHY: 0, DEGRADED: 1, PROBING: 2}


class DeviceHealthGovernor:
    """Consecutive-fault breaker for the batcher's device pipeline.

    - ``record_fault()``: one fused dispatch failed (fell back per
      item).  ``FAULT_THRESHOLD`` consecutive faults degrade; a fault
      during a probe re-degrades immediately.
    - ``record_trip()``: the pipeline watchdog quarantined a stalled
      window — degrade immediately (a hang is worse than an error).
    - ``record_success()``: a fused window completed cleanly.  Resets
      the consecutive-fault count; a successful PROBE window restores
      HEALTHY.
    - ``admit()``: may this collection window use the fused pipeline?
      HEALTHY → yes.  DEGRADED → no, until ``probe_after_s`` has
      passed, when ONE window is admitted as the probe (state flips to
      PROBING; concurrent windows keep the fallback until the probe's
      verdict).
    """

    FAULT_THRESHOLD = 3

    def __init__(self, stats=None, probe_after_s: float = 5.0,
                 flight=None, tier: str = "xla"):
        from pilosa_tpu.obs import NULL_FLIGHT, NopStats
        self._stats = stats or NopStats()
        # the serving kernel tier (r24): surfaced on /status so an
        # operator reading a degrade can see which tier the fused
        # pipeline was running — degraded serving itself always runs
        # the per-item XLA fallback path, whatever the tier
        self.tier = tier
        # flight recorder (r19): every state transition lands on the
        # incident timeline; a degrade ALSO triggers the ring dump —
        # the run-up to the breaker opening is the postmortem
        self.flight = flight or NULL_FLIGHT
        self.probe_after_s = max(0.05, float(probe_after_s))
        self._state = HEALTHY
        self._consecutive = 0
        self._since = time.monotonic()  # last transition
        self._trips = 0
        self._faults_total = 0
        self._lock = threading.Lock()

    # -- hot-path reads (lock-free: single attribute loads) ------------------

    @property
    def state(self) -> str:
        return self._state

    def fastlane_ok(self) -> bool:
        """Solo fast lane admits only while HEALTHY — a degraded or
        probing device must not dispatch inline on caller threads
        (the one place a hang wedges a thread the watchdog cannot
        reclaim)."""
        return self._state == HEALTHY

    def pipelining_ok(self) -> bool:
        """Readback run-ahead is a HEALTHY-only optimization: degraded
        and probe windows finish inline so a stall surfaces (and is
        bounded) one window at a time."""
        return self._state == HEALTHY

    # -- events --------------------------------------------------------------

    def _transition(self, to: str) -> None:
        """Caller holds the lock."""
        came = self._state
        self._state = to
        self._since = time.monotonic()
        self._stats.gauge("device_health_state", STATE_CODE[to])
        self.flight.record("governor", "device", f"{came}->{to}")
        if to == DEGRADED:
            # incident capture: the moment the breaker opens is
            # exactly when the preceding pipeline timeline matters
            self.flight.incident("governor_degrade", "device",
                                 f"from {came}")

    def record_fault(self) -> None:
        with self._lock:
            self._consecutive += 1
            self._faults_total += 1
            if self._state == PROBING:
                # the probe window itself faulted: the device is still
                # sick — re-degrade and schedule the next probe
                self._transition(DEGRADED)
            elif (self._state == HEALTHY
                  and self._consecutive >= self.FAULT_THRESHOLD):
                self._transition(DEGRADED)

    def record_trip(self) -> None:
        with self._lock:
            self._trips += 1
            self._consecutive = 0  # a hang resets the error streak
            if self._state != DEGRADED:
                self._transition(DEGRADED)

    def record_success(self) -> None:
        if self._state == HEALTHY and self._consecutive == 0:
            return  # lock-free happy path
        with self._lock:
            self._consecutive = 0
            if self._state == PROBING:
                self._transition(HEALTHY)

    def admit(self) -> bool:
        """True = this collection window may use the fused pipeline."""
        if self._state == HEALTHY:
            return True  # lock-free happy path
        with self._lock:
            if self._state == HEALTHY:
                return True
            if (self._state == DEGRADED
                    and time.monotonic() - self._since
                    >= self.probe_after_s):
                self._transition(PROBING)
                return True  # this window IS the probe
            return False

    # -- introspection -------------------------------------------------------

    def payload(self) -> dict:
        """The ``/status`` deviceHealth block."""
        with self._lock:
            return {
                "state": self._state,
                "stateCode": STATE_CODE[self._state],
                "consecutiveFaults": self._consecutive,
                "faultsTotal": self._faults_total,
                "watchdogTrips": self._trips,
                "sinceSeconds": round(
                    time.monotonic() - self._since, 3),
                "probeAfterSeconds": self.probe_after_s,
                "faultThreshold": self.FAULT_THRESHOLD,
                "kernelTier": self.tier,
            }
