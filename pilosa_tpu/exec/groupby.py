"""Vectorized GroupBy: the whole combination tree in one device program.

Reference: ``executor.go#executeGroupByShard`` walks the cross-product of
``Rows()`` selections recursively, intersecting per combination.  Host
recursion costs one dispatch (plus a ~100ms tunneled read) per prefix
combination; this module instead runs ONE compiled program that loops
over prefix combinations with ``lax.map`` (device-side, no host reads)
and vectorizes the innermost level as a popcount matrix — O(1) dispatch
+ O(1) reads for the entire GroupBy, any number of levels.

Aggregates (``aggregate=Sum/Count/Min/Max(field=f)``) ride the same
program: per-combination BSI bit counts (Sum) or min/max bit descent
(Min/Max) reduce over shards on device in int32; the host finishes the
``<< b`` weighting in exact int64 (``bsi.combine_sum`` policy).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels

# Device shard-axis sums are int32: per-shard per-bit counts are <= 2^20,
# so totals stay exact for up to 2047 shards (kernels.SAFE_SHARD_SUM) —
# far beyond a 1B-column index (954 shards).  The executor asserts this.
MAX_SHARDS = kernels.SAFE_SHARD_SUM

# Min/Max device path reconstructs |value| as int32 from bit flags:
# depths beyond 30 bits would overflow the signed reconstruction.
MINMAX_MAX_DEPTH = 30


@partial(jax.jit, static_argnames=("agg",))
def _groupby_program(prefix_planes, combo_idx, last_plane, filter_words,
                     agg_plane, agg):
    """All GroupBy combination counts (+ optional aggregate) in one program.

    prefix_planes: tuple of uint32[S, n_l, W], one per non-innermost
        ``Rows()`` level (possibly empty); ``combo_idx`` int32[C, L-1]
        indexes one row slot per level per combination.
    last_plane: uint32[S, n_last, W] — innermost level, vectorized.
    filter_words: uint32[S, W] | None.
    agg_plane: BSI uint32[S, D+2, W] | None; agg: None | "sum" | "minmax".

    Returns per-combination stacked outputs: counts int32[C, n_last] and
    aggregate arrays (see body).
    """

    def body(ix):
        prefix = filter_words
        for lvl, plane in enumerate(prefix_planes):
            row = plane[:, ix[lvl], :]
            prefix = row if prefix is None else jnp.bitwise_and(prefix, row)
        counts = jnp.sum(kernels.row_counts(last_plane, prefix), axis=0,
                         dtype=jnp.int32)
        out = {"counts": counts}
        if agg is None:
            return out
        words = (last_plane if prefix is None
                 else jnp.bitwise_and(last_plane, prefix[:, None, :]))
        aplane = agg_plane[:, None]  # (S, 1, D+2, W) broadcast over rows
        if agg == "sum":
            pos_c, neg_c, cnt = bsik.bit_counts(aplane, words)
            out["pos"] = jnp.sum(pos_c, axis=0, dtype=jnp.int32)
            out["neg"] = jnp.sum(neg_c, axis=0, dtype=jnp.int32)
            out["cnt"] = jnp.sum(cnt, axis=0, dtype=jnp.int32)
        else:  # minmax: signed int32 offsets, sentinel-reduced over shards
            mm = bsik.min_max_bits(aplane, words)
            depth = mm["min_bits"].shape[-1]
            weights = (jnp.int32(1) << jnp.arange(depth, dtype=jnp.int32))

            def signed(bits, neg):
                v = jnp.sum(bits.astype(jnp.int32) * weights, axis=-1)
                return jnp.where(neg, -v, v)

            big = jnp.int32(2**31 - 1)
            mn = jnp.where(mm["min_cnt"] > 0,
                           signed(mm["min_bits"], mm["min_neg"]), big)
            mx = jnp.where(mm["max_cnt"] > 0,
                           signed(mm["max_bits"], mm["max_neg"]), -big)
            gmn, gmx = jnp.min(mn, axis=0), jnp.max(mx, axis=0)
            out["min"] = gmn
            out["min_cnt"] = jnp.sum(
                jnp.where(mn == gmn[None], mm["min_cnt"], 0), axis=0,
                dtype=jnp.int32)
            out["max"] = gmx
            out["max_cnt"] = jnp.sum(
                jnp.where(mx == gmx[None], mm["max_cnt"], 0), axis=0,
                dtype=jnp.int32)
        return out

    if not prefix_planes:
        return jax.tree.map(lambda x: x[None],
                            body(jnp.zeros((0,), jnp.int32)))
    # batch_size vmaps combos in chunks: a plain lax.map serializes one
    # tiny AND+popcount kernel per combination (measured ~1.7 ms each on
    # a v5e — 4.3 s for a 50x50 prefix grid); 32-wide batches amortize
    # the per-iteration overhead while bounding the fused intermediate
    return jax.lax.map(body, combo_idx, batch_size=32)


def combo_grid(levels: list[np.ndarray]) -> np.ndarray:
    """Cartesian product of per-level arrays in lexicographic order,
    [C, L] in the input dtype (row-slot int32 or row-id uint64 — row
    ids are uint64 like the storage layer's; fragment._check_rows caps
    them at 2^40)."""
    if not levels:
        return np.zeros((1, 0), np.int32)
    grids = np.meshgrid(*levels, indexing="ij")
    return np.stack([g.reshape(-1) for g in grids], axis=-1)


# Per-dispatch device-output budget: bounds the combination block so a
# huge tree (e.g. 256^3 combos with a Sum aggregate) streams in fixed-
# size pieces instead of materializing int32[C, n_last, depth] at once.
BLOCK_OUT_BYTES = 64 << 20
# Smaller blocks when a limit= may stop the stream after the first few
# groups — trades a couple of extra dispatches for early exit.
LIMIT_BLOCK = 1024


def iter_blocks(specs, filter_words, agg_plane, agg_kind,
                limited: bool = False):
    """Execute the program over lexicographic combination blocks.

    specs: list of (field, rows np.ndarray, PlaneSet); the last spec is
    the vectorized innermost level.  Yields (combo_rows uint64[B, L-1],
    outputs dict of np arrays) in combination order; callers stop
    consuming once a ``limit=`` is satisfied.  Blocks are padded to one
    static shape (single compile), the pad tail is sliced off here.
    """
    *prefix_specs, (last_f, last_rows, last_ps) = specs
    slot_levels = [np.array([ps.slot_of[int(r)] for r in rows], np.int32)
                   for _, rows, ps in prefix_specs]
    row_levels = [np.asarray(rows, np.uint64) for _, rows, _ in prefix_specs]
    combo_slots = combo_grid(slot_levels).astype(np.int32)
    combo_rows = combo_grid(row_levels)
    n_combos = combo_slots.shape[0]

    n_last = last_ps.plane.shape[1]
    per_combo = n_last * 4
    if agg_kind == "sum":
        depth = agg_plane.plane.shape[1] - 2
        per_combo += n_last * (2 * depth + 1) * 4
    elif agg_kind == "minmax":
        per_combo += n_last * 16
    block = max(1, min(n_combos, BLOCK_OUT_BYTES // per_combo,
                       *([LIMIT_BLOCK] if limited else [])))

    planes = tuple(ps.plane for _, _, ps in prefix_specs)
    aplane = agg_plane.plane if agg_plane is not None else None
    for start in range(0, n_combos, block):
        sl = combo_slots[start:start + block]
        n = sl.shape[0]
        if n < block:  # pad to the compiled shape; tail dropped below
            sl = np.concatenate([sl, np.repeat(sl[-1:], block - n, axis=0)])
        out = _groupby_program(planes, jnp.asarray(sl), last_ps.plane,
                               filter_words, aplane, agg_kind)
        yield (combo_rows[start:start + n],
               {k: np.asarray(v)[:n] for k, v in out.items()})
