"""Vectorized GroupBy: the whole combination tree in one device program.

Reference: ``executor.go#executeGroupByShard`` walks the cross-product of
``Rows()`` selections recursively, intersecting per combination.  Host
recursion costs one dispatch (plus a ~100ms tunneled read) per prefix
combination; this module instead runs ONE compiled program that loops
over prefix combinations with ``lax.map`` (device-side, no host reads)
and vectorizes the innermost level as a popcount matrix — O(1) dispatch
+ O(1) reads for the entire GroupBy, any number of levels.

Aggregates (``aggregate=Sum/Count/Min/Max(field=f)``) ride the same
program: per-combination BSI bit counts (Sum) or min/max bit descent
(Min/Max) reduce over shards on device in int32; the host finishes the
``<< b`` weighting in exact int64 (``bsi.combine_sum`` policy).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels

# Device shard-axis sums are int32: per-shard per-bit counts are <= 2^20,
# so totals stay exact for up to 2047 shards (kernels.SAFE_SHARD_SUM) —
# far beyond a 1B-column index (954 shards).  The executor asserts this.
MAX_SHARDS = kernels.SAFE_SHARD_SUM

# Min/Max device path reconstructs |value| as int32 from bit flags:
# depths beyond 30 bits would overflow the signed reconstruction.
MINMAX_MAX_DEPTH = 30


def groupby_out(prefix_planes, combo_idx, last_plane, filter_words,
                agg_plane, agg, agg_delta=None):
    """All GroupBy combination counts (+ optional aggregate) in one program.

    prefix_planes: tuple of uint32[S, n_l, W], one per non-innermost
        ``Rows()`` level (possibly empty); ``combo_idx`` int32[C, L-1]
        indexes one row slot per level per combination.
    last_plane: uint32[S, n_last, W] — innermost level, vectorized.
    filter_words: uint32[S, W] | None.
    agg_plane: BSI uint32[S, D+2, W] | None; agg: None | "sum" | "minmax".
    agg_delta (r20): the agg plane's pending write overlay as
        ``(col_shard, col_word, col_vals, col_mask)`` — aggregates
        answer base⊕delta with the same split the flat families use
        (touched word columns excluded from the base pass, answered
        by a merged mini plane), so GroupBy stays fold-free under
        sustained BSI ingest.

    Returns per-combination stacked outputs: counts int32[C, n_last] and
    aggregate arrays (see body).
    """
    mini = excl = None
    if agg is not None and agg_delta is not None:
        from pilosa_tpu.ingest.delta import (bsi_excl_filter,
                                             bsi_mini_plane)
        cs, cw, cv, cm = agg_delta
        excl = bsi_excl_filter(agg_plane, cs, cw, None)   # [S, W]
        mini = bsi_mini_plane(agg_plane, cs, cw, cv, cm)  # [K, R, 1]
        s = agg_plane.shape[0]
        cs_ok = cs < s
        cs_c = jnp.clip(cs, 0, s - 1)

    def body(ix):
        prefix = filter_words
        for lvl, plane in enumerate(prefix_planes):
            row = plane[:, ix[lvl], :]
            prefix = row if prefix is None else jnp.bitwise_and(prefix, row)
        counts = jnp.sum(kernels.row_counts(last_plane, prefix), axis=0,
                         dtype=jnp.int32)
        out = {"counts": counts}
        if agg is None:
            return out
        words = (last_plane if prefix is None
                 else jnp.bitwise_and(last_plane, prefix[:, None, :]))
        aplane = agg_plane[:, None]  # (S, 1, D+2, W) broadcast over rows
        if mini is not None:
            # mini side first: the combination's filter words GATHERED
            # at the touched columns (from the PRE-exclusion words —
            # the exclusion below zeroes exactly these), zero on pad
            # lanes; base side: touched word columns masked out
            wmini = jnp.where(cs_ok[:, None],
                              words[cs_c, :, cw], 0)     # [K, n_last]
            words = jnp.bitwise_and(words, excl[:, None, :])
            mini_b = mini[:, None]       # [K, 1, R, 1] over n_last
            wmini_b = wmini[..., None]   # [K, n_last, 1]
        if agg == "sum":
            pos_c, neg_c, cnt = bsik.bit_counts(aplane, words)
            pos = jnp.sum(pos_c, axis=0, dtype=jnp.int32)
            neg = jnp.sum(neg_c, axis=0, dtype=jnp.int32)
            cn = jnp.sum(cnt, axis=0, dtype=jnp.int32)
            if mini is not None:
                mp, mn, mc = bsik.bit_counts(mini_b, wmini_b)
                pos = pos + jnp.sum(mp, axis=0, dtype=jnp.int32)
                neg = neg + jnp.sum(mn, axis=0, dtype=jnp.int32)
                cn = cn + jnp.sum(mc, axis=0, dtype=jnp.int32)
            out["pos"], out["neg"], out["cnt"] = pos, neg, cn
        else:  # minmax: signed int32 offsets, sentinel-reduced over shards
            mm = bsik.min_max_bits(aplane, words)
            if mini is not None:
                # touched columns append as pseudo-shard entries (the
                # per-key shapes match: [S, n_last, ...] ⧺ [K, n_last,
                # ...]); the sentinel reduce over axis 0 below then
                # combines base and mini exactly
                mmm = bsik.min_max_bits(mini_b, wmini_b)
                mm = {k: jnp.concatenate([mm[k], mmm[k]], axis=0)
                      for k in mm}
            depth = mm["min_bits"].shape[-1]
            weights = (jnp.int32(1) << jnp.arange(depth, dtype=jnp.int32))

            def signed(bits, neg):
                v = jnp.sum(bits.astype(jnp.int32) * weights, axis=-1)
                return jnp.where(neg, -v, v)

            big = jnp.int32(2**31 - 1)
            mn = jnp.where(mm["min_cnt"] > 0,
                           signed(mm["min_bits"], mm["min_neg"]), big)
            mx = jnp.where(mm["max_cnt"] > 0,
                           signed(mm["max_bits"], mm["max_neg"]), -big)
            gmn, gmx = jnp.min(mn, axis=0), jnp.max(mx, axis=0)
            out["min"] = gmn
            out["min_cnt"] = jnp.sum(
                jnp.where(mn == gmn[None], mm["min_cnt"], 0), axis=0,
                dtype=jnp.int32)
            out["max"] = gmx
            out["max_cnt"] = jnp.sum(
                jnp.where(mx == gmx[None], mm["max_cnt"], 0), axis=0,
                dtype=jnp.int32)
        return out

    if not prefix_planes:
        return jax.tree.map(lambda x: x[None],
                            body(jnp.zeros((0,), jnp.int32)))
    # batch_size vmaps combos in chunks: a plain lax.map serializes one
    # tiny AND+popcount kernel per combination (measured ~1.7 ms each on
    # a v5e — 4.3 s for a 50x50 prefix grid); 32-wide batches amortize
    # the per-iteration overhead while bounding the fused intermediate
    return jax.lax.map(body, combo_idx, batch_size=32)


_groupby_program = partial(jax.jit, static_argnames=("agg",))(groupby_out)


def block_part_names(agg: str | None) -> tuple[str, ...]:
    """The canonical part order of one flattened GroupBy block (the
    ``fused.run_groupby_batch`` layout)."""
    if agg == "sum":
        return ("counts", "pos", "neg", "cnt")
    if agg == "minmax":
        return ("counts", "min", "min_cnt", "max", "max_cnt")
    return ("counts",)


def block_shapes(n_combos: int, n_last: int, depth: int,
                 agg: str | None) -> dict[str, tuple]:
    """Per-part shapes of one block's outputs (leading dim C = the
    padded combination count; prefix-less GroupBys run C = 1)."""
    c = n_combos
    shapes = {"counts": (c, n_last)}
    if agg == "sum":
        shapes.update(pos=(c, n_last, depth), neg=(c, n_last, depth),
                      cnt=(c, n_last))
    elif agg == "minmax":
        shapes.update({"min": (c, n_last), "min_cnt": (c, n_last),
                       "max": (c, n_last), "max_cnt": (c, n_last)})
    return shapes


def unflatten_block(flat: np.ndarray, n_combos: int, n_last: int,
                    depth: int, agg: str | None) -> dict[str, np.ndarray]:
    """Invert ``fused.run_groupby_batch``'s flatten: one packed int32
    read back into the per-part arrays ``iter_blocks`` consumers
    slice."""
    shapes = block_shapes(n_combos, n_last, depth, agg)
    out = {}
    off = 0
    for name in block_part_names(agg):
        shape = shapes[name]
        size = int(np.prod(shape, dtype=np.int64))
        out[name] = flat[off:off + size].reshape(shape)
        off += size
    return out


def combo_grid(levels: list[np.ndarray]) -> np.ndarray:
    """Cartesian product of per-level arrays in lexicographic order,
    [C, L] in the input dtype (row-slot int32 or row-id uint64 — row
    ids are uint64 like the storage layer's; fragment._check_rows caps
    them at 2^40)."""
    if not levels:
        return np.zeros((1, 0), np.int32)
    grids = np.meshgrid(*levels, indexing="ij")
    return np.stack([g.reshape(-1) for g in grids], axis=-1)


# Per-dispatch device-output budget: bounds the combination block so a
# huge tree (e.g. 256^3 combos with a Sum aggregate) streams in fixed-
# size pieces instead of materializing int32[C, n_last, depth] at once.
BLOCK_OUT_BYTES = 64 << 20
# Smaller blocks when a limit= may stop the stream after the first few
# groups — trades a couple of extra dispatches for early exit.
LIMIT_BLOCK = 1024


def iter_blocks(specs, filter_words, agg_plane, agg_kind,
                limited: bool = False, run=None, agg_delta=None):
    """Execute the program over lexicographic combination blocks.

    specs: list of (field, rows np.ndarray, PlaneSet); the last spec is
    the vectorized innermost level.  Yields (combo_rows uint64[B, L-1],
    outputs dict of np arrays) in combination order; callers stop
    consuming once a ``limit=`` is satisfied.  Blocks are padded to one
    static shape (single compile), the pad tail is sliced off here.

    ``run`` (r20): an alternative block dispatcher with the
    ``_groupby_program`` signature returning a dict of HOST arrays —
    the executor routes blocks through the batcher's collection
    window here, so a GroupBy block shares its dispatch window and
    packed readback with concurrent Counts/aggregates instead of
    interleaving solo device round trips.
    """
    *prefix_specs, (last_f, last_rows, last_ps) = specs
    slot_levels = [np.array([ps.slot_of[int(r)] for r in rows], np.int32)
                   for _, rows, ps in prefix_specs]
    row_levels = [np.asarray(rows, np.uint64) for _, rows, _ in prefix_specs]
    combo_slots = combo_grid(slot_levels).astype(np.int32)
    combo_rows = combo_grid(row_levels)
    n_combos = combo_slots.shape[0]

    n_last = last_ps.plane.shape[1]
    per_combo = n_last * 4
    if agg_kind == "sum":
        depth = agg_plane.plane.shape[1] - 2
        per_combo += n_last * (2 * depth + 1) * 4
    elif agg_kind == "minmax":
        per_combo += n_last * 16
    block = max(1, min(n_combos, BLOCK_OUT_BYTES // per_combo,
                       *([LIMIT_BLOCK] if limited else [])))

    planes = tuple(ps.plane for _, _, ps in prefix_specs)
    aplane = agg_plane.plane if agg_plane is not None else None
    if run is None:
        def run(pl, ci, lp, fw, ap, agg, ad):
            at = ((ad.col_shard, ad.col_word, ad.col_vals,
                   ad.col_mask) if ad is not None else None)
            return _groupby_program(pl, ci, lp, fw, ap, agg,
                                    agg_delta=at)
    for start in range(0, n_combos, block):
        sl = combo_slots[start:start + block]
        n = sl.shape[0]
        if n < block:  # pad to the compiled shape; tail dropped below
            sl = np.concatenate([sl, np.repeat(sl[-1:], block - n, axis=0)])
        # the combo block stays a HOST array here: the batcher route
        # hashes it for dedupe (a device array would force a blocking
        # D2H read per block just to digest bytes that originated
        # host-side), and jit converts it on dispatch either way
        out = run(planes, sl, last_ps.plane,
                  filter_words, aplane, agg_kind, agg_delta)
        yield (combo_rows[start:start + n],
               {k: np.asarray(v)[:n] for k, v in out.items()})
