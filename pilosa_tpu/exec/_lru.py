"""Shared lock-free recency stamps for the serving caches.

The fused-program cache and the device plane cache both keep their HIT
path lock-free (a plain-dict read plus a recency-stamp write, both
GIL-atomic) and take their own lock only to insert and evict.  The
stamp bookkeeping — including the evict-then-touch race, where a
``touch`` that lost the race against an eviction re-inserts an orphan
stamp — lives here so it is handled once, identically, for both.
"""

from __future__ import annotations

import itertools


class Stamps:
    """Approximate-LRU recency stamps.

    Thread contract: :meth:`touch` may run WITHOUT the owner's lock —
    it only writes an existing key (no dict resize), except when it
    loses the race against a concurrent eviction, in which case it
    re-inserts an orphan entry (cleaned by :meth:`cleanup`).  Every
    other method runs under the owner cache's lock."""

    def __init__(self):
        self._stamp: dict = {}
        self._tick = itertools.count()

    def touch(self, key) -> None:
        if key in self._stamp:
            self._stamp[key] = next(self._tick)

    def insert(self, key) -> None:
        self._stamp[key] = next(self._tick)

    def pop(self, key) -> None:
        self._stamp.pop(key, None)

    def get(self, key, default: int = 0) -> int:
        return self._stamp.get(key, default)

    def snapshot(self) -> list:
        """Items snapshot that tolerates a racing lock-free touch
        re-inserting a key mid-iteration (retry; the window is a few
        instructions)."""
        while True:
            try:
                return list(self._stamp.items())
            except RuntimeError:
                continue

    def cleanup(self, live) -> None:
        """Drop orphan stamps (keys no longer in the owning cache)."""
        for k, _ in self.snapshot():
            if k not in live:
                self._stamp.pop(k, None)

    def clear(self) -> None:
        self._stamp.clear()
