"""Query result types.

Reference: ``row.go`` / ``executor.go`` result values — ``Row``,
``PairsField`` (TopN), ``ValCount`` (Sum/Min/Max), ``GroupCount``,
plus plain bool/int for writes and Count (SURVEY.md §3.2).  Each type
knows its REST JSON shape (``http/handler.go`` response encoding).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field

import numpy as np


@dataclass
class RowResult:
    """Set of columns (one PQL bitmap call's result), already translated
    to absolute column IDs; ``keys`` filled instead when the index is
    keyed."""

    columns: np.ndarray = dc_field(
        default_factory=lambda: np.empty(0, np.uint64))
    keys: list[str] | None = None
    attrs: dict | None = None  # column attrs (Options columnAttrs=true)
    row_attrs: dict | None = None  # the queried row's attributes
    # (reference: v1 Row.Attrs; suppressed by excludeRowAttrs=true)

    def to_json(self):
        out = ({"keys": self.keys} if self.keys is not None
               else {"columns": [int(c) for c in self.columns]})
        if self.attrs is not None:
            out["attrs"] = {str(k): v for k, v in self.attrs.items()}
        if self.row_attrs is not None:
            out["rowAttrs"] = self.row_attrs
        return out


@dataclass
class Pair:
    id: int = 0
    key: str | None = None
    count: int = 0

    def to_json(self):
        if self.key is not None:
            return {"key": self.key, "count": self.count}
        return {"id": self.id, "count": self.count}


@dataclass
class PairsResult:
    """TopN result."""

    pairs: list[Pair]

    def to_json(self):
        return [p.to_json() for p in self.pairs]


@dataclass
class ValCount:
    """Sum/Min/Max result: aggregate value + contributing column count."""

    value: int | float = 0
    count: int = 0

    def to_json(self):
        return {"value": self.value, "count": self.count}


@dataclass
class RowIdsResult:
    """``Rows()`` result: row IDs (or keys) of a field."""

    rows: np.ndarray = dc_field(
        default_factory=lambda: np.empty(0, np.uint64))
    keys: list[str] | None = None

    def to_json(self):
        if self.keys is not None:
            return {"keys": self.keys}
        return {"rows": [int(r) for r in self.rows]}


@dataclass
class DistinctResult:
    """``Distinct()`` result: sorted distinct BSI field values
    (reference: v2 SignedRow-valued Distinct)."""

    values: list

    def to_json(self):
        return {"values": self.values}


@dataclass
class FieldRow:
    field: str
    row_id: int = 0
    row_key: str | None = None

    def to_json(self):
        if self.row_key is not None:
            return {"field": self.field, "rowKey": self.row_key}
        return {"field": self.field, "rowID": self.row_id}


@dataclass
class GroupCount:
    group: list[FieldRow]
    count: int
    agg: int | None = None  # aggregate value when GroupBy has one

    def to_json(self):
        out = {"group": [g.to_json() for g in self.group], "count": self.count}
        if self.agg is not None:
            out["agg"] = self.agg
        return out


class GroupCountsResult:
    """GroupBy result held COLUMNAR (a row-id matrix plus count/agg
    arrays) with ``GroupCount`` objects materialized lazily.

    Rationale (reference: ``executor.go#executeGroupBy`` returns
    ``[]GroupCount`` eagerly): a 125k-group GroupBy spent ~1 s of its
    1.7 s warm latency constructing per-group dataclass objects after
    the aggregate math was already vectorized.  Arrays in, objects only
    at the access/serialization edge.

    Columnar form: ``fields`` (one name per Rows level), ``row_ids``
    int64[G, L], optional per-level ``row_keys`` (translated key lists
    for keyed fields), ``counts`` int64[G], optional ``aggs`` [G]
    (int64 or object dtype for big ints) with ``agg_mask`` marking
    which groups carry a valid aggregate.
    """

    __slots__ = ("_groups", "fields", "row_ids", "row_keys", "counts",
                 "aggs", "agg_mask")

    def __init__(self, groups: list[GroupCount] | None = None, *,
                 fields: list[str] | None = None, row_ids=None,
                 row_keys: list | None = None, counts=None, aggs=None,
                 agg_mask=None):
        self._groups = groups
        self.fields = fields or []
        self.row_ids = row_ids
        self.row_keys = row_keys
        self.counts = counts
        self.aggs = aggs
        self.agg_mask = agg_mask

    def __eq__(self, other):
        return (isinstance(other, GroupCountsResult)
                and self.groups == other.groups)

    def __len__(self):
        if self._groups is not None:
            return len(self._groups)
        return 0 if self.row_ids is None else len(self.row_ids)

    @property
    def groups(self) -> list[GroupCount]:
        if self._groups is None:
            self._groups = [
                GroupCount([FieldRow(f, row_key=k) if k is not None
                            else FieldRow(f, row_id=r)
                            for f, r, k in zip(self.fields, rows, keys)],
                           count, agg)
                for rows, keys, count, agg in self._iter_columns()]
        return self._groups

    def _iter_columns(self):
        """Yield (row_ids, row_keys, count, agg|None) per group from the
        columnar store, converting numpy scalars to Python ints once."""
        ids = self.row_ids.tolist()
        counts = self.counts.tolist()
        n_levels = len(self.fields)
        keys_by_level = self.row_keys or [None] * n_levels
        aggs = None
        if self.aggs is not None:
            aggs = self.aggs.tolist()
            mask = (self.agg_mask.tolist() if self.agg_mask is not None
                    else [True] * len(aggs))
        for i, (rows, count) in enumerate(zip(ids, counts)):
            keys = [kl[i] if kl is not None else None
                    for kl in keys_by_level]
            agg = aggs[i] if aggs is not None and mask[i] else None
            yield rows, keys, count, agg

    def to_json(self):
        if self._groups is not None:
            return [g.to_json() for g in self._groups]
        out = []
        for rows, keys, count, agg in self._iter_columns():
            group = [{"field": f, "rowKey": k} if k is not None
                     else {"field": f, "rowID": r}
                     for f, r, k in zip(self.fields, rows, keys)]
            g = {"group": group, "count": count}
            if agg is not None:
                g["agg"] = agg
            out.append(g)
        return out


@dataclass
class ExtractResult:
    """``Extract()`` result (reference: v2 ``ExtractedTable`` — shape
    reconstructed from memory of the upstream JSON surface): per
    selected column, each requested field's value(s)."""

    field_specs: list[tuple[str, str]]  # (name, type)
    columns: list  # (column id | key, [per-field value])

    def to_json(self):
        return {
            "fields": [{"name": n, "type": t} for n, t in self.field_specs],
            "columns": [
                ({"key": c, "rows": vals} if isinstance(c, str)
                 else {"column": int(c), "rows": vals})
                for c, vals in self.columns],
        }


def result_to_json(r):
    """Any handler result -> JSON-able value (bool/int pass through)."""
    if hasattr(r, "to_json"):
        return r.to_json()
    return r
