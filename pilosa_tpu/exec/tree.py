"""Whole-tree PQL compilation: compound boolean queries as ONE program.

PAPER.md frames the rebuild as "container ops become XLA
bitwise+popcount kernels", but until r16 only leaf Count/TopN/
selected-count shapes rode the fused/batched device path — a compound
``Count(Intersect(Row, Union(Row, Row), Not(Row)))``, the bread and
butter of segmentation queries at 1B cols, materialized one per-row
cache entry per leaf and compiled one program per distinct tree
STRUCTURE.  This module is the tree planner: it lowers a parsed
compound call to a canonical kernel spec —

- **rows gathered as traced operands**: every plain ``Row`` leaf of the
  anchor field becomes a slot index into the ONE resident field plane
  (``uint32[S, R_pad, W]``); the kernel gathers them in-program, so no
  per-leaf arrays are built and the gather rides the plane's delta
  overlay (base⊕delta, rebuild-free under sustained ingest);
- **ops as a small postfix/ALU program** (:mod:`engine.kernels` tree
  opcodes) the kernel folds over each word block — the program is a
  traced ``int32[K, P, 2]`` operand, so ANY tree shape whose pow2
  buckets (gathered width, program length, item count) match reuses
  one compiled executable;
- **common-subexpression elimination inside one request**: duplicate
  leaves (same row, same BSI predicate, repeated ``All``/exists)
  collapse to one operand; across concurrent requests the batcher's
  tree kind unions slot sets (:func:`assemble_items`), so N windowed
  compound queries over the same plane still cost one memory pass and
  one packed readback.

What lowers: ``Intersect/Union/Difference/Xor/Not/UnionRows`` trees
over plain set-field rows, with BSI range conditions as leaf row
filters (predicate bitmaps enter as extra operands), ``All`` as the
existence row, time-range rows as extra operands off the bucketed time
plane (r23, ``pilosa_tpu.timeviews``), ``ConstRow`` as a literal extra
operand, and ``Shift``/``Limit`` as STATIC postfix ops folded into the
skeleton (their arguments are compiled structure, like the fused
"shift" node's ``n``).  What falls back (``Unfusable`` → the generic
fused / eager paths, identical answers): trees with no plain-row leaf
to anchor the gather (pure time/ConstRow trees ride the generic fused
planner instead), and trees deeper than the fixed operand stack or
longer than ``TREE_MAX_PROG`` steps.
"""

from __future__ import annotations

from dataclasses import dataclass

from pilosa_tpu.engine.kernels import (TREE_AND, TREE_LIMIT, TREE_PUSH,
                                       TREE_PUSHX, TREE_SHIFT,
                                       TREE_STACK_DEPTH,
                                       TREE_STATIC_OPS, TREE_ZERO)
from pilosa_tpu.exec.fused import Unfusable
from pilosa_tpu.pql.ast import BETWEEN_OPS, BOOL_CALLS, Call, Condition
from pilosa_tpu.store.field import BSI_TYPES
from pilosa_tpu.store.view import VIEW_STANDARD

# the compound-call names the tree compiler owns (a bare Row/All Count
# keeps the existing selected/whole-plane serving spine)
TREE_CALLS = (frozenset(BOOL_CALLS)
              | {"Not", "UnionRows", "Shift", "Limit", "ConstRow"})

# program-length cap: a UnionRows over thousands of rows would explode
# the postfix program (and its pow2 bucket); past this the tree falls
# back to the generic path, which unions through rows_plane
TREE_MAX_PROG = 96

# op token -> tree opcode (TREE_AND + offset into the shared order;
# pql.ast.BOOL_CALLS is the name->token source of truth)
_OP_CODE = {"and": TREE_AND, "or": TREE_AND + 1,
            "andnot": TREE_AND + 2, "xor": TREE_AND + 3}

_NOT_BOOL = object()


def fold_bool_call(call: Call, recurse, zeros, exists, combine,
                   complement):
    """Shared structure + edge semantics of the boolean-algebra
    operators — with :data:`pql.ast.BOOL_CALLS`, THE single source of
    truth the eager path (``Executor._bitmap``), both fused planners
    (``_plan``/``_plan_spec``) and this tree compiler all fold
    through:

    - ``Union()`` with no children is the empty bitmap (``zeros()``);
    - every other operator requires >= 1 child, and with exactly one
      child IS that child (``Difference(x) == x``);
    - ``Not`` is unary and evaluates as ``andnot(exists, x)``;
      ``complement(exists_thunk, child_thunk)`` controls evaluation
      ORDER (the postfix lowering must push ``exists`` first);
    - n-ary operators call ``combine(op, child_thunks)`` ONCE with
      every child as a thunk — sites fold pairwise (eager, postfix)
      or build one FLAT n-ary node (the planners): a per-child nested
      pair would recurse once per child downstream and blow the
      recursion limit on wide flat calls (a 1000-child Union is
      legal PQL).

    Returns the folded site-specific value, or :data:`_NOT_BOOL` when
    ``call`` is not a boolean-algebra operator (use
    :func:`is_not_bool` to test; callers fall through to their leaf
    handling)."""
    from pilosa_tpu.exec.executor import ExecutionError
    name = call.name
    if name == "Not":
        if len(call.children) != 1:
            raise ExecutionError("Not: exactly one child required")
        return complement(exists, lambda: recurse(call.children[0]))
    op = BOOL_CALLS.get(name)
    if op is None:
        return _NOT_BOOL
    kids = call.children
    if not kids:
        if name == "Union":
            return zeros()
        raise ExecutionError(f"{name}: at least one child required")
    return combine(op, tuple((lambda k=kid: recurse(k))
                             for kid in kids))


def is_not_bool(value) -> bool:
    return value is _NOT_BOOL


@dataclass(frozen=True)
class TreeSpec:
    """One compound Count tree as a canonical, hashable kernel spec —
    the plan-cache unit for tree shapes (r16).  Nothing here is a
    device array: ``rows`` re-resolve to plane slots and ``extras``
    re-materialize through the plane cache on every hit, so the spec
    survives writes exactly as far as its validity flags allow."""

    field: str        # anchor set field whose plane rows are gathered
    rows: tuple       # gathered row ids (first-use order, CSE-deduped)
    extras: tuple     # extra operand specs (see _Lower._extra)
    prog: tuple       # ((opcode, arg), ...) postfix; args: rows ++ extras
    depth: int        # operator nesting depth (tree_fusion_depth)
    cse_hits: int     # duplicate leaves collapsed inside this request
    volatile: bool    # row-set resolution depends on data (UnionRows)
    keyed_rows: bool  # some row id came from a key translation
    bsi_depths: tuple  # ((field, bit_depth), ...) predicate bakes
    static_ops: int = 0  # Shift/Limit ops folded into the skeleton


class _Lower:
    """One lowering pass: call tree → postfix program + operand pools.

    Emission tracks the simulated stack pointer; a tree that would
    exceed the kernel's fixed :data:`TREE_STACK_DEPTH` or
    :data:`TREE_MAX_PROG` raises :class:`Unfusable` (falls back)."""

    def __init__(self, ex, ctx):
        self.ex = ex
        self.ctx = ctx
        self.field = None              # anchor Field (first set leaf)
        self.rows: dict[int, int] = {}          # row id -> arg pos
        self.extras: dict[tuple, int] = {}      # extra spec -> pos
        self.prog: list = []
        self.sp = 0
        self.max_sp = 0
        self.depth = 0
        self.cse_hits = 0
        self.volatile = False
        self.keyed_rows = False
        self.bsi_depths: dict[str, int] = {}
        self.static_ops = 0

    # -- emission -----------------------------------------------------------

    def _emit(self, op: int, arg=0) -> None:
        if op in TREE_STATIC_OPS:
            self.static_ops += 1  # unary: pop one, push one (sp-neutral)
        elif op >= TREE_AND:
            self.sp -= 1
        else:  # PUSH / ZERO
            self.sp += 1
        self.max_sp = max(self.max_sp, self.sp)
        if self.max_sp > TREE_STACK_DEPTH:
            raise Unfusable("tree deeper than the fused operand stack")
        if len(self.prog) >= TREE_MAX_PROG:
            raise Unfusable("tree program longer than TREE_MAX_PROG")
        self.prog.append((op, arg))

    def _extra(self, spec: tuple):
        pos = self.extras.get(spec)
        if pos is None:
            pos = self.extras[spec] = len(self.extras)
        else:
            self.cse_hits += 1
        return ("e", pos)

    def _push_exists(self) -> None:
        from pilosa_tpu.exec.executor import ExecutionError
        if self.ctx.index.existence_field is None:
            # same query error, same text, as the eager path's _exists
            raise ExecutionError(
                f"index {self.ctx.index.name!r} does not track existence "
                "(required for Not/All)")
        self._emit(TREE_PUSH, self._extra(("exists",)))

    def _push_field_row(self, field, row_id: int) -> None:
        if self.field is None:
            self.field = field
        if field.name == self.field.name:
            pos = self.rows.get(row_id)
            if pos is None:
                pos = self.rows[row_id] = len(self.rows)
            else:
                self.cse_hits += 1
            self._emit(TREE_PUSH, ("r", pos))
            return
        # rows of OTHER set fields enter as extra operands
        # (row_words re-fetches fresh through the plane cache per hit)
        self._emit(TREE_PUSH, self._extra(
            ("row", field.name, VIEW_STANDARD, row_id)))

    # -- call walk ----------------------------------------------------------

    def lower(self, call: Call, depth: int) -> None:
        name = call.name
        if name in ("Row", "Range"):
            self._leaf(call)
            return
        if name == "All":
            self._push_exists()
            return
        self.depth = max(self.depth, depth)
        if name == "UnionRows":
            self._union_rows(call)
            return
        if name == "Shift":
            from pilosa_tpu.exec.executor import ExecutionError
            if len(call.children) != 1:
                raise ExecutionError("Shift: exactly one child required")
            n = self.ex._shift_n(call)  # validates, same errors as eager
            self.lower(call.children[0], depth + 1)
            self._emit(TREE_SHIFT, n)
            return
        if name == "Limit":
            self._limit(call, depth)
            return
        if name == "ConstRow":
            self._const_row(call)
            return
        def emit_fold(op, kids):
            kids[0]()
            for child in kids[1:]:
                child()
                self._emit(_OP_CODE[op])

        out = fold_bool_call(
            call,
            recurse=lambda c: self.lower(c, depth + 1),
            zeros=lambda: self._emit(TREE_ZERO),
            exists=self._push_exists,
            combine=emit_fold,
            complement=lambda exists, child: (exists(), child(),
                                              self._emit(
                                                  _OP_CODE["andnot"])))
        if is_not_bool(out):
            raise Unfusable(f"{name} is not tree-compiled")

    def _leaf(self, call: Call) -> None:
        from pilosa_tpu.exec.executor import ExecutionError, _field_arg
        hit = _field_arg(call)
        if hit is None:
            raise ExecutionError(f"{call.name}: missing field argument")
        fname, value = hit
        field = self.ex._field(self.ctx, fname)
        if isinstance(value, Condition) or field.options.type in BSI_TYPES:
            cond = (value if isinstance(value, Condition)
                    else Condition("==", value))
            self._bsi(field, cond)
            return
        if field.options.keys:
            self.keyed_rows = True
        row_id = self.ex._row_id(self.ctx, field, value, create=False)
        if row_id is None:
            self._emit(TREE_ZERO)
            return
        if ("from" in call.args or "to" in call.args
                or "_timestamp" in call.args):
            # time-range rows (r23): one extra operand off the bucketed
            # time plane — a fused OR-scan over the contiguous bucket
            # range, the oracle path when the plane isn't resident.
            # Order matters: the eager path resolves the row FIRST
            # (unknown row → zeros, never a not-a-time-field error).
            if not field.options.time_quantum:
                raise ExecutionError(
                    f"field {field.name!r} is not a time field")
            frm = call.args.get("from", call.args.get("_timestamp"))
            to = call.args.get("to", call.args.get("_timestamp2"))
            self._emit(TREE_PUSH, self._extra(
                ("trange", field.name, int(row_id),
                 None if frm is None else str(frm),
                 None if to is None else str(to))))
            return
        self._push_field_row(field, int(row_id))

    def _union_rows(self, call: Call) -> None:
        from pilosa_tpu.exec.executor import ExecutionError
        bad = [c.name for c in call.children if c.name != "Rows"]
        if bad:
            raise ExecutionError(
                f"UnionRows: children must be Rows calls, got {bad}")
        if not call.children:
            raise ExecutionError("UnionRows: Rows children required")
        # the row SET comes from data, not query text: the spec cannot
        # survive writes (a new row must join the union on next plan)
        self.volatile = True
        n = 0
        for rc in call.children:
            fname = rc.args.get("_field") or rc.args.get("field")
            field = self.ex._field(self.ctx, str(fname))
            for r in self.ex._rows_of(self.ctx, field, rc):
                self._push_field_row(field, int(r))
                n += 1
                if n > 1:
                    self._emit(_OP_CODE["or"])
        if n == 0:
            self._emit(TREE_ZERO)

    def _limit(self, call: Call, depth: int) -> None:
        """``Limit(x, limit=, offset=)`` as a STATIC postfix op: the
        rank-window kernel (``engine.kernels.rank_limit``) keeps bits
        by global column rank in-program — the host column round trip
        the eager ``_limit_bitmap`` pays disappears.  Bounds are
        compiled structure (skeleton key), like Shift's ``n``."""
        from pilosa_tpu.exec.executor import ExecutionError
        if len(call.children) != 1:
            raise ExecutionError(
                "Limit: exactly one bitmap child required")
        offset = int(call.args.get("offset", 0))
        limit = call.args.get("limit")
        if offset < 0 or (limit is not None and int(limit) < 0):
            raise ExecutionError("Limit: limit/offset must be >= 0")
        self.lower(call.children[0], depth + 1)
        self._emit(TREE_LIMIT,
                   (offset, -1 if limit is None else int(limit)))

    def _const_row(self, call: Call) -> None:
        """``ConstRow(columns=[...])`` as a literal extra operand; key
        columns translate per hit (the plan survival rules mark keyed
        specs non-survivable, same as keyed rows)."""
        from pilosa_tpu.exec.executor import ExecutionError
        cols = call.args.get("columns")
        if cols is None:
            raise ExecutionError("ConstRow: missing columns argument")
        if any(isinstance(c, str) for c in cols):
            self.keyed_rows = True
        self._emit(TREE_PUSH, self._extra(("constrow", tuple(cols))))

    def _bsi(self, field, cond: Condition) -> None:
        from pilosa_tpu.exec.executor import (_SCALAR_TO_KEY,
                                              ExecutionError)
        if field.options.type not in BSI_TYPES:
            raise ExecutionError(
                f"field {field.name!r}: condition on non-BSI field")
        self.bsi_depths[field.name] = field.options.bit_depth
        if cond.op in BETWEEN_OPS:
            lo_op = "gt" if cond.op.startswith("<>") else "ge"
            hi_op = "lt" if cond.op.endswith("><") else "le"
            self._bsi_cmp(field, lo_op, cond.value[0])
            self._bsi_cmp(field, hi_op, cond.value[1])
            self._emit(_OP_CODE["and"])
            return
        self._bsi_cmp(field, _SCALAR_TO_KEY[cond.op], cond.value)

    def _bsi_cmp(self, field, op_key: str, value) -> None:
        opts = field.options
        depth = opts.bit_depth
        offset = field.to_stored(value) - opts.base
        bound = (1 << depth) - 1
        if offset > bound or offset < -bound:
            # saturated predicate: everything-not-null or nothing.
            # The baked verdict depends on bit_depth — bsi_depths
            # validity drops the spec when a write grows the depth.
            all_hit = ((op_key in ("lt", "le", "ne")) if offset > bound
                       else (op_key in ("gt", "ge", "ne")))
            if all_hit:
                self._emit(TREE_PUSH, self._extra(
                    ("bsi-exists", field.name)))
            else:
                self._emit(TREE_ZERO)
            return
        # masks/sign re-derive from (offset, depth) per hit — pure
        # functions of query text + the depth the validity rules pin
        self._emit(TREE_PUSH, self._extra(
            ("bsi", field.name, op_key, int(offset))))


def lower_count_tree(ex, ctx, call: Call) -> TreeSpec:
    """Lower one compound bitmap call (a ``Count`` child) to a
    canonical :class:`TreeSpec`.  Raises :class:`Unfusable` for shapes
    the tree path doesn't cover (callers fall back to the generic
    fused / eager paths) and ``ExecutionError`` for genuine query
    errors — identically to the other planners, so fused and
    op-at-a-time agree on edge semantics."""
    low = _Lower(ex, ctx)
    low.lower(call, 1)
    if low.field is None:
        raise Unfusable("no plain-row leaf to anchor the plane gather")
    # resolve symbolic push args: rows stay TREE_PUSH (arg = row
    # position), extras become TREE_PUSHX (arg = extra position) —
    # statically distinct opcodes so the fused skeleton knows which
    # operand stack each push reads
    prog = tuple(
        ((TREE_PUSH, arg[1]) if arg[0] == "r" else (TREE_PUSHX, arg[1]))
        if (op == TREE_PUSH and isinstance(arg, tuple)) else (op, arg)
        for op, arg in low.prog)
    return TreeSpec(field=low.field.name, rows=tuple(low.rows),
                    extras=tuple(low.extras), prog=prog,
                    depth=low.depth, cse_hits=low.cse_hits,
                    volatile=low.volatile, keyed_rows=low.keyed_rows,
                    bsi_depths=tuple(low.bsi_depths.items()),
                    static_ops=low.static_ops)


def assemble_items(items) -> tuple:
    """Union the items' gathered plane slots and extra arrays and
    remap every postfix program into the shared operand space — the
    cross-request half of CSE: N windowed compound queries over
    overlapping rows of one plane pay ONE gather of the slot union
    (``exec.batcher`` tree kind) and duplicate extra arrays (same
    exists row, same predicate bitmap) enter once.

    ``items``: sequence of ``(slots, prog, extras)`` where ``slots``
    are plane row slots, PUSH args address that item's ``slots`` and
    PUSHX args its ``extras``.  Returns ``(slot_union, progs,
    extra_arrays)`` in :meth:`FusedCache.run_tree_counts` operand
    order (PUSH args index the union; PUSHX args the extra list)."""
    slot_pos: dict[int, int] = {}
    extra_pos: dict[int, int] = {}
    extra_arrays: list = []
    for slots, _prog, extras in items:
        for s in slots:
            if s not in slot_pos:
                slot_pos[s] = len(slot_pos)
        for a in extras:
            if id(a) not in extra_pos:
                extra_pos[id(a)] = len(extra_arrays)
                extra_arrays.append(a)
    progs = []
    for slots, prog, extras in items:
        out = []
        for op, arg in prog:
            if op == TREE_PUSH:
                arg = slot_pos[slots[arg]]
            elif op == TREE_PUSHX:
                arg = extra_pos[id(extras[arg])]
            out.append((op, arg))
        progs.append(tuple(out))
    return tuple(slot_pos), tuple(progs), tuple(extra_arrays)
