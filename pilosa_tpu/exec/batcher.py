"""Cross-request coalescing: the concurrent serving spine.

Within-request batching (executor count runs) amortizes fixed
per-dispatch/per-read costs across one query string; this batcher does
the same ACROSS concurrent requests: server threads submit planned
work items, a collector waits a tiny window, and one fused program per
(kind, shape) group answers the whole batch with a single device read.

Motivation (BASELINE.md): transports can impose a fixed cost per
synchronous device read (~100ms on this image's tunnel; ~10us on local
hardware).  When reads SERIALIZE, N coalesced items pay that cost once
instead of N times — and past the tunnel's device-stream limit the
batcher funnels any number of HTTP clients through ONE device stream.

r6 changes (the concurrency-gap work, ISSUE 1):

- **default-on with an ADAPTIVE window**: the window grows under queue
  pressure (concurrent submitters pile into one dispatch) and shrinks
  to zero when traffic is solo, so a lone request pays no collection
  wait.  ``count_batch_window=adaptive`` is the server default; a
  numeric value keeps the old fixed-window behavior, 0 disables.
- **every one-dispatch-one-read dense family coalesces**: Counts (any
  fusable tree, BSI conditions included), BSI Sum/Min/Max, whole-plane
  row counts (same-field Count batches and dense TopN — deduplicated:
  N concurrent requests over the SAME resident plane share one
  program and one read instead of stacking N copies of a multi-GB
  popcount), and Distinct presence scans (deduplicated likewise).

r12 changes (the roofline work, ISSUE 7):

- **selected-row counts** (``submit_selected``): the multi-query fused
  popcount — concurrent requests' row slots union into ONE gather +
  popcount pass over just those rows' memory;
- **batched readback**: every one-program kind dispatches async and
  the window's outputs pack into ONE device array read with ONE
  device->host transfer — the window pays the per-read RPC floor once
  total, not once per kind/shape group.

r17 changes (the solo-floor/roofline work, ISSUE 12):

- **pipelined readback**: the collector hands each dispatched window
  to a dedicated readback worker, so window N's device compute
  overlaps window N-1's packed read instead of serializing behind it
  (``pipeline_depth`` bounds run-ahead; ``dispatch_pipeline_depth`` /
  ``readback_overlap_ratio`` on /metrics);
- **solo fast lane**: a width-1 request with no queue pressure skips
  window formation and dispatches inline on the CALLER's thread over
  pre-bound operands (``solo_fastlane_hits_total``) — the attack on
  the one-RPC-per-query solo floor;
- **donated ping-pong chains**: the window and fast-lane dispatch
  paths pass retired output buffers back as donated scratch
  (``fused.PingPong``), so consecutive dispatches re-use two standing
  output slots instead of allocating per window, and the selcounts
  union gathers in SORTED slot order (ascending memory stride).
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from pilosa_tpu.engine import kernels


class _Pending:
    __slots__ = ("kind", "nodes", "leaves", "delta", "event", "result",
                 "error")

    def __init__(self, kind, nodes, leaves, delta=None):
        self.kind = kind      # "count" | "sum" | "minmax" | "rowcounts"
        #                       | "selcounts" | "tree" | "distinct"
        self.nodes = nodes    # count: tuple of plan trees;
        #                       selcounts: tuple of plane row slots;
        #                       tree: (slots, postfix prog, extras);
        #                       others: None
        self.leaves = leaves  # count: plan leaves; others: plane[, filter]
        self.delta = delta    # rowcounts/selcounts: the plane's
        #                       DeltaOverlay (base⊕delta merge, r15)
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class CountBatcher:
    """Cross-request coalescing for Count, the BSI aggregates
    (Sum/Min/Max), whole-plane row counts, and Distinct — each
    kind/shape group in one collection window runs as one fused
    program + one read."""

    # adaptive-window bounds: MIN is the smallest non-zero window (below
    # it the window snaps to 0 — solo traffic must not wait at all);
    # MAX bounds queue-pressure growth so a burst can't add visible
    # latency to its own tail
    ADAPT_MIN = 0.0005
    ADAPT_MAX = 0.005

    def __init__(self, fused, window_s="adaptive", max_batch: int = 64,
                 stats=None, pipeline_depth: int = 2,
                 solo_fastlane: bool = True):
        from pilosa_tpu.exec.fused import PingPong
        from pilosa_tpu.obs import NopStats
        from pilosa_tpu.obs.metrics import (BYTE_BUCKETS, COUNT_BUCKETS,
                                            RATIO_BUCKETS)
        self.fused = fused
        self.adaptive = window_s == "adaptive"
        self.window_s = 0.0 if self.adaptive else float(window_s)
        self._win = 0.0 if self.adaptive else self.window_s
        self.max_batch = max_batch
        self.stats = stats or NopStats()
        # device-plane telemetry (r14): window occupancy and fill are
        # item counts / ratios, not latencies — declare their bucket
        # sets up front (idempotent; see Stats.set_buckets)
        self.stats.set_buckets("batcher_window_items", COUNT_BUCKETS)
        self.stats.set_buckets("batcher_window_fill_ratio", RATIO_BUCKETS)
        self.stats.set_buckets("kernel_window_bytes", BYTE_BUCKETS)
        self.stats.set_buckets("readback_overlap_ratio", RATIO_BUCKETS)
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool = None  # persistent group-dispatch pool (lazy)
        # pipelined readback (r17 tentpole): the collector hands each
        # dispatched window to a dedicated readback worker, so window
        # N's device compute overlaps window N-1's packed device->host
        # read instead of serializing behind it.  ``pipeline_depth``
        # bounds dispatched-but-unread windows via the _pipe_slots
        # semaphore (taken before a window dispatches, released when
        # its readback finishes); depth <= 1 restores the pre-r17
        # inline readback.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._readq: queue.Queue | None = (
            queue.Queue() if self.pipeline_depth > 1 else None)
        # the actual run-ahead bound: a slot is taken BEFORE a
        # window's groups dispatch and released when its readback
        # finishes, so dispatched-but-unread windows can never exceed
        # pipeline_depth (a queue-size bound alone would let the
        # collector dispatch one extra window past the put)
        self._pipe_slots = threading.Semaphore(self.pipeline_depth)
        self._read_thread: threading.Thread | None = None
        # dispatched-but-unread windows; collector increments, reader
        # decrements — locked, a lost update would permanently skew
        # the depth gauge and the overlap observations
        self._inflight_windows = 0
        self._pipe_lock = threading.Lock()
        # solo fast lane (r17 tentpole): with no queue pressure, a
        # width-1 request skips window formation entirely and rides a
        # pre-bound dispatch chain on the CALLER's thread — no enqueue,
        # no worker wakeup, no cross-thread event round-trip
        self.solo_fastlane = bool(solo_fastlane)
        # concurrent fast-lane dispatches in flight: the lane admits
        # only when it is ZERO, so overlapping callers fall into the
        # collection window instead — that pile-up is the adaptive
        # window's pressure signal, and coalescing (dedupe + one scan
        # per window) must keep winning under real concurrency
        self._fl_active = 0
        self._fl_lock = threading.Lock()
        # donated ping-pong output chains shared by the windowed and
        # fast-lane dispatch paths (see fused.PingPong)
        self._pp = PingPong()

    def _group_pool(self):
        # persistent: a pool built and torn down per collection window
        # would put thread churn back on the very hot loop this
        # batcher exists to strip of per-request overhead
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="pilosa-batch-group")
        return self._pool

    @property
    def current_window(self) -> float:
        return self._win

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="pilosa-count-batcher",
                                            daemon=True)
            self._thread.start()

    def _enqueue(self, p: _Pending) -> _Pending:
        with self._lock:
            self._queue.append(p)
            self._ensure_worker()
        self._kick.set()
        return p

    def wait(self, p: _Pending):
        """Block on an enqueued item's result (pairs with the
        ``enqueue_*`` methods — a caller that needs several items can
        enqueue them ALL into one collection window before waiting on
        any, instead of serializing one window per item)."""
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _submit(self, p: _Pending):
        return self.wait(self._enqueue(p))

    # -- solo fast lane (r17) ------------------------------------------------

    def _fl_try_enter(self) -> bool:
        """Atomically admit ONE fast-lane dispatch: fast lane enabled,
        adaptive window currently snapped to 0 (traffic is solo —
        under queue pressure the window grows and coalescing wins),
        nothing already queued to join, and no other fast-lane
        dispatch in flight — the admission check and the in-flight
        increment happen under one lock, so two simultaneous callers
        can never both take the lane (the loser lands in the window,
        which is the adaptive pressure signal).  A True return must
        be paired with :meth:`_fl_leave`."""
        if not (self.solo_fastlane and self.adaptive
                and self._win == 0.0 and not self._queue):
            return False
        with self._fl_lock:
            if self._fl_active:
                return False
            self._fl_active += 1
        return True

    def _fl_leave(self) -> None:
        with self._fl_lock:
            self._fl_active -= 1

    def _fastlane_done(self, kind: str, nbytes: int) -> None:
        # NO kernel_dispatch_seconds here: that family observes
        # enqueue-only on the windowed path (the read is deferred to
        # the packed readback), while a fast-lane call spans dispatch
        # PLUS the host read — mixing the two would corrupt the
        # compile-spike/enqueue-floor analysis the metric exists for.
        # Fast-lane latency is visible end-to-end in query_seconds /
        # query_stage_seconds.
        self.stats.count("solo_fastlane_hits_total", 1, kind=kind)
        if nbytes:
            self.stats.count("kernel_bytes_scanned_total", nbytes,
                             kind=kind)

    def _fastlane_counts(self, nodes: tuple, leaves: tuple):
        """One request's Count run dispatched inline on the caller
        thread: same padding rule as the windowed `_dispatch_counts`
        (offset-0 single item), donated ping-pong scratch for the
        int32[K_pad, S] output.  None = fall back to the window."""
        from pilosa_tpu.exec.fused import pow2_bucket
        try:
            padded = tuple(nodes) + (nodes[0],) * (
                pow2_bucket(len(nodes)) - len(nodes))
            scratch = self._pp.scratch(
                (len(padded), leaves[0].shape[0]), "int32")
            out = self.fused.run_count_batch(padded, leaves,
                                             scratch=scratch)
            host = np.asarray(out).astype(np.int64)
            self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            return None
        self._fastlane_done("count",
                            sum(getattr(a, "nbytes", 0) for a in leaves))
        return [int(row.sum()) for row in host[:len(nodes)]]

    def _fastlane_selected(self, plane, slots: tuple, delta):
        """Width-N selected counts inline: sorted-unique slot gather
        (ascending stride), pre-bound device slot indices, donated
        int32[bucket] output slot.  None = fall back to the window."""
        from pilosa_tpu.exec.fused import pow2_bucket
        order = sorted(set(slots))
        pos = {s: i for i, s in enumerate(order)}
        try:
            scratch = self._pp.scratch(
                (pow2_bucket(len(order)),), "int32")
            out = self.fused.run_selected_counts(
                plane, tuple(order), delta=delta, scratch=scratch,
                sorted_idx=True)
            host = np.asarray(out).astype(np.int64)
            self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            return None
        nbytes = (len(order) * plane.shape[0] * plane.shape[-1] * 4
                  + (delta.nbytes if delta is not None else 0))
        self._fastlane_done("selcounts", nbytes)
        return host[[pos[s] for s in slots]]

    def _fastlane_rowcounts(self, plane, filter_words, delta):
        try:
            if delta is not None:
                out = self.fused.run_rowcounts_delta(
                    plane, delta, filter_words=filter_words)
                host = np.asarray(out).astype(np.int64)
            else:
                flags = (filter_words is not None,)
                leaves = ((plane,) if filter_words is None
                          else (plane, filter_words))
                scratch = self._pp.scratch((1, plane.shape[-2]),
                                           "int32")
                out = self.fused.run_rowcounts_batch(flags, leaves,
                                                     scratch=scratch)
                host = np.asarray(out).astype(np.int64)[0]
                self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            return None
        self._fastlane_done(
            "rowcounts",
            plane.nbytes + (getattr(filter_words, "nbytes", 0) or 0)
            + (delta.nbytes if delta is not None else 0))
        return host

    def _fastlane_tree(self, plane, slots: tuple, prog: tuple,
                       extras: tuple, delta):
        try:
            out = self.fused.run_tree_counts(plane, tuple(slots),
                                             (tuple(prog),),
                                             tuple(extras), delta=delta)
            val = int(np.asarray(out).astype(np.int64)[0])
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            return None
        nbytes = (len(slots) * plane.shape[0] * plane.shape[-1] * 4
                  + sum(getattr(a, "nbytes", 0) for a in extras)
                  + (delta.nbytes if delta is not None else 0))
        self._fastlane_done("tree", nbytes)
        return val

    # -- blocking submits ----------------------------------------------------

    def submit(self, node, leaves) -> int:
        """Block until the coalesced batch containing this Count runs;
        returns the host-finished int64 total."""
        return self.submit_many((node,), leaves)[0]

    def submit_many(self, nodes, leaves) -> list[int]:
        """A whole request's Count run as ONE batch item (the nodes
        share one leaf list); N concurrent requests coalesce into one
        program regardless of how many Counts each carries."""
        nodes, leaves = tuple(nodes), tuple(leaves)
        if self._fl_try_enter():
            try:
                out = self._fastlane_counts(nodes, leaves)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self._submit(_Pending("count", nodes, leaves))

    def submit_sum(self, plane, filter_words) -> tuple[int, int]:
        """BSI Sum: (sum of offsets, non-null count), host-finished."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("sum", None, leaves))

    def submit_minmax(self, plane, filter_words):
        """BSI Min/Max: per-shard (min, min_cnt, max, max_cnt) tuples."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("minmax", None, leaves))

    def submit_rowcounts(self, plane, filter_words=None,
                         delta=None) -> np.ndarray:
        """Whole-plane per-row totals int64[R_pad] (cross-shard reduce
        on device — callers gate on the int32-exact shard bound).
        Identical concurrent items (same plane/filter objects) share
        one computation.  ``delta`` (the plane's DeltaOverlay) makes
        the answer base⊕delta — items over the same (plane, overlay)
        pair still dedupe to one scan."""
        if self._fl_try_enter():
            try:
                out = self._fastlane_rowcounts(plane, filter_words,
                                               delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self.wait(self.enqueue_rowcounts(plane, filter_words,
                                                delta))

    def enqueue_rowcounts(self, plane, filter_words=None,
                          delta=None) -> _Pending:
        """Non-blocking variant: returns a handle for :meth:`wait`, so
        a request needing several row-count reads (filtered TopN with
        tanimoto) lands them all in ONE collection window."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._enqueue(_Pending("rowcounts", None, leaves,
                                      delta=delta))

    def submit_selected(self, plane, slots: tuple,
                        delta=None) -> np.ndarray:
        """Selected-row Counts (the multi-query fused popcount): the
        window's items over the SAME resident plane merge into one
        row-gather + popcount program — one pass over the UNION of
        requested rows, N accumulators — and the per-item answers come
        back int64[len(slots)] in the caller's slot order.  Duplicate
        slots across concurrent requests are computed once.  ``delta``
        merges the plane's pending write overlay at dispatch time."""
        if self._fl_try_enter():
            try:
                out = self._fastlane_selected(plane, tuple(slots),
                                              delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self._submit(_Pending("selcounts", tuple(slots), (plane,),
                                     delta=delta))

    def submit_tree(self, plane, slots: tuple, prog: tuple,
                    extras: tuple = (), delta=None) -> int:
        """One compound-tree Count (whole-tree compilation, r16): the
        window's tree items over the SAME (plane, overlay) pair union
        their gathered row slots into ONE in-program gather and fold
        every item's postfix program in one fused dispatch — N
        concurrent compound queries cost one memory pass and join the
        window's single packed readback."""
        if self._fl_try_enter():
            try:
                out = self._fastlane_tree(plane, slots, prog, extras,
                                          delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self.wait(self.enqueue_tree(plane, slots, prog, extras,
                                           delta))

    def enqueue_tree(self, plane, slots: tuple, prog: tuple,
                     extras: tuple = (), delta=None) -> _Pending:
        """Non-blocking :meth:`submit_tree`: a request carrying K
        compound Counts enqueues them ALL into one collection window
        before waiting on any."""
        return self._enqueue(_Pending(
            "tree", (tuple(slots), tuple(prog), tuple(extras)),
            (plane,), delta=delta))

    def submit_distinct(self, plane, filter_words):
        """BSI Distinct presence: host (pos bool[2^d], neg bool[2^d]).
        Coalescing here is DEDUPLICATION only — the presence scan is a
        multi-dispatch block loop, so stacking would multiply compute;
        identical concurrent requests share one scan."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("distinct", None, leaves))

    def _loop(self) -> None:
        while True:
            self._kick.wait()
            # collection window: let concurrent submitters pile in.
            # Adaptive mode keeps it at 0 for solo traffic and grows it
            # only while batches actually coalesce.
            win = self._win if self.adaptive else self.window_s
            if win > 0:
                time.sleep(win)
            with self._lock:
                backlog = len(self._queue)
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
                if not self._queue:
                    self._kick.clear()
            if not batch:
                continue
            if self.adaptive:
                if len(batch) > 1 or backlog > len(batch):
                    self._win = min(max(self._win * 2, self.ADAPT_MIN),
                                    self.ADAPT_MAX)
                elif self._win:
                    nxt = self._win / 2
                    self._win = 0.0 if nxt < self.ADAPT_MIN else nxt
            self.stats.count("batcher_batches", 1)
            self.stats.count("batcher_items", len(batch))
            self.stats.gauge("batcher_window_seconds", self._win)
            # window occupancy + fill ratio (r14 device telemetry):
            # the coalescing histograms the config23 roofline reasons
            # about — how many items a window actually collects and
            # how close it runs to max_batch
            self.stats.observe("batcher_window_items", float(len(batch)))
            self.stats.observe("batcher_window_fill_ratio",
                               len(batch) / self.max_batch)
            # stacked outputs need uniform shapes: group by kind + the
            # output-shaping leaf dimension (counts: n_shards — mixed
            # row/plane leaf ranks fuse fine, only the int32[S] outputs
            # must stack; aggregates/rowcounts: the full plane shape;
            # selcounts: the plane IDENTITY — one gather per plane)
            groups: dict[tuple, list[_Pending]] = {}
            for p in batch:
                if p.kind == "count":
                    key = ("count", p.leaves[0].shape[0])
                elif p.kind == "selcounts":
                    # delta identity joins the key: items over the
                    # same (plane, overlay) pair slot-union into one
                    # gather; a fresher overlay is a different answer
                    key = ("selcounts", id(p.leaves[0]),
                           id(p.delta) if p.delta is not None else 0)
                elif p.kind == "tree":
                    # same (plane, overlay) pair → one gather of the
                    # slot UNION serves every item's program
                    key = ("tree", id(p.leaves[0]),
                           id(p.delta) if p.delta is not None else 0)
                elif p.kind == "rowcounts" and p.delta is not None:
                    key = ("rowcounts-delta", id(p.leaves[0]),
                           id(p.delta),
                           id(p.leaves[1]) if len(p.leaves) == 2 else 0)
                else:
                    key = (p.kind, p.leaves[0].shape)
                groups.setdefault(key, []).append(p)
            # BATCHED READBACK (r12): every one-program kind dispatches
            # asynchronously, then the whole window's outputs are
            # packed into ONE device array and read with ONE
            # device->host transfer — on transports with a fixed
            # per-read RPC floor, the window now pays that floor once
            # total, not once per kind/shape group.  Distinct stays on
            # the pool: its presence scan is a multi-dispatch host
            # loop that cannot join a single readback.
            pending = []
            distinct_futs = []
            program_groups = []
            for key, group in groups.items():
                if key[0] == "distinct":
                    distinct_futs.append(self._group_pool().submit(
                        self._run_distinct, group))
                else:
                    program_groups.append((key, group))
            # run-ahead bound BEFORE dispatching: at pipeline_depth
            # dispatched-but-unread windows the collector waits here,
            # so device output held by in-flight windows never exceeds
            # the documented knob
            slot_held = False
            if self._readq is not None and (program_groups
                                            or distinct_futs):
                self._pipe_slots.acquire()
                slot_held = True
            t_disp = time.perf_counter()
            if len(program_groups) == 1:
                # the common (and solo-path) case skips the pool
                # round-trip: one group, dispatch inline
                key, group = program_groups[0]
                try:
                    pending.append((key, group)
                                   + self._dispatch_one(key, group))
                except Exception:  # noqa: BLE001 — per-item fallback
                    self._run_fallback(key, group)
            elif program_groups:
                # dispatch groups CONCURRENTLY (a first-time compile
                # in one group must not stall the others' warm
                # dispatches), then join for the window's single
                # packed readback
                futs = [(key, group, self._group_pool().submit(
                    self._dispatch_one, key, group))
                    for key, group in program_groups]
                for key, group, fut in futs:
                    try:
                        pending.append((key, group) + fut.result())
                    except Exception:  # noqa: BLE001 — per-item fallback
                        self._run_fallback(key, group)
            # bytes the window's fused programs read from HBM (r14):
            # per-kind scan-volume counters feed capacity math, and
            # bytes / (readback-start -> readback-complete) is the
            # LIVE bandwidth the config23 roofline bench measures
            # offline — the gauge tracks how far serving sits from
            # that roof (see _finish_window for why the clock starts
            # at the read, not the dispatch)
            win_bytes = 0
            for key, group, _, _ in pending:
                nbytes = self._group_bytes(key[0], group)
                if nbytes:
                    self.stats.count("kernel_bytes_scanned_total",
                                     nbytes, kind=key[0])
                    win_bytes += nbytes
            item = (pending, distinct_futs, win_bytes)
            if slot_held and (pending or distinct_futs):
                # PIPELINED READBACK (r17): hand the dispatched window
                # to the readback worker and immediately collect the
                # next one — window N's device compute overlaps window
                # N-1's packed device->host read.
                with self._pipe_lock:
                    overlapped = self._inflight_windows > 0
                    self._inflight_windows += 1
                    depth = self._inflight_windows
                self.stats.observe("readback_overlap_ratio",
                                   1.0 if overlapped else 0.0)
                self.stats.gauge("dispatch_pipeline_depth", depth)
                self._ensure_reader()
                self._readq.put(item)
            else:
                if slot_held:  # every dispatch fell back: nothing to read
                    self._pipe_slots.release()
                self._finish_window(item)

    def _ensure_reader(self) -> None:
        if self._read_thread is None or not self._read_thread.is_alive():
            self._read_thread = threading.Thread(
                target=self._read_loop, name="pilosa-batch-readback",
                daemon=True)
            self._read_thread.start()

    def _read_loop(self) -> None:
        while True:
            item = self._readq.get()
            try:
                self._finish_window(item)
            except Exception:  # noqa: BLE001 — per-item state is set by
                pass           # _readback's fallbacks; the worker lives on
            finally:
                with self._pipe_lock:
                    self._inflight_windows -= 1
                    depth = self._inflight_windows
                self.stats.gauge("dispatch_pipeline_depth", depth)
                self._pipe_slots.release()

    def _finish_window(self, item) -> None:
        """Read one dispatched window back and finish its items — the
        half of the old loop tail that runs on the readback worker
        when pipelining is on (inline when off)."""
        pending, distinct_futs, win_bytes = item
        # bandwidth wall clock starts HERE, not at dispatch: a
        # pipelined window's queue wait overlaps the previous window's
        # read (the feature working as intended) and must not deflate
        # the gauge — the read itself still blocks on any residual
        # compute, so bytes/wall remains the live achieved bandwidth
        t0 = time.perf_counter()
        self._readback(pending)
        if win_bytes:
            # per-window scan-volume distribution (byte-scale
            # buckets) + the live bandwidth the window achieved
            self.stats.observe("kernel_window_bytes",
                               float(win_bytes))
            wall = time.perf_counter() - t0
            if wall > 0:
                self.stats.gauge("kernel_bandwidth_gbps",
                                 round(win_bytes / wall / 1e9, 4))
        for f in distinct_futs:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — _run_distinct sets its
                pass           # items' events/errors itself

    def _dispatch_one(self, key, group):
        """Build + enqueue one group's fused program; returns
        ``(device_out, finish)`` with the device->host read deferred to
        the window's single packed readback.  Raises on dispatch
        failure (the caller falls back per item).  Dispatch time is
        observed per kind — a first-time XLA compile shows up as a
        spike in ``kernel_dispatch_seconds{kind=...}``, warm dispatches
        as the enqueue floor."""
        t0 = time.perf_counter()
        kind = key[0]
        if kind == "count":
            ret = self._dispatch_counts(group)
        elif kind == "rowcounts":
            ret = self._dispatch_rowcounts(group)
        elif kind == "rowcounts-delta":
            ret = self._dispatch_rowcounts_delta(group)
        elif kind == "selcounts":
            ret = self._dispatch_selcounts(group)
        elif kind == "tree":
            ret = self._dispatch_tree(group)
        else:
            ret = self._dispatch_aggs(kind, group)
        self.stats.observe("kernel_dispatch_seconds",
                           time.perf_counter() - t0, kind=kind)
        return ret

    @staticmethod
    def _group_bytes(kind: str, group: list[_Pending]) -> int:
        """Estimated HBM bytes one group's fused program reads.  count
        leaves each enter the program (sum of leaf footprints);
        selcounts gathers only the UNION of requested rows; the
        dedup'd kinds (rowcounts/sum/minmax/distinct) scan each unique
        plane[, filter] once however many items share it."""
        if kind == "selcounts":
            plane = group[0].leaves[0]
            rows = {s for p in group for s in p.nodes}
            return len(rows) * plane.shape[0] * plane.shape[-1] * 4
        if kind == "tree":
            # one gather of the slot UNION + each unique extra once
            plane = group[0].leaves[0]
            rows = {s for p in group for s in p.nodes[0]}
            extras = {id(a): a for p in group for a in p.nodes[2]}
            d = group[0].delta
            return (len(rows) * plane.shape[0] * plane.shape[-1] * 4
                    + sum(getattr(a, "nbytes", 0)
                          for a in extras.values())
                    + (d.nbytes if d is not None else 0))
        if kind == "rowcounts-delta":
            # one base scan + the overlay gather per unique (plane,
            # overlay, filter) key — items in this group are identical
            p0 = group[0]
            d = p0.delta
            return (sum(getattr(a, "nbytes", 0) for a in p0.leaves)
                    + (d.nbytes if d is not None else 0))
        if kind == "count":
            return sum(getattr(a, "nbytes", 0)
                       for p in group for a in p.leaves)
        seen: set = set()
        total = 0
        for p in group:
            k = tuple(id(a) for a in p.leaves)
            if k in seen:
                continue
            seen.add(k)
            total += sum(getattr(a, "nbytes", 0) for a in p.leaves)
        return total

    def _run_fallback(self, key, group):
        if key[0] == "count":
            self._fallback_counts(group)
        elif key[0] in ("rowcounts", "rowcounts-delta"):
            self._fallback_rowcounts(group)
        elif key[0] == "selcounts":
            self._fallback_selcounts(group)
        elif key[0] == "tree":
            self._fallback_tree(group)
        else:
            self._fallback_aggs(key[0], group)

    def _readback(self, pending: list) -> None:
        """One device->host transfer for the whole collection window:
        pack every group's int32 output into a single flat array, read
        it once, slice per group.  A single-group window reads its
        output directly (the pack would only add a dispatch); any pack
        or finish failure degrades to per-group reads, then to the
        per-item fallbacks."""
        if not pending:
            return
        if len(pending) == 1:
            key, group, out, finish = pending[0]
            try:
                finish(np.asarray(out))
            except Exception:  # noqa: BLE001 — per-item fallback
                self._run_fallback(key, group)
            else:
                # only after a delivered finish (which copied): a
                # retire failure must never re-run a group whose
                # results callers are already reading
                self._pp.retire(out)
            return
        # canonical pack order: groups arrive in batch order, so the
        # same kinds in a different order would otherwise compile a
        # fresh concatenate program per PERMUTATION of shapes —
        # churning the shared program LRU for zero benefit
        pending.sort(key=lambda item: (item[0][0], str(item[2].shape)))
        packed_dev = None
        try:
            total = sum(int(np.prod(out.shape, dtype=np.int64))
                        for _, _, out, _ in pending)
            packed_dev = self.fused.run_readback_pack(
                tuple(out for _, _, out, _ in pending),
                scratch=self._pp.scratch((total,), "int32"))
            packed = np.asarray(packed_dev)
            self.stats.count("batcher_readback_packed", 1)
            self.stats.count("batcher_readback_groups", len(pending))
        except Exception:  # noqa: BLE001 — per-group reads
            packed = packed_dev = None
        off = 0
        for key, group, out, finish in pending:
            try:
                if packed is None:
                    host = np.asarray(out)
                else:
                    size = int(np.prod(out.shape, dtype=np.int64))
                    host = packed[off:off + size].reshape(out.shape)
                    off += size
                finish(host)
            except Exception:  # noqa: BLE001 — per-item fallback
                self._run_fallback(key, group)
        # every finish copied out of `packed` (astype/int/fancy-index),
        # so the packed device buffer can re-enter the donated chain
        self._pp.retire(packed_dev)

    def _dispatch_counts(self, group: list[_Pending]):
        from pilosa_tpu.exec.fused import pow2_bucket, shift_leaves
        all_nodes, all_leaves, spans = [], [], []
        for p in group:
            start = len(all_nodes)
            for node in p.nodes:
                all_nodes.append(shift_leaves(node, len(all_leaves)))
            all_leaves.extend(p.leaves)
            spans.append((start, len(all_nodes)))
        # pad the NODE count to a pow2 bucket by repeating node 0
        # (already leaf-shifted; see fused.pow2_bucket)
        n = len(all_nodes)
        all_nodes.extend([all_nodes[0]] * (pow2_bucket(n) - n))
        per_shard = self.fused.run_count_batch(
            tuple(all_nodes), tuple(all_leaves),
            scratch=self._pp.scratch(
                (len(all_nodes), group[0].leaves[0].shape[0]),
                "int32"))

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p, (a, b) in zip(group, spans):
                p.result = [int(row.sum()) for row in host[a:b]]
                p.event.set()
        return per_shard, finish

    def _fallback_counts(self, group: list[_Pending]) -> None:
        for p in group:
            try:
                p.result = [
                    int(kernels.shard_totals(
                        self.fused.run(node, p.leaves, "count")))
                    for node in p.nodes]
            except Exception as e2:  # noqa: BLE001
                p.error = e2
            finally:
                p.event.set()

    def _dispatch_selcounts(self, group: list[_Pending]):
        """The window's selected-row Counts over one plane: gather the
        UNION of every item's requested slots once (N concurrent
        requests over overlapping rows pay one pass over the union,
        the multi-query analogue of the rowcounts dedup), popcount,
        reduce shards on device.  The group key carries the delta
        identity, so every item here shares one (plane, overlay) pair
        and the merge happens once for the union.  The union gathers
        in SORTED slot order (ascending memory stride, r17) with a
        donated ping-pong output slot."""
        from pilosa_tpu.exec.fused import pow2_bucket
        plane = group[0].leaves[0]
        order = sorted({s for p in group for s in p.nodes})
        pos = {s: i for i, s in enumerate(order)}
        out = self.fused.run_selected_counts(
            plane, tuple(order), delta=group[0].delta,
            scratch=self._pp.scratch((pow2_bucket(len(order)),),
                                     "int32"),
            sorted_idx=True)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p in group:
                p.result = host[[pos[s] for s in p.nodes]]
                p.event.set()
        return out, finish

    def _dispatch_tree(self, group: list[_Pending]):
        """The window's compound-tree Counts over one (plane, overlay)
        pair: union every item's gathered slots and extra operands
        (``exec.tree.assemble_items``), remap the postfix programs
        into the shared operand space and run ONE fused program — one
        memory pass over the union, K answers, packed readback."""
        from pilosa_tpu.exec.tree import assemble_items
        plane = group[0].leaves[0]
        slots, progs, extras = assemble_items([p.nodes for p in group])
        out = self.fused.run_tree_counts(plane, slots, progs, extras,
                                         delta=group[0].delta)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for k, p in enumerate(group):
                p.result = int(host[k])
                p.event.set()
        return out, finish

    def _fallback_tree(self, group: list[_Pending]) -> None:
        for p in group:
            try:
                slots, prog, extras = p.nodes
                out = self.fused.run_tree_counts(
                    p.leaves[0], slots, (prog,), extras, delta=p.delta)
                p.result = int(np.asarray(out).astype(np.int64)[0])
            except Exception as e2:  # noqa: BLE001
                p.error = e2
            finally:
                p.event.set()

    def _dispatch_rowcounts_delta(self, group: list[_Pending]):
        """Whole-plane row counts of base⊕delta: the group key is the
        (plane, overlay, filter) identity triple, so the whole group
        is ONE scan + one overlay adjustment shared by every item."""
        p0 = group[0]
        flt = p0.leaves[1] if len(p0.leaves) == 2 else None
        out = self.fused.run_rowcounts_delta(p0.leaves[0], p0.delta,
                                             filter_words=flt)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p in group:
                p.result = host
                p.event.set()
        return out, finish

    def _fallback_selcounts(self, group: list[_Pending]) -> None:
        import jax.numpy as jnp
        for p in group:
            try:
                idx = jnp.asarray(p.nodes, dtype=jnp.int32)
                if p.delta is not None:
                    from pilosa_tpu.ingest.delta import \
                        adjusted_selected_counts
                    d = p.delta
                    p.result = np.asarray(adjusted_selected_counts(
                        p.leaves[0], idx, d.rows, d.words,
                        d.vals)).astype(np.int64)
                else:
                    p.result = kernels.shard_totals(
                        kernels.selected_row_counts(p.leaves[0], idx))
            except Exception as e2:  # noqa: BLE001
                p.error = e2
            finally:
                p.event.set()

    @staticmethod
    def _dedupe(group: list[_Pending]):
        """Unique items by leaf identity + the caller index of each
        item's unique representative — N requests over the same
        resident plane compute once and share the read."""
        uniq: dict[tuple, int] = {}
        items: list[_Pending] = []
        assign: list[int] = []
        for p in group:
            k = tuple(id(a) for a in p.leaves)
            slot = uniq.get(k)
            if slot is None:
                slot = uniq[k] = len(items)
                items.append(p)
            assign.append(slot)
        return items, assign

    def _dispatch_rowcounts(self, group: list[_Pending]):
        from pilosa_tpu.exec.fused import pow2_bucket
        items, assign = self._dedupe(group)
        # canonical flag order + pow2 pad (repeating item 0): bounded
        # program set per plane shape, like the aggregate batches
        order = sorted(range(len(items)), key=lambda i: len(items[i].leaves))
        items = [items[i] for i in order]
        back = {old: new for new, old in enumerate(order)}
        assign = [back[a] for a in assign]
        padded = items + [items[0]] * (pow2_bucket(len(items))
                                       - len(items))
        flags = tuple(len(p.leaves) == 2 for p in padded)
        leaves = tuple(a for p in padded for a in p.leaves)
        out = self.fused.run_rowcounts_batch(
            flags, leaves,
            scratch=self._pp.scratch(
                (len(flags), leaves[0].shape[-2]), "int32"))

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p, slot in zip(group, assign):
                p.result = host[slot]
                p.event.set()
        return out, finish

    def _fallback_rowcounts(self, group: list[_Pending]) -> None:
        for p in group:
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                if p.delta is not None:
                    from pilosa_tpu.ingest.delta import \
                        adjusted_row_counts
                    d = p.delta
                    p.result = np.asarray(adjusted_row_counts(
                        p.leaves[0], d.rows, d.words, d.vals, flt,
                        reduce_shards=False)).astype(np.int64).sum(
                            axis=0)
                else:
                    p.result = kernels.shard_totals(
                        kernels.row_counts(p.leaves[0], flt))
            except Exception as e2:  # noqa: BLE001
                p.error = e2
            finally:
                p.event.set()

    def _run_distinct(self, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        t0 = time.perf_counter()
        items, assign = self._dedupe(group)
        results: list = [None] * len(items)
        errors: list = [None] * len(items)

        def scan(i: int) -> None:
            p = items[i]
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                pos, neg = bsik.distinct_presence(p.leaves[0], flt)
                results[i] = (np.asarray(pos), np.asarray(neg))
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        if len(items) == 1:
            scan(0)
        else:
            # NON-identical items (different planes/filters) keep the
            # pre-batcher concurrency: the scans are multi-dispatch
            # block loops, so running them serially in this worker
            # would make the last caller wait out every other scan.
            # Plain threads, NOT _group_pool: this method itself runs
            # inside that pool, and a nested map could deadlock with
            # every pool worker occupied by group runs; thread spawn
            # is noise next to a presence scan.
            ts = [threading.Thread(target=scan, args=(i,))
                  for i in range(len(items))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        for p, slot in zip(group, assign):
            if errors[slot] is not None:
                p.error = errors[slot]
            else:
                p.result = results[slot]
            p.event.set()
        # distinct can't join the packed readback (multi-dispatch host
        # loop), so its dispatch observation covers the whole scan —
        # read included — and its bytes land on the same counter
        self.stats.observe("kernel_dispatch_seconds",
                           time.perf_counter() - t0, kind="distinct")
        nbytes = self._group_bytes("distinct", group)
        if nbytes:
            self.stats.count("kernel_bytes_scanned_total", nbytes,
                             kind="distinct")

    def _dispatch_aggs(self, kind: str, group: list[_Pending]):
        from pilosa_tpu.engine import bsi as bsik
        from pilosa_tpu.exec.fused import pow2_bucket
        # pad the batch to a pow2 bucket (repeating item 0; see
        # fused.pow2_bucket) so the program set stays bounded per
        # (kind, shape)
        group.sort(key=lambda p: len(p.leaves))  # canonical flag order:
        # program variants per bucket stay O(bucket), not O(2^bucket)
        pad = [group[0]] * (pow2_bucket(len(group)) - len(group))
        flags = tuple(len(p.leaves) == 2 for p in group + pad)
        all_leaves = tuple(a for p in group + pad for a in p.leaves)
        if kind == "sum":
            out = self.fused.run_sum_batch(flags, all_leaves)
            decode = bsik.decode_sum_packed
        else:
            out = self.fused.run_minmax_batch(flags, all_leaves)
            decode = bsik.decode_minmax_packed

        def finish(host: np.ndarray) -> None:
            for k, p in enumerate(group):
                p.result = decode(host[k])
                p.event.set()
        return out, finish

    def _fallback_aggs(self, kind: str, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        for p in group:
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                if kind == "sum":
                    p.result = bsik.sum_count(p.leaves[0], flt)
                else:
                    p.result = bsik.min_max(p.leaves[0], flt)
            except Exception as e2:  # noqa: BLE001
                p.error = e2
            finally:
                p.event.set()
