"""Cross-request Count coalescing.

Within-request batching (executor count runs) amortizes fixed
per-dispatch/per-read costs across one query string; this batcher does
the same ACROSS concurrent requests: server threads submit planned
Count trees, a collector waits a tiny window, and one fused program
answers the whole batch with a single device read.

Motivation (BASELINE.md): transports can impose a fixed cost per
synchronous device read (~100ms on this image's tunnel; ~10us on local
hardware).  When reads SERIALIZE, N coalesced Counts pay that cost once
instead of N times.  Measured on this image's tunnel: neutral at
low concurrency (~130 count-qps either way, its reads overlap across
threads), but it becomes the scaling lever past the tunnel's device-
stream limit: unbatched serving crashes the tunnel outright beyond 8
concurrent streams, while the batcher funnels any number of HTTP
clients through ONE device stream — 32 clients reached 148 qps e2e
where unbatched tops out at 80.  Off by default
(``count_batch_window`` in the server config) — a solo request would
only gain latency.
"""

from __future__ import annotations

import threading

import numpy as np

from pilosa_tpu.engine import kernels


class _Pending:
    __slots__ = ("node", "leaves", "event", "result", "error")

    def __init__(self, node, leaves):
        self.node = node
        self.leaves = leaves
        self.event = threading.Event()
        self.result: int | None = None
        self.error: Exception | None = None


class CountBatcher:
    def __init__(self, fused, window_s: float = 0.002, max_batch: int = 64):
        self.fused = fused
        self.window_s = window_s
        self.max_batch = max_batch
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="pilosa-count-batcher",
                                            daemon=True)
            self._thread.start()

    def submit(self, node, leaves) -> int:
        """Block until the coalesced batch containing this Count runs;
        returns the host-finished int64 total."""
        p = _Pending(node, tuple(leaves))
        with self._lock:
            self._queue.append(p)
            self._ensure_worker()
        self._kick.set()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _loop(self) -> None:
        from pilosa_tpu.exec.fused import shift_leaves
        while True:
            self._kick.wait()
            # collection window: let concurrent submitters pile in
            threading.Event().wait(self.window_s)
            with self._lock:
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
                if not self._queue:
                    self._kick.clear()
            if not batch:
                continue
            # stacked counts need a uniform shard axis: group by the
            # leaves' n_shards (differs across indexes / shard sets)
            groups: dict[int, list[_Pending]] = {}
            for p in batch:
                groups.setdefault(int(p.leaves[0].shape[0]), []).append(p)
            for group in groups.values():
                self._run_group(group, shift_leaves)

    def _run_group(self, group: list[_Pending], shift_leaves) -> None:
        try:
            nodes, all_leaves = [], []
            for p in group:
                nodes.append(shift_leaves(p.node, len(all_leaves)))
                all_leaves.extend(p.leaves)
            per_shard = self.fused.run_count_batch(
                tuple(nodes), tuple(all_leaves))
            host = np.asarray(per_shard).astype(np.int64)
            for p, row in zip(group, host):
                p.result = int(row.sum())
                p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    p.result = int(kernels.shard_totals(
                        self.fused.run(p.node, p.leaves, "count")))
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()
