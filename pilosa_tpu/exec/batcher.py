"""Cross-request Count coalescing.

Within-request batching (executor count runs) amortizes fixed
per-dispatch/per-read costs across one query string; this batcher does
the same ACROSS concurrent requests: server threads submit planned
Count trees, a collector waits a tiny window, and one fused program
answers the whole batch with a single device read.

Motivation (BASELINE.md): transports can impose a fixed cost per
synchronous device read (~100ms on this image's tunnel; ~10us on local
hardware).  When reads SERIALIZE, N coalesced Counts pay that cost once
instead of N times.  Measured on this image's tunnel: neutral at
low concurrency (~130 count-qps either way, its reads overlap across
threads), but it becomes the scaling lever past the tunnel's device-
stream limit: unbatched serving crashes the tunnel outright beyond 8
concurrent streams, while the batcher funnels any number of HTTP
clients through ONE device stream — 32 clients reached 148 qps e2e
where unbatched tops out at 80.  Off by default
(``count_batch_window`` in the server config) — a solo request would
only gain latency.
"""

from __future__ import annotations

import threading

import numpy as np

from pilosa_tpu.engine import kernels


class _Pending:
    __slots__ = ("kind", "node", "leaves", "event", "result", "error")

    def __init__(self, kind, node, leaves):
        self.kind = kind      # "count" | "sum" | "minmax"
        self.node = node      # count: plan tree; aggregates: None
        self.leaves = leaves  # count: plan leaves; agg: (plane[, filter])
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class CountBatcher:
    """Cross-request coalescing for Count AND the BSI aggregates
    (Sum/Min/Max join the same collection window; each kind/shape group
    runs as one fused program + one read)."""

    def __init__(self, fused, window_s: float = 0.002, max_batch: int = 64):
        self.fused = fused
        self.window_s = window_s
        self.max_batch = max_batch
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="pilosa-count-batcher",
                                            daemon=True)
            self._thread.start()

    def _submit(self, p: _Pending):
        with self._lock:
            self._queue.append(p)
            self._ensure_worker()
        self._kick.set()
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def submit(self, node, leaves) -> int:
        """Block until the coalesced batch containing this Count runs;
        returns the host-finished int64 total."""
        return self._submit(_Pending("count", node, tuple(leaves)))

    def submit_sum(self, plane, filter_words) -> tuple[int, int]:
        """BSI Sum: (sum of offsets, non-null count), host-finished."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("sum", None, leaves))

    def submit_minmax(self, plane, filter_words):
        """BSI Min/Max: per-shard (min, min_cnt, max, max_cnt) tuples."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("minmax", None, leaves))

    def _loop(self) -> None:
        while True:
            self._kick.wait()
            # collection window: let concurrent submitters pile in
            threading.Event().wait(self.window_s)
            with self._lock:
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
                if not self._queue:
                    self._kick.clear()
            if not batch:
                continue
            # stacked outputs need uniform shapes: group by kind + the
            # leaves' n_shards (+ depth via the plane shape for
            # aggregates — differs across indexes / fields / shard sets)
            groups: dict[tuple, list[_Pending]] = {}
            for p in batch:
                key = (p.kind, p.leaves[0].shape)
                groups.setdefault(key, []).append(p)
            # one program per group, but dispatch groups CONCURRENTLY:
            # transports that overlap reads across threads (the axon
            # tunnel does) pay one read floor for the window, not one
            # per kind
            items = list(groups.items())
            if len(items) == 1:
                self._run_one(*items[0])
            else:
                from concurrent.futures import ThreadPoolExecutor
                with ThreadPoolExecutor(max_workers=len(items)) as pool:
                    list(pool.map(lambda kv: self._run_one(*kv), items))

    def _run_one(self, key, group):
        if key[0] == "count":
            self._run_counts(group)
        else:
            self._run_aggs(key[0], group)

    def _run_counts(self, group: list[_Pending]) -> None:
        from pilosa_tpu.exec.fused import shift_leaves
        try:
            # pad to a pow2 bucket by repeating item 0 — without it,
            # every distinct batch SIZE compiles a fresh program and the
            # compiles land on serving latency (measured: 32 concurrent
            # HTTP clients collapsed to ~23 qps from the recompile storm)
            n = len(group)
            bucket = 1
            while bucket < n:
                bucket *= 2
            items = group + [group[0]] * (bucket - n)
            nodes, all_leaves = [], []
            for p in items:
                nodes.append(shift_leaves(p.node, len(all_leaves)))
                all_leaves.extend(p.leaves)
            per_shard = self.fused.run_count_batch(
                tuple(nodes), tuple(all_leaves))
            host = np.asarray(per_shard).astype(np.int64)
            for p, row in zip(group, host):
                p.result = int(row.sum())
                p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    p.result = int(kernels.shard_totals(
                        self.fused.run(p.node, p.leaves, "count")))
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()

    def _run_aggs(self, kind: str, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        # pad the batch to a pow2 bucket (repeating item 0) so the
        # program set stays bounded per (kind, shape): otherwise every
        # distinct batch SIZE would compile a fresh program, and the
        # compiles land on serving latency
        group.sort(key=lambda p: len(p.leaves))  # canonical flag order:
        # program variants per bucket stay O(bucket), not O(2^bucket)
        n = len(group)
        bucket = 1
        while bucket < n:
            bucket *= 2
        pad = [group[0]] * (bucket - n)
        flags = tuple(len(p.leaves) == 2 for p in group + pad)
        all_leaves = tuple(a for p in group + pad for a in p.leaves)
        try:
            if kind == "sum":
                out = np.asarray(self.fused.run_sum_batch(flags, all_leaves))
                for k, p in enumerate(group):
                    p.result = bsik.decode_sum_packed(out[k])
                    p.event.set()
            else:
                out = np.asarray(
                    self.fused.run_minmax_batch(flags, all_leaves))
                for k, p in enumerate(group):
                    p.result = bsik.decode_minmax_packed(out[k])
                    p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    flt = p.leaves[1] if len(p.leaves) == 2 else None
                    if kind == "sum":
                        p.result = bsik.sum_count(p.leaves[0], flt)
                    else:
                        p.result = bsik.min_max(p.leaves[0], flt)
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()
