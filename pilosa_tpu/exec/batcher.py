"""Cross-request coalescing: the concurrent serving spine.

Within-request batching (executor count runs) amortizes fixed
per-dispatch/per-read costs across one query string; this batcher does
the same ACROSS concurrent requests: server threads submit planned
work items, a collector waits a tiny window, and one fused program per
(kind, shape) group answers the whole batch with a single device read.

Motivation (BASELINE.md): transports can impose a fixed cost per
synchronous device read (~100ms on this image's tunnel; ~10us on local
hardware).  When reads SERIALIZE, N coalesced items pay that cost once
instead of N times — and past the tunnel's device-stream limit the
batcher funnels any number of HTTP clients through ONE device stream.

r6 changes (the concurrency-gap work, ISSUE 1):

- **default-on with an ADAPTIVE window**: the window grows under queue
  pressure (concurrent submitters pile into one dispatch) and shrinks
  to zero when traffic is solo, so a lone request pays no collection
  wait.  ``count_batch_window=adaptive`` is the server default; a
  numeric value keeps the old fixed-window behavior, 0 disables.
- **every one-dispatch-one-read dense family coalesces**: Counts (any
  fusable tree, BSI conditions included), BSI Sum/Min/Max, whole-plane
  row counts (same-field Count batches and dense TopN — deduplicated:
  N concurrent requests over the SAME resident plane share one
  program and one read instead of stacking N copies of a multi-GB
  popcount), and Distinct presence scans (deduplicated likewise).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from pilosa_tpu.engine import kernels


class _Pending:
    __slots__ = ("kind", "nodes", "leaves", "event", "result", "error")

    def __init__(self, kind, nodes, leaves):
        self.kind = kind      # "count" | "sum" | "minmax" | "rowcounts"
        #                       | "distinct"
        self.nodes = nodes    # count: tuple of plan trees; others: None
        self.leaves = leaves  # count: plan leaves; others: plane[, filter]
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None


class CountBatcher:
    """Cross-request coalescing for Count, the BSI aggregates
    (Sum/Min/Max), whole-plane row counts, and Distinct — each
    kind/shape group in one collection window runs as one fused
    program + one read."""

    # adaptive-window bounds: MIN is the smallest non-zero window (below
    # it the window snaps to 0 — solo traffic must not wait at all);
    # MAX bounds queue-pressure growth so a burst can't add visible
    # latency to its own tail
    ADAPT_MIN = 0.0005
    ADAPT_MAX = 0.005

    def __init__(self, fused, window_s="adaptive", max_batch: int = 64,
                 stats=None):
        from pilosa_tpu.obs import NopStats
        self.fused = fused
        self.adaptive = window_s == "adaptive"
        self.window_s = 0.0 if self.adaptive else float(window_s)
        self._win = 0.0 if self.adaptive else self.window_s
        self.max_batch = max_batch
        self.stats = stats or NopStats()
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool = None  # persistent group-dispatch pool (lazy)

    def _group_pool(self):
        # persistent: a pool built and torn down per collection window
        # would put thread churn back on the very hot loop this
        # batcher exists to strip of per-request overhead
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="pilosa-batch-group")
        return self._pool

    @property
    def current_window(self) -> float:
        return self._win

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._loop,
                                            name="pilosa-count-batcher",
                                            daemon=True)
            self._thread.start()

    def _enqueue(self, p: _Pending) -> _Pending:
        with self._lock:
            self._queue.append(p)
            self._ensure_worker()
        self._kick.set()
        return p

    def wait(self, p: _Pending):
        """Block on an enqueued item's result (pairs with the
        ``enqueue_*`` methods — a caller that needs several items can
        enqueue them ALL into one collection window before waiting on
        any, instead of serializing one window per item)."""
        p.event.wait()
        if p.error is not None:
            raise p.error
        return p.result

    def _submit(self, p: _Pending):
        return self.wait(self._enqueue(p))

    def submit(self, node, leaves) -> int:
        """Block until the coalesced batch containing this Count runs;
        returns the host-finished int64 total."""
        return self._submit(_Pending("count", (node,), tuple(leaves)))[0]

    def submit_many(self, nodes, leaves) -> list[int]:
        """A whole request's Count run as ONE batch item (the nodes
        share one leaf list); N concurrent requests coalesce into one
        program regardless of how many Counts each carries."""
        return self._submit(_Pending("count", tuple(nodes), tuple(leaves)))

    def submit_sum(self, plane, filter_words) -> tuple[int, int]:
        """BSI Sum: (sum of offsets, non-null count), host-finished."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("sum", None, leaves))

    def submit_minmax(self, plane, filter_words):
        """BSI Min/Max: per-shard (min, min_cnt, max, max_cnt) tuples."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("minmax", None, leaves))

    def submit_rowcounts(self, plane, filter_words=None) -> np.ndarray:
        """Whole-plane per-row totals int64[R_pad] (cross-shard reduce
        on device — callers gate on the int32-exact shard bound).
        Identical concurrent items (same plane/filter objects) share
        one computation."""
        return self.wait(self.enqueue_rowcounts(plane, filter_words))

    def enqueue_rowcounts(self, plane, filter_words=None) -> _Pending:
        """Non-blocking variant: returns a handle for :meth:`wait`, so
        a request needing several row-count reads (filtered TopN with
        tanimoto) lands them all in ONE collection window."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._enqueue(_Pending("rowcounts", None, leaves))

    def submit_distinct(self, plane, filter_words):
        """BSI Distinct presence: host (pos bool[2^d], neg bool[2^d]).
        Coalescing here is DEDUPLICATION only — the presence scan is a
        multi-dispatch block loop, so stacking would multiply compute;
        identical concurrent requests share one scan."""
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("distinct", None, leaves))

    def _loop(self) -> None:
        while True:
            self._kick.wait()
            # collection window: let concurrent submitters pile in.
            # Adaptive mode keeps it at 0 for solo traffic and grows it
            # only while batches actually coalesce.
            win = self._win if self.adaptive else self.window_s
            if win > 0:
                time.sleep(win)
            with self._lock:
                backlog = len(self._queue)
                batch = self._queue[: self.max_batch]
                del self._queue[: len(batch)]
                if not self._queue:
                    self._kick.clear()
            if not batch:
                continue
            if self.adaptive:
                if len(batch) > 1 or backlog > len(batch):
                    self._win = min(max(self._win * 2, self.ADAPT_MIN),
                                    self.ADAPT_MAX)
                elif self._win:
                    nxt = self._win / 2
                    self._win = 0.0 if nxt < self.ADAPT_MIN else nxt
            self.stats.count("batcher_batches", 1)
            self.stats.count("batcher_items", len(batch))
            self.stats.gauge("batcher_window_seconds", self._win)
            # stacked outputs need uniform shapes: group by kind + the
            # output-shaping leaf dimension (counts: n_shards — mixed
            # row/plane leaf ranks fuse fine, only the int32[S] outputs
            # must stack; aggregates/rowcounts: the full plane shape)
            groups: dict[tuple, list[_Pending]] = {}
            for p in batch:
                if p.kind == "count":
                    key = ("count", p.leaves[0].shape[0])
                else:
                    key = (p.kind, p.leaves[0].shape)
                groups.setdefault(key, []).append(p)
            # one program per group, but dispatch groups CONCURRENTLY:
            # transports that overlap reads across threads (the axon
            # tunnel does) pay one read floor for the window, not one
            # per kind
            items = list(groups.items())
            if len(items) == 1:
                self._run_one(*items[0])
            else:
                list(self._group_pool().map(
                    lambda kv: self._run_one(*kv), items))

    def _run_one(self, key, group):
        if key[0] == "count":
            self._run_counts(group)
        elif key[0] == "rowcounts":
            self._run_rowcounts(group)
        elif key[0] == "distinct":
            self._run_distinct(group)
        else:
            self._run_aggs(key[0], group)

    def _run_counts(self, group: list[_Pending]) -> None:
        from pilosa_tpu.exec.fused import shift_leaves
        try:
            all_nodes, all_leaves, spans = [], [], []
            for p in group:
                start = len(all_nodes)
                for node in p.nodes:
                    all_nodes.append(shift_leaves(node, len(all_leaves)))
                all_leaves.extend(p.leaves)
                spans.append((start, len(all_nodes)))
            # pad the NODE count to a pow2 bucket by repeating node 0
            # (already leaf-shifted) — without it, every distinct batch
            # size compiles a fresh program and the compiles land on
            # serving latency (measured: 32 concurrent HTTP clients
            # collapsed to ~23 qps from the recompile storm)
            n = len(all_nodes)
            bucket = 1
            while bucket < n:
                bucket *= 2
            all_nodes.extend([all_nodes[0]] * (bucket - n))
            per_shard = self.fused.run_count_batch(
                tuple(all_nodes), tuple(all_leaves))
            host = np.asarray(per_shard).astype(np.int64)
            for p, (a, b) in zip(group, spans):
                p.result = [int(row.sum()) for row in host[a:b]]
                p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    p.result = [
                        int(kernels.shard_totals(
                            self.fused.run(node, p.leaves, "count")))
                        for node in p.nodes]
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()

    @staticmethod
    def _dedupe(group: list[_Pending]):
        """Unique items by leaf identity + the caller index of each
        item's unique representative — N requests over the same
        resident plane compute once and share the read."""
        uniq: dict[tuple, int] = {}
        items: list[_Pending] = []
        assign: list[int] = []
        for p in group:
            k = tuple(id(a) for a in p.leaves)
            slot = uniq.get(k)
            if slot is None:
                slot = uniq[k] = len(items)
                items.append(p)
            assign.append(slot)
        return items, assign

    def _run_rowcounts(self, group: list[_Pending]) -> None:
        items, assign = self._dedupe(group)
        # canonical flag order + pow2 pad (repeating item 0): bounded
        # program set per plane shape, like the aggregate batches
        order = sorted(range(len(items)), key=lambda i: len(items[i].leaves))
        items = [items[i] for i in order]
        back = {old: new for new, old in enumerate(order)}
        assign = [back[a] for a in assign]
        n = len(items)
        bucket = 1
        while bucket < n:
            bucket *= 2
        padded = items + [items[0]] * (bucket - n)
        flags = tuple(len(p.leaves) == 2 for p in padded)
        leaves = tuple(a for p in padded for a in p.leaves)
        try:
            out = np.asarray(
                self.fused.run_rowcounts_batch(flags, leaves)
            ).astype(np.int64)
            for p, slot in zip(group, assign):
                p.result = out[slot]
                p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    flt = p.leaves[1] if len(p.leaves) == 2 else None
                    p.result = kernels.shard_totals(
                        kernels.row_counts(p.leaves[0], flt))
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()

    def _run_distinct(self, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        items, assign = self._dedupe(group)
        results: list = [None] * len(items)
        errors: list = [None] * len(items)

        def scan(i: int) -> None:
            p = items[i]
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                pos, neg = bsik.distinct_presence(p.leaves[0], flt)
                results[i] = (np.asarray(pos), np.asarray(neg))
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        if len(items) == 1:
            scan(0)
        else:
            # NON-identical items (different planes/filters) keep the
            # pre-batcher concurrency: the scans are multi-dispatch
            # block loops, so running them serially in this worker
            # would make the last caller wait out every other scan.
            # Plain threads, NOT _group_pool: this method itself runs
            # inside that pool, and a nested map could deadlock with
            # every pool worker occupied by group runs; thread spawn
            # is noise next to a presence scan.
            ts = [threading.Thread(target=scan, args=(i,))
                  for i in range(len(items))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        for p, slot in zip(group, assign):
            if errors[slot] is not None:
                p.error = errors[slot]
            else:
                p.result = results[slot]
            p.event.set()

    def _run_aggs(self, kind: str, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        # pad the batch to a pow2 bucket (repeating item 0) so the
        # program set stays bounded per (kind, shape): otherwise every
        # distinct batch SIZE would compile a fresh program, and the
        # compiles land on serving latency
        group.sort(key=lambda p: len(p.leaves))  # canonical flag order:
        # program variants per bucket stay O(bucket), not O(2^bucket)
        n = len(group)
        bucket = 1
        while bucket < n:
            bucket *= 2
        pad = [group[0]] * (bucket - n)
        flags = tuple(len(p.leaves) == 2 for p in group + pad)
        all_leaves = tuple(a for p in group + pad for a in p.leaves)
        try:
            if kind == "sum":
                out = np.asarray(self.fused.run_sum_batch(flags, all_leaves))
                for k, p in enumerate(group):
                    p.result = bsik.decode_sum_packed(out[k])
                    p.event.set()
            else:
                out = np.asarray(
                    self.fused.run_minmax_batch(flags, all_leaves))
                for k, p in enumerate(group):
                    p.result = bsik.decode_minmax_packed(out[k])
                    p.event.set()
        except Exception:  # noqa: BLE001 — per-item fallback
            for p in group:
                try:
                    flt = p.leaves[1] if len(p.leaves) == 2 else None
                    if kind == "sum":
                        p.result = bsik.sum_count(p.leaves[0], flt)
                    else:
                        p.result = bsik.min_max(p.leaves[0], flt)
                except Exception as e2:  # noqa: BLE001
                    p.error = e2
                finally:
                    p.event.set()
