"""Cross-request coalescing: the concurrent serving spine.

Within-request batching (executor count runs) amortizes fixed
per-dispatch/per-read costs across one query string; this batcher does
the same ACROSS concurrent requests: server threads submit planned
work items, a collector waits a tiny window, and one fused program per
(kind, shape) group answers the whole batch with a single device read.

Motivation (BASELINE.md): transports can impose a fixed cost per
synchronous device read (~100ms on this image's tunnel; ~10us on local
hardware).  When reads SERIALIZE, N coalesced items pay that cost once
instead of N times — and past the tunnel's device-stream limit the
batcher funnels any number of HTTP clients through ONE device stream.

r6 changes (the concurrency-gap work, ISSUE 1):

- **default-on with an ADAPTIVE window**: the window grows under queue
  pressure (concurrent submitters pile into one dispatch) and shrinks
  to zero when traffic is solo, so a lone request pays no collection
  wait.  ``count_batch_window=adaptive`` is the server default; a
  numeric value keeps the old fixed-window behavior, 0 disables.
- **every one-dispatch-one-read dense family coalesces**: Counts (any
  fusable tree, BSI conditions included), BSI Sum/Min/Max, whole-plane
  row counts (same-field Count batches and dense TopN — deduplicated:
  N concurrent requests over the SAME resident plane share one
  program and one read instead of stacking N copies of a multi-GB
  popcount), and Distinct presence scans (deduplicated likewise).

r12 changes (the roofline work, ISSUE 7):

- **selected-row counts** (``submit_selected``): the multi-query fused
  popcount — concurrent requests' row slots union into ONE gather +
  popcount pass over just those rows' memory;
- **batched readback**: every one-program kind dispatches async and
  the window's outputs pack into ONE device array read with ONE
  device->host transfer — the window pays the per-read RPC floor once
  total, not once per kind/shape group.

r17 changes (the solo-floor/roofline work, ISSUE 12):

- **pipelined readback**: the collector hands each dispatched window
  to a dedicated readback worker, so window N's device compute
  overlaps window N-1's packed read instead of serializing behind it
  (``pipeline_depth`` bounds run-ahead; ``dispatch_pipeline_depth`` /
  ``readback_overlap_ratio`` on /metrics);
- **solo fast lane**: a width-1 request with no queue pressure skips
  window formation and dispatches inline on the CALLER's thread over
  pre-bound operands (``solo_fastlane_hits_total``) — the attack on
  the one-RPC-per-query solo floor;
- **donated ping-pong chains**: the window and fast-lane dispatch
  paths pass retired output buffers back as donated scratch
  (``fused.PingPong``), so consecutive dispatches re-use two standing
  output slots instead of allocating per window, and the selcounts
  union gathers in SORTED slot order (ascending memory stride).

r18 changes (the self-healing pipeline, ISSUE 13):

- **deadline propagation**: every ``submit_*``/``enqueue_*`` carries
  the query's monotonic deadline; :meth:`wait` blocks with a BOUNDED
  timeout and raises ``QueryTimeoutError`` (naming the item's stage)
  on expiry, marking the item ABANDONED so the group's shared
  readback skips finishing it without disturbing co-batched answers.
  The solo fast lane checks the deadline before dispatching.
- **pipeline watchdog + window quarantine**: a monitor thread bounds
  each in-flight window's dispatch and readback age
  (``dispatch_watchdog_seconds``).  A stuck window is QUARANTINED:
  its unfinished items fail with a structured
  ``PipelineStalledError`` naming the stalled stage, its pipeline
  slot is reclaimed, and the wedged stage worker is superseded by a
  fresh thread (the zombie exits when the hang resolves) so the
  queue keeps draining.  In a multi-group window, each group's
  dispatch is bounded individually — a hung group fails alone while
  the window's other groups (other planes, other kinds) proceed.
  ``pipeline_watchdog_trips_total{stage}`` /
  ``pipeline_quarantined_windows_total`` on /metrics.
- **device health governor** (``exec.health``): consecutive dispatch
  faults or watchdog trips flip serving to DEGRADED — fast lane off,
  pipelining off, every window executed inline per item on the
  proven op-at-a-time fallback path — then probe back to HEALTHY.
  ``device_health_state`` gauge + ``deviceHealth`` on /status.
- Two wedge classes fixed: a readback failure OUTSIDE ``_readback``'s
  per-item fallbacks now fails every unfinished item in the window
  (no ``_Pending.event`` left unset forever), and a collector death
  with items queued fails the backlog immediately instead of
  orphaning it until the next enqueue.
"""

from __future__ import annotations

import queue
import threading
import time

import numpy as np

from pilosa_tpu import fault
from pilosa_tpu.engine import kernels
# attribution context (r19): submits run on the CALLER's thread, so
# the executor's thread-local (tenant, plane, trace) is read once at
# _Pending construction and rides the item through the window
from pilosa_tpu.obs.ledger import query_context as _query_ctx


def _stall_error(msg: str, stage: str, elapsed: float = 0.0):
    # lazy: executor imports this module lazily and vice versa
    from pilosa_tpu.exec.executor import PipelineStalledError
    return PipelineStalledError(msg, stage=stage, elapsed=elapsed)


class _Pending:
    __slots__ = ("kind", "nodes", "leaves", "delta", "event", "result",
                 "error", "deadline", "abandoned", "stage", "delivered",
                 "tenant", "plane", "trace")

    def __init__(self, kind, nodes, leaves, delta=None, deadline=None):
        self.kind = kind      # "count" | "sum" | "minmax" | "rowcounts"
        #                       | "selcounts" | "tree" | "distinct"
        #                       | "bsirange" | "groupby" (r20)
        self.nodes = nodes    # count: tuple of plan trees;
        #                       selcounts: tuple of plane row slots;
        #                       tree: (slots, postfix prog, extras);
        #                       bsirange: (spec, operands, sig);
        #                       groupby: (args, static, sig);
        #                       others: None
        self.leaves = leaves  # count: plan leaves; others: plane[, filter]
        self.delta = delta    # rowcounts/selcounts: the plane's
        #                       DeltaOverlay (base⊕delta merge, r15);
        #                       sum/minmax/bsirange: the BSI plane's
        #                       BsiOverlay (r20)
        self.event = threading.Event()
        self.result = None
        self.error: Exception | None = None
        # deadline-aware waiting (r18): the query's time.monotonic()
        # cutoff.  On expiry the caller marks the item ABANDONED and
        # leaves — the group's shared finish skips it, co-batched
        # items are untouched.  ``stage`` names where the item
        # currently is (queued → dispatch → readback) so a timeout or
        # quarantine error can say what stalled.
        self.deadline = deadline
        self.abandoned = False
        self.stage = "queued"
        # True once a result/error was actually STORED — the event
        # alone cannot distinguish "answered" from "abandoned item
        # acknowledged" at the deadline boundary (see wait())
        self.delivered = False
        # cost-ledger attribution (r19), stamped here because the
        # submit runs on the caller thread: who pays for this item's
        # share of its window, which plane it scanned, and the trace
        # to exemplar the hottest shape bucket with
        self.tenant, self.plane, self.trace = _query_ctx()


class _Window:
    """One dispatched collection window's lifecycle record: what the
    watchdog ages, what quarantine fails, what owns a pipeline slot."""

    __slots__ = ("wid", "items", "stage", "t0", "pending",
                 "distinct_futs", "win_bytes", "slot_held", "inflight",
                 "done", "faulted", "bounded", "charge")

    def __init__(self, wid: int, items: list, slot_held: bool):
        self.wid = wid
        self.items = items          # every _Pending popped into this window
        self.stage = "dispatch"     # "dispatch" -> "readback"
        self.t0 = time.monotonic()  # current STAGE's start (reset on
        #                             progress so the watchdog bounds
        #                             stall time, not total time)
        self.pending: list = []     # dispatched (key, group, out, finish)
        self.distinct_futs: list = []
        self.win_bytes = 0
        self.slot_held = slot_held  # owns a _pipe_slots token
        self.inflight = False       # counted in _inflight_windows
        self.done = False           # closed (finished or quarantined)
        self.faulted = False        # any group fell back this window
        # True while the collector bounds this window's group joins
        # ITSELF (the multi-group fut.result(watchdog) path): the
        # whole-window watchdog defers, so a single hung group can
        # never take co-batched innocents down with it
        self.bounded = False
        # cost-ledger entries (r19): (tenant, shape, plane, byte
        # share, trace) per item, built alongside the win_bytes loop
        # so the charge reuses the already-computed group bytes; the
        # window's measured seconds apportion over these at readback
        self.charge: list = []


class CountBatcher:
    """Cross-request coalescing for Count, the BSI aggregates
    (Sum/Min/Max), whole-plane row counts, and Distinct — each
    kind/shape group in one collection window runs as one fused
    program + one read."""

    # adaptive-window bounds: MIN is the smallest non-zero window (below
    # it the window snaps to 0 — solo traffic must not wait at all);
    # MAX bounds queue-pressure growth so a burst can't add visible
    # latency to its own tail
    ADAPT_MIN = 0.0005
    ADAPT_MAX = 0.005

    def __init__(self, fused, window_s="adaptive", max_batch: int = 64,
                 stats=None, pipeline_depth: int = 2,
                 solo_fastlane: bool = True,
                 watchdog_s: float = 5.0,
                 probe_after_s: float = 5.0,
                 placement_key=None,
                 ledger=None, flight=None,
                 loop_fusion: bool = False):
        from pilosa_tpu.exec.fused import PingPong
        from pilosa_tpu.exec.health import DeviceHealthGovernor
        from pilosa_tpu.obs import NULL_FLIGHT, NULL_LEDGER, NopStats
        from pilosa_tpu.obs.metrics import (BYTE_BUCKETS, COUNT_BUCKETS,
                                            RATIO_BUCKETS)
        self.fused = fused
        # placement identity (ISSUE 16 mesh serving): joins every batch
        # group key, so co-batching / slot unions / plan-cache survival
        # decisions can never mix items compiled against different
        # placements.  None single-device — group keys unchanged.
        self.placement_key = placement_key
        self.adaptive = window_s == "adaptive"
        self.window_s = 0.0 if self.adaptive else float(window_s)
        self._win = 0.0 if self.adaptive else self.window_s
        self.max_batch = max_batch
        self.stats = stats or NopStats()
        # device-plane telemetry (r14): window occupancy and fill are
        # item counts / ratios, not latencies — declare their bucket
        # sets up front (idempotent; see Stats.set_buckets)
        self.stats.set_buckets("batcher_window_items", COUNT_BUCKETS)
        self.stats.set_buckets("batcher_window_fill_ratio", RATIO_BUCKETS)
        self.stats.set_buckets("kernel_window_bytes", BYTE_BUCKETS)
        self.stats.set_buckets("readback_overlap_ratio", RATIO_BUCKETS)
        # per-SHAPE window fill (r20): how many items each kind's
        # group actually coalesced per window — the attribution the
        # PQL-surface bench reasons about (a kind stuck at 1 under
        # concurrency is not co-batching)
        self.stats.set_buckets("pipeline_window_fill", COUNT_BUCKETS)
        # on-device dispatch loops (r24): merge a window's same-shape
        # selcounts groups (distinct planes/overlays) into ONE jitted
        # loop program — N same-shape scans, one enqueue.  Off by
        # default: today's per-group dispatch is the proven path.
        self.loop_fusion = bool(loop_fusion)
        self.stats.set_buckets("dispatch_loop_iters", COUNT_BUCKETS)
        # lifetime co-batched BSI aggregate items (mirror of the
        # bsi_batch_hits_total counter) for /status
        self._bsi_batch_hits = 0
        self._queue: list[_Pending] = []
        self._lock = threading.Lock()
        self._kick = threading.Event()
        self._thread: threading.Thread | None = None
        self._pool = None  # persistent group-dispatch pool (lazy)
        # pipelined readback (r17 tentpole): the collector hands each
        # dispatched window to a dedicated readback worker, so window
        # N's device compute overlaps window N-1's packed device->host
        # read instead of serializing behind it.  ``pipeline_depth``
        # bounds dispatched-but-unread windows via the _pipe_slots
        # semaphore (taken before a window dispatches, released when
        # its readback finishes); depth <= 1 restores the pre-r17
        # inline readback.
        self.pipeline_depth = max(1, int(pipeline_depth))
        self._readq: queue.Queue | None = (
            queue.Queue() if self.pipeline_depth > 1 else None)
        # the actual run-ahead bound: a slot is taken BEFORE a
        # window's groups dispatch and released when its readback
        # finishes (or when quarantine reclaims it — r18), so
        # dispatched-but-unread windows can never exceed pipeline_depth
        self._pipe_slots = threading.Semaphore(self.pipeline_depth)
        self._read_thread: threading.Thread | None = None
        # dispatched-but-unread windows; collector increments, reader
        # decrements — locked, a lost update would permanently skew
        # the depth gauge and the overlap observations
        self._inflight_windows = 0
        self._pipe_lock = threading.Lock()
        # pipeline watchdog + window quarantine (r18 tentpole): every
        # dispatched window registers here; the monitor thread bounds
        # each window's per-STAGE age by ``watchdog_s`` and
        # quarantines overage — items failed with a structured error
        # naming the stage, pipeline slot reclaimed, the wedged stage
        # worker superseded.  0 disables (the pre-r18 contract: no
        # monitor thread, unbounded dispatch waits).
        self.watchdog_s = max(0.0, float(watchdog_s))
        self._windows: dict[int, _Window] = {}
        self._win_seq = 0
        self._watchdog: threading.Thread | None = None
        self._busy = 0  # collector cycles mid-batch (watchdog idleness)
        self._trips = 0        # watchdog trips (mirror of the counter)
        self._quarantined = 0  # quarantined windows/groups
        # device-cost ledger + pipeline flight recorder (r19): the
        # ledger apportions each window's measured seconds/bytes to
        # the items it served; the flight recorder rings every
        # lifecycle event and dumps on incidents.  Both default to
        # null objects so standalone batchers pay nothing.
        self.ledger = ledger or NULL_LEDGER
        self.flight = flight or NULL_FLIGHT
        # per-group dispatch seconds captured in _dispatch_one and
        # popped into the window charge at readback (id(group) keys —
        # plain dict writes, GIL-atomic, no lock on the dispatch path)
        self._disp_s: dict[int, float] = {}
        # device health governor (r18): healthy→degraded→probing
        # breaker fed by dispatch faults + watchdog trips; degraded
        # serving runs windows on the per-item fallback path
        self.governor = DeviceHealthGovernor(
            stats=self.stats, probe_after_s=probe_after_s,
            flight=self.flight,
            tier=getattr(fused, "effective_tier", "xla"))
        # solo fast lane (r17 tentpole): with no queue pressure, a
        # width-1 request skips window formation entirely and rides a
        # pre-bound dispatch chain on the CALLER's thread — no enqueue,
        # no worker wakeup, no cross-thread event round-trip
        self.solo_fastlane = bool(solo_fastlane)
        # concurrent fast-lane dispatches in flight: the lane admits
        # only when it is ZERO, so overlapping callers fall into the
        # collection window instead — that pile-up is the adaptive
        # window's pressure signal, and coalescing (dedupe + one scan
        # per window) must keep winning under real concurrency
        self._fl_active = 0
        self._fl_lock = threading.Lock()
        # donated ping-pong output chains shared by the windowed and
        # fast-lane dispatch paths (see fused.PingPong)
        self._pp = PingPong()

    def _group_pool(self):
        # persistent: a pool built and torn down per collection window
        # would put thread churn back on the very hot loop this
        # batcher exists to strip of per-request overhead
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=8, thread_name_prefix="pilosa-batch-group")
        return self._pool

    @property
    def current_window(self) -> float:
        return self._win

    def health_payload(self) -> dict:
        """The ``/status`` deviceHealth block: governor state plus the
        watchdog's knobs and lifetime trip/quarantine counts."""
        out = self.governor.payload()
        out.update({
            "watchdogSeconds": self.watchdog_s,
            "quarantinedWindows": self._quarantined,
            "inflightWindows": self._inflight_windows,
            # r20: lifetime BSI-aggregate items that co-batched into
            # an existing same-plane group (the window-fill proof)
            "bsiBatchHits": self._bsi_batch_hits,
        })
        return out

    # -- item delivery (r18) -------------------------------------------------
    #
    # Every result/error hand-off routes through these two, so an item
    # can never be finished twice (quarantine racing a late readback)
    # and an ABANDONED item (deadline expired, caller gone) is skipped
    # without disturbing its co-batched neighbors.

    @staticmethod
    def _deliver(p: _Pending, value) -> None:
        if not (p.abandoned or p.event.is_set()):
            p.result = value
            p.delivered = True
        p.event.set()

    @staticmethod
    def _deliver_error(p: _Pending, err: Exception) -> None:
        if not (p.abandoned or p.event.is_set()):
            p.error = err
            p.delivered = True
        p.event.set()

    @staticmethod
    def _skip(p: _Pending) -> bool:
        """True when a finish loop should not compute this item's
        answer (abandoned by its caller, or already settled by
        quarantine)."""
        if p.abandoned or p.event.is_set():
            p.event.set()
            return True
        return False

    @staticmethod
    def _check_deadline(deadline: float | None,
                        stage: str = "dispatch") -> None:
        """Refuse work whose deadline already passed — the solo fast
        lane's pre-dispatch check, and the enqueue guard that keeps an
        expired caller from occupying a window slot at all."""
        if deadline is not None and time.monotonic() > deadline:
            from pilosa_tpu.exec.executor import QueryTimeoutError
            raise QueryTimeoutError(
                f"query deadline expired before {stage}", stage=stage)

    def _ensure_worker(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(target=self._run_collector,
                                            name="pilosa-count-batcher",
                                            daemon=True)
            self._thread.start()
        self._ensure_watchdog()

    def _enqueue(self, p: _Pending) -> _Pending:
        self.flight.record("enqueue", p.tenant, p.kind)
        with self._lock:
            self._queue.append(p)
            self._ensure_worker()
        self._kick.set()
        return p

    def wait(self, p: _Pending):
        """Block on an enqueued item's result (pairs with the
        ``enqueue_*`` methods — a caller that needs several items can
        enqueue them ALL into one collection window before waiting on
        any, instead of serializing one window per item).

        Deadline-aware (r18): an item carrying a deadline waits with a
        BOUNDED timeout; on expiry it is marked abandoned (the shared
        readback skips it) and ``QueryTimeoutError`` names the stage
        the item was in when the clock ran out."""
        if p.deadline is None:
            p.event.wait()
        else:
            remaining = p.deadline - time.monotonic()
            if remaining <= 0 or not p.event.wait(remaining):
                p.abandoned = True
                # boundary race: a deliverer between our timeout and
                # the abandon mark may have STORED the answer (then
                # p.delivered is True — return it) or may observe the
                # mark and skip (event set, nothing stored — the event
                # alone cannot tell the two apart, so only `delivered`
                # decides; a timeout here while a late store lands is
                # an honest timeout either way)
                if not p.delivered:
                    from pilosa_tpu.exec.executor import QueryTimeoutError
                    raise QueryTimeoutError(
                        "query deadline expired in the dispatch "
                        f"pipeline (stage={p.stage})", stage=p.stage)
        if p.error is not None:
            raise p.error
        return p.result

    def _submit(self, p: _Pending):
        return self.wait(self._enqueue(p))

    # -- solo fast lane (r17) ------------------------------------------------

    def _fl_try_enter(self) -> bool:
        """Atomically admit ONE fast-lane dispatch: fast lane enabled,
        device HEALTHY (a degraded device must not dispatch inline on
        caller threads, r18), adaptive window currently snapped to 0
        (traffic is solo — under queue pressure the window grows and
        coalescing wins), nothing already queued to join, and no other
        fast-lane dispatch in flight — the admission check and the
        in-flight increment happen under one lock, so two simultaneous
        callers can never both take the lane (the loser lands in the
        window, which is the adaptive pressure signal).  A True return
        must be paired with :meth:`_fl_leave`."""
        if not (self.solo_fastlane and self.adaptive
                and self._win == 0.0 and not self._queue
                and self.governor.fastlane_ok()):
            return False
        with self._fl_lock:
            if self._fl_active:
                return False
            self._fl_active += 1
        return True

    def _fl_leave(self) -> None:
        with self._fl_lock:
            self._fl_active -= 1

    def _fastlane_done(self, kind: str, nbytes: int,
                       wall: float = 0.0) -> None:
        # NO kernel_dispatch_seconds here: that family observes
        # enqueue-only on the windowed path (the read is deferred to
        # the packed readback), while a fast-lane call spans dispatch
        # PLUS the host read — mixing the two would corrupt the
        # compile-spike/enqueue-floor analysis the metric exists for.
        # Fast-lane latency is visible end-to-end in query_seconds /
        # query_stage_seconds.
        self.stats.count("solo_fastlane_hits_total", 1, kind=kind)
        if nbytes:
            self.stats.count("kernel_bytes_scanned_total", nbytes,
                             kind=kind)
        # solo item = whole charge (r19): the lane spans dispatch plus
        # the host read on the caller thread, so ``wall`` IS the
        # item's device cost — no apportioning needed
        tenant, plane, trace = _query_ctx()
        self.ledger.charge_solo(tenant, kind, plane, wall, nbytes,
                                trace_id=trace)

    def _fastlane_counts(self, nodes: tuple, leaves: tuple):
        """One request's Count run dispatched inline on the caller
        thread: same padding rule as the windowed `_dispatch_counts`
        (offset-0 single item), donated ping-pong scratch for the
        int32[K_pad, S] output.  None = fall back to the window."""
        from pilosa_tpu.exec.fused import pow2_bucket
        t0 = time.perf_counter()
        try:
            padded = tuple(nodes) + (nodes[0],) * (
                pow2_bucket(len(nodes)) - len(nodes))
            scratch = self._pp.scratch(
                (len(padded), leaves[0].shape[0]), "int32")
            out = self.fused.run_count_batch(padded, leaves,
                                             scratch=scratch)
            host = np.asarray(out).astype(np.int64)
            self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        self._fastlane_done("count",
                            sum(getattr(a, "nbytes", 0) for a in leaves),
                            wall=time.perf_counter() - t0)
        return [int(row.sum()) for row in host[:len(nodes)]]

    def _fastlane_selected(self, plane, slots: tuple, delta):
        """Width-N selected counts inline: sorted-unique slot gather
        (ascending stride), pre-bound device slot indices, donated
        int32[bucket] output slot.  None = fall back to the window."""
        from pilosa_tpu.exec.fused import pow2_bucket
        order = sorted(set(slots))
        pos = {s: i for i, s in enumerate(order)}
        t0 = time.perf_counter()
        try:
            scratch = self._pp.scratch(
                (pow2_bucket(len(order)),), "int32")
            out = self.fused.run_selected_counts(
                plane, tuple(order), delta=delta, scratch=scratch,
                sorted_idx=True)
            host = np.asarray(out).astype(np.int64)
            self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        nbytes = (len(order) * plane.shape[0] * plane.shape[-1] * 4
                  + (delta.nbytes if delta is not None else 0))
        self._fastlane_done("selcounts", nbytes,
                            wall=time.perf_counter() - t0)
        return host[[pos[s] for s in slots]]

    def _fastlane_rowcounts(self, plane, filter_words, delta):
        t0 = time.perf_counter()
        try:
            if delta is not None:
                out = self.fused.run_rowcounts_delta(
                    plane, delta, filter_words=filter_words)
                host = np.asarray(out).astype(np.int64)
            else:
                flags = (filter_words is not None,)
                leaves = ((plane,) if filter_words is None
                          else (plane, filter_words))
                scratch = self._pp.scratch((1, plane.shape[-2]),
                                           "int32")
                out = self.fused.run_rowcounts_batch(flags, leaves,
                                                     scratch=scratch)
                host = np.asarray(out).astype(np.int64)[0]
                self._pp.retire(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        self._fastlane_done(
            "rowcounts",
            plane.nbytes + (getattr(filter_words, "nbytes", 0) or 0)
            + (delta.nbytes if delta is not None else 0),
            wall=time.perf_counter() - t0)
        return host

    def _fastlane_tree(self, plane, slots: tuple, prog: tuple,
                       extras: tuple, delta):
        t0 = time.perf_counter()
        try:
            out = self.fused.run_tree_counts(plane, tuple(slots),
                                             (tuple(prog),),
                                             tuple(extras), delta=delta)
            val = int(np.asarray(out).astype(np.int64)[0])
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        nbytes = (len(slots) * plane.shape[0] * plane.shape[-1] * 4
                  + sum(getattr(a, "nbytes", 0) for a in extras)
                  + (delta.nbytes if delta is not None else 0))
        self._fastlane_done("tree", nbytes,
                            wall=time.perf_counter() - t0)
        return val

    @staticmethod
    def _agg_bytes(plane, extra, delta) -> int:
        return (plane.nbytes + extra
                + (delta.nbytes if delta is not None else 0))

    def _fastlane_agg(self, kind: str, plane, filter_words, delta):
        """One BSI Sum/Min/Max dispatched inline on the caller thread
        (batch of one through the per-plane family — same program
        bucketing as the windowed path).  None = fall back."""
        from pilosa_tpu.engine import bsi as bsik
        flags = (filter_words is not None,)
        filters = (filter_words,) if filter_words is not None else ()
        t0 = time.perf_counter()
        try:
            if kind == "sum":
                out = self.fused.run_sum_plane_batch(
                    plane, flags, filters, delta=delta)
                val = bsik.decode_sum_packed(np.asarray(out)[0])
            else:
                out = self.fused.run_minmax_plane_batch(
                    plane, flags, filters, delta=delta)
                val = bsik.decode_minmax_packed(np.asarray(out)[0])
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        self._fastlane_done(kind, self._agg_bytes(
            plane, sum(getattr(f, "nbytes", 0) for f in filters),
            delta), wall=time.perf_counter() - t0)
        return val

    def _fastlane_bsirange(self, plane, spec: tuple, operands: tuple,
                           delta):
        """One BSI Range-count inline: batch of one through
        ``run_range_batch``.  None = fall back to the window."""
        t0 = time.perf_counter()
        try:
            out = self.fused.run_range_batch(plane, (spec,),
                                             tuple(operands),
                                             delta=delta)
            val = int(np.asarray(out).astype(np.int64)[0])
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        self._fastlane_done("bsirange", self._agg_bytes(plane, 0, delta),
                            wall=time.perf_counter() - t0)
        return val

    def _fastlane_groupby(self, args: tuple, agg_kind, meta: tuple):
        """One GroupBy block inline on the caller thread.  None =
        fall back to the window."""
        from pilosa_tpu.exec import groupby as gb
        planes, ci, lp, fw, ap, dl = args
        t0 = time.perf_counter()
        try:
            out = self.fused.run_groupby_batch(planes, ci, lp, fw, ap,
                                               agg_kind, delta=dl)
            host = np.asarray(out)
        except Exception:  # noqa: BLE001 — windowed path is the fallback
            self.governor.record_fault()
            return None
        self._fastlane_done("groupby", self._groupby_bytes(args),
                            wall=time.perf_counter() - t0)
        return gb.unflatten_block(host, *meta, agg_kind)

    @staticmethod
    def _groupby_bytes(args: tuple) -> int:
        planes, _ci, lp, fw, ap, dl = args
        return (sum(getattr(p, "nbytes", 0) for p in planes)
                + lp.nbytes + (getattr(fw, "nbytes", 0) or 0)
                + (ap.nbytes if ap is not None else 0)
                + (dl.nbytes if dl is not None else 0))

    # -- blocking submits ----------------------------------------------------

    def submit(self, node, leaves, deadline: float | None = None) -> int:
        """Block until the coalesced batch containing this Count runs;
        returns the host-finished int64 total."""
        return self.submit_many((node,), leaves, deadline=deadline)[0]

    def submit_many(self, nodes, leaves,
                    deadline: float | None = None) -> list[int]:
        """A whole request's Count run as ONE batch item (the nodes
        share one leaf list); N concurrent requests coalesce into one
        program regardless of how many Counts each carries."""
        nodes, leaves = tuple(nodes), tuple(leaves)
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_counts(nodes, leaves)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self._submit(_Pending("count", nodes, leaves,
                                     deadline=deadline))

    def submit_sum(self, plane, filter_words, delta=None,
                   deadline: float | None = None) -> tuple[int, int]:
        """BSI Sum: (sum of offsets, non-null count), host-finished.
        Concurrent items over the SAME plane co-batch into one
        program (identical filters dedupe to one scan); ``delta`` (a
        ``BsiOverlay``, r20) merges the plane's pending write columns
        at dispatch — base⊕delta exact, no fold on the query path."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_agg("sum", plane, filter_words,
                                         delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("sum", None, leaves, delta=delta,
                                     deadline=deadline))

    def submit_minmax(self, plane, filter_words, delta=None,
                      deadline: float | None = None):
        """BSI Min/Max: (min, min_cnt, max, max_cnt) tuples — one per
        shard, plus one per overlay-touched word column when the
        plane carries a delta (zero-count entries; the host combine
        drops them).  Same co-batch/dedupe/overlay contract as
        :meth:`submit_sum`."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_agg("minmax", plane, filter_words,
                                         delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._submit(_Pending("minmax", None, leaves, delta=delta,
                                     deadline=deadline))

    def submit_bsirange(self, plane, spec: tuple, operands: tuple,
                        sig: tuple, delta=None,
                        deadline: float | None = None) -> int:
        """One BSI Range-count (``Count(Row(field op p))`` and the
        between forms) as a first-class batch item: the window's
        range counts over the SAME (plane, overlay) pair fuse into
        one program referencing the plane once, and identical
        predicates (same ``sig``: op keys, offsets, filter identity)
        dedupe to a single comparison chain.  ``spec`` is the item's
        static shape, ``operands`` its traced masks/sign/filter
        arrays (see ``fused.run_range_batch``)."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_bsirange(plane, spec, operands,
                                              delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self.wait(self.enqueue_bsirange(plane, spec, operands,
                                               sig, delta,
                                               deadline=deadline))

    def enqueue_bsirange(self, plane, spec: tuple, operands: tuple,
                         sig: tuple, delta=None,
                         deadline: float | None = None) -> _Pending:
        """Non-blocking :meth:`submit_bsirange`: a request carrying K
        range Counts enqueues them ALL into one collection window
        before waiting on any."""
        self._check_deadline(deadline, stage="queued")
        return self._enqueue(_Pending(
            "bsirange", (spec, tuple(operands), sig), (plane,),
            delta=delta, deadline=deadline))

    def submit_groupby(self, planes: tuple, combo_idx, last_plane,
                       filter_words, agg_plane, agg_kind,
                       meta: tuple, digest, delta=None,
                       deadline: float | None = None) -> dict:
        """One GroupBy combination block through the window machinery
        (r20): identical concurrent blocks (same planes, same
        combinations — ``digest`` hashes the combo slots) dedupe to
        ONE program, and any block shares its collection window's
        dispatch pool + packed readback with concurrent Counts and
        aggregates.  ``delta``: the agg plane's ``BsiOverlay`` —
        aggregate GroupBys answer base⊕delta in-program.  ``meta`` =
        (n_combos, n_last, depth) for the unflatten; returns the
        block's output dict of host arrays."""
        self._check_deadline(deadline)
        args = (planes, combo_idx, last_plane, filter_words, agg_plane,
                delta)
        if self._fl_try_enter():
            try:
                out = self._fastlane_groupby(args, agg_kind, meta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        sig = (tuple(id(p) for p in planes), id(last_plane),
               id(filter_words) if filter_words is not None else 0,
               id(agg_plane) if agg_plane is not None else 0,
               id(delta) if delta is not None else 0,
               agg_kind, digest)
        return self._submit(_Pending(
            "groupby", (args, (agg_kind, meta), sig), (last_plane,),
            deadline=deadline))

    def submit_rowcounts(self, plane, filter_words=None,
                         delta=None,
                         deadline: float | None = None) -> np.ndarray:
        """Whole-plane per-row totals int64[R_pad] (cross-shard reduce
        on device — callers gate on the int32-exact shard bound).
        Identical concurrent items (same plane/filter objects) share
        one computation.  ``delta`` (the plane's DeltaOverlay) makes
        the answer base⊕delta — items over the same (plane, overlay)
        pair still dedupe to one scan."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_rowcounts(plane, filter_words,
                                               delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self.wait(self.enqueue_rowcounts(plane, filter_words,
                                                delta, deadline=deadline))

    def enqueue_rowcounts(self, plane, filter_words=None,
                          delta=None,
                          deadline: float | None = None) -> _Pending:
        """Non-blocking variant: returns a handle for :meth:`wait`, so
        a request needing several row-count reads (filtered TopN with
        tanimoto) lands them all in ONE collection window."""
        self._check_deadline(deadline, stage="queued")
        leaves = (plane,) if filter_words is None else (plane, filter_words)
        return self._enqueue(_Pending("rowcounts", None, leaves,
                                      delta=delta, deadline=deadline))

    def submit_selected(self, plane, slots: tuple,
                        delta=None,
                        deadline: float | None = None) -> np.ndarray:
        """Selected-row Counts (the multi-query fused popcount): the
        window's items over the SAME resident plane merge into one
        row-gather + popcount program — one pass over the UNION of
        requested rows, N accumulators — and the per-item answers come
        back int64[len(slots)] in the caller's slot order.  Duplicate
        slots across concurrent requests are computed once.  ``delta``
        merges the plane's pending write overlay at dispatch time."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_selected(plane, tuple(slots),
                                              delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self._submit(_Pending("selcounts", tuple(slots), (plane,),
                                     delta=delta, deadline=deadline))

    def submit_tree(self, plane, slots: tuple, prog: tuple,
                    extras: tuple = (), delta=None,
                    deadline: float | None = None) -> int:
        """One compound-tree Count (whole-tree compilation, r16): the
        window's tree items over the SAME (plane, overlay) pair union
        their gathered row slots into ONE in-program gather and fold
        every item's postfix program in one fused dispatch — N
        concurrent compound queries cost one memory pass and join the
        window's single packed readback."""
        self._check_deadline(deadline)
        if self._fl_try_enter():
            try:
                out = self._fastlane_tree(plane, slots, prog, extras,
                                          delta)
            finally:
                self._fl_leave()
            if out is not None:
                return out
        return self.wait(self.enqueue_tree(plane, slots, prog, extras,
                                           delta, deadline=deadline))

    def enqueue_tree(self, plane, slots: tuple, prog: tuple,
                     extras: tuple = (), delta=None,
                     deadline: float | None = None) -> _Pending:
        """Non-blocking :meth:`submit_tree`: a request carrying K
        compound Counts enqueues them ALL into one collection window
        before waiting on any."""
        self._check_deadline(deadline, stage="queued")
        return self._enqueue(_Pending(
            "tree", (tuple(slots), tuple(prog), tuple(extras)),
            (plane,), delta=delta, deadline=deadline))

    def submit_distinct(self, plane, filter_words,
                        deadline: float | None = None):
        """BSI Distinct presence: host (pos bool[2^d], neg bool[2^d]).
        Coalescing here is DEDUPLICATION only — the presence scan is a
        multi-dispatch block loop, so stacking would multiply compute;
        identical concurrent requests share one scan."""
        self._check_deadline(deadline)
        return self._submit(_Pending("distinct", None,
                                     (plane,) if filter_words is None
                                     else (plane, filter_words),
                                     deadline=deadline))

    # -- collector -----------------------------------------------------------

    def _superseded(self) -> bool:
        """True when a fresh collector replaced this thread (the
        quarantine restart, r18): the zombie must stop touching the
        shared queue the moment it notices."""
        return self._thread is not threading.current_thread()

    def _run_collector(self) -> None:
        """Collector main: one window cycle per loop, wrapped so a
        cycle failure can never kill the worker silently — before r18
        a collector death with items already queued orphaned them
        until the NEXT enqueue happened to call ``_ensure_worker``;
        now the queued backlog is failed with structured errors and
        the same thread keeps serving."""
        while True:
            if self._superseded():
                return
            try:
                self._collect_once()
            except Exception as e:  # noqa: BLE001 — worker must survive
                self._fail_backlog(e)

    def _fail_backlog(self, exc: Exception) -> None:
        """Collector-death path: every queued item is failed loudly
        (structured error naming the stage) instead of wedging until a
        future enqueue restarts the worker."""
        with self._lock:
            batch = self._queue[:]
            self._queue.clear()
            self._kick.clear()
        err = _stall_error(
            f"dispatch collector failed; {len(batch)} queued item(s) "
            f"aborted: {exc!r}", stage="collect")
        err.__cause__ = exc
        for p in batch:
            self._deliver_error(p, err)

    def _collect_once(self) -> None:
        self._kick.wait()
        if self._superseded():
            return
        # collection window: let concurrent submitters pile in.
        # Adaptive mode keeps it at 0 for solo traffic and grows it
        # only while batches actually coalesce.
        win = self._win if self.adaptive else self.window_s
        if win > 0:
            time.sleep(win)
        with self._lock:
            backlog = len(self._queue)
            batch = self._queue[: self.max_batch]
            del self._queue[: len(batch)]
            if not self._queue:
                self._kick.clear()
        if not batch:
            return
        # busy marker: the idle-exiting watchdog must outlive every
        # popped-but-not-yet-registered batch (see _watchdog_loop)
        with self._lock:
            self._busy += 1
        try:
            self._process_batch(batch, backlog)
        finally:
            with self._lock:
                self._busy -= 1

    def _process_batch(self, batch: list, backlog: int) -> None:
        if self.adaptive:
            if len(batch) > 1 or backlog > len(batch):
                self._win = min(max(self._win * 2, self.ADAPT_MIN),
                                self.ADAPT_MAX)
            elif self._win:
                nxt = self._win / 2
                self._win = 0.0 if nxt < self.ADAPT_MIN else nxt
        self.stats.count("batcher_batches", 1)
        self.stats.count("batcher_items", len(batch))
        self.stats.gauge("batcher_window_seconds", self._win)
        # window occupancy + fill ratio (r14 device telemetry):
        # the coalescing histograms the config23 roofline reasons
        # about — how many items a window actually collects and
        # how close it runs to max_batch
        self.stats.observe("batcher_window_items", float(len(batch)))
        self.stats.observe("batcher_window_fill_ratio",
                           len(batch) / self.max_batch)
        # stacked outputs need uniform shapes: group by kind + the
        # output-shaping leaf dimension (counts: n_shards — mixed
        # row/plane leaf ranks fuse fine, only the int32[S] outputs
        # must stack; aggregates/rowcounts: the full plane shape;
        # selcounts: the plane IDENTITY — one gather per plane)
        groups: dict[tuple, list[_Pending]] = {}
        for p in batch:
            if p.kind == "count":
                key = ("count", p.leaves[0].shape[0])
            elif p.kind == "selcounts":
                # delta identity joins the key: items over the
                # same (plane, overlay) pair slot-union into one
                # gather; a fresher overlay is a different answer
                key = ("selcounts", id(p.leaves[0]),
                       id(p.delta) if p.delta is not None else 0)
            elif p.kind == "tree":
                # same (plane, overlay) pair → one gather of the
                # slot UNION serves every item's program
                key = ("tree", id(p.leaves[0]),
                       id(p.delta) if p.delta is not None else 0)
            elif p.kind == "rowcounts" and p.delta is not None:
                key = ("rowcounts-delta", id(p.leaves[0]),
                       id(p.delta),
                       id(p.leaves[1]) if len(p.leaves) == 2 else 0)
            elif p.kind in ("sum", "minmax", "bsirange"):
                # BSI aggregates group by plane IDENTITY (r20): the
                # window's same-plane aggregates co-batch into one
                # program referencing the plane once, and identical
                # items (same filter / predicate signature) dedupe to
                # one scan inside the dispatch.  The overlay identity
                # joins the key like selcounts — a fresher overlay is
                # a different answer.
                key = (p.kind, id(p.leaves[0]),
                       id(p.delta) if p.delta is not None else 0)
            elif p.kind == "groupby":
                # identical concurrent GroupBy blocks (same planes,
                # same combination block — the sig carries a digest)
                # dedupe to ONE program; distinct blocks still share
                # the window's dispatch pool and packed readback
                key = ("groupby",) + p.nodes[2]
            else:
                key = (p.kind, p.leaves[0].shape)
            # placement identity rides every group key (kind stays at
            # key[0] — fallback routing and fill attribution key on it)
            groups.setdefault(key + (self.placement_key,),
                              []).append(p)
        # per-shape coalescing attribution (r20): window fill by kind,
        # plus the lifetime count of BSI-aggregate items that joined
        # an existing same-plane group (the co-batch proof counter)
        for key, group in groups.items():
            self.stats.observe("pipeline_window_fill",
                               float(len(group)), kind=key[0])
            if key[0] in ("sum", "minmax", "bsirange") \
                    and len(group) > 1:
                self.stats.count("bsi_batch_hits_total",
                                 len(group) - 1, kind=key[0])
                self._bsi_batch_hits += len(group) - 1
        if self.loop_fusion:
            groups = self._fuse_selcounts_loops(groups)
        # DEGRADED serving (r18 governor): the device is suspect —
        # every group runs inline per item on the proven op-at-a-time
        # fallback path (answers stay exact; throughput, not
        # correctness, is what degrades).  No pipeline, no fast lane,
        # no shared readback to stall.
        if not self.governor.admit():
            for p in batch:
                p.stage = "dispatch"
            for key, group in groups.items():
                if key[0] == "distinct":
                    self._run_distinct(group)
                else:
                    self._run_fallback(key, group)
            return
        self._dispatch_window(batch, groups)

    def _fuse_selcounts_loops(self, groups: dict) -> dict:
        """The r24 loop-fusion grouping rule: selcounts groups key on
        plane IDENTITY, so a window touching K same-shape planes (or K
        overlay snapshots of one plane) costs K dispatches — merge ≥2
        groups sharing (plane shape, overlay pow2 bucket) into ONE
        ``selcounts-loop`` super-group that
        :meth:`fused.FusedCache.run_selected_counts_loop` serves as a
        single jitted loop program.  Items keep their per-group slot
        unions inside the dispatch; the merged kind routes to the same
        per-item selcounts fallback on any failure."""
        sigs: dict[tuple, list] = {}
        for key, group in groups.items():
            if key[0] != "selcounts":
                continue
            p0 = group[0]
            d = p0.delta
            sigs.setdefault(
                (p0.leaves[0].shape,
                 d.rows.shape[0] if d is not None else None,
                 key[-1]),  # placement identity stays unmixed
                []).append(key)
        for sig, keys in sigs.items():
            if len(keys) < 2:
                continue
            merged: list = []
            for k in keys:
                merged.extend(groups.pop(k))
            groups[("selcounts-loop", sig[0], sig[1],
                    self.placement_key)] = merged
        return groups

    def _dispatch_window(self, batch: list, groups: dict) -> None:
        """The fused pipeline: one dispatch per group, the window's
        outputs packed into one readback (handed to the readback
        worker when pipelining is on).  Registered with the watchdog
        for the whole dispatch→readback lifetime."""
        # BATCHED READBACK (r12): every one-program kind dispatches
        # asynchronously, then the whole window's outputs are
        # packed into ONE device array and read with ONE
        # device->host transfer — on transports with a fixed
        # per-read RPC floor, the window now pays that floor once
        # total, not once per kind/shape group.  Distinct stays on
        # the pool: its presence scan is a multi-dispatch host
        # loop that cannot join a single readback.
        pending = []
        distinct_futs = []
        program_groups = []
        for key, group in groups.items():
            if key[0] == "distinct":
                distinct_futs.append(self._group_pool().submit(
                    self._run_distinct, group))
            else:
                program_groups.append((key, group))
        # run-ahead bound BEFORE dispatching: at pipeline_depth
        # dispatched-but-unread windows the collector waits here,
        # so device output held by in-flight windows never exceeds
        # the documented knob.  Quarantine reclaims a stuck window's
        # slot, so this acquire cannot deadlock behind a wedge.
        slot_held = False
        use_pipe = (self._readq is not None
                    and self.governor.pipelining_ok())
        if use_pipe and (program_groups or distinct_futs):
            self._pipe_slots.acquire()
            slot_held = True
        w = self._register_window(batch, slot_held)
        for p in batch:
            p.stage = "dispatch"
        if len(program_groups) == 1:
            # the common (and solo-path) case skips the pool
            # round-trip: one group, dispatch inline — a hang here
            # wedges the collector, which the watchdog resolves by
            # quarantining the window and superseding this thread
            key, group = program_groups[0]
            try:
                pending.append((key, group)
                               + self._dispatch_one(key, group))
            except Exception:  # noqa: BLE001 — per-item fallback
                w.faulted = True
                self.governor.record_fault()
                if not w.done:
                    # the fallback gets its OWN stage budget: aging it
                    # against the failed dispatch's t0 would let the
                    # watchdog quarantine a legitimately progressing
                    # per-item recovery
                    w.t0 = time.monotonic()
                    self._run_fallback(key, group)
        elif program_groups:
            # dispatch groups CONCURRENTLY (a first-time compile
            # in one group must not stall the others' warm
            # dispatches), then join for the window's single
            # packed readback.  Each group's join is bounded by the
            # watchdog (r18): a hung group fails ALONE — the other
            # groups' (other planes', other kinds') items proceed.
            from concurrent.futures import TimeoutError as _FutTimeout
            futs = [(key, group, self._group_pool().submit(
                self._dispatch_one, key, group))
                for key, group in program_groups]
            bound = self.watchdog_s if self.watchdog_s > 0 else None
            # the collector bounds each join ITSELF here, so the
            # whole-window watchdog defers (w.bounded): a single hung
            # group fails alone — co-batched groups of other kinds /
            # planes proceed, and innocents are never quarantined
            w.bounded = True
            for key, group, fut in futs:
                try:
                    pending.append((key, group) + fut.result(bound))
                except _FutTimeout:
                    self._fail_stalled_group(key, group, bound)
                    w.faulted = True
                except Exception:  # noqa: BLE001 — per-item fallback
                    w.faulted = True
                    self.governor.record_fault()
                    if not w.done:
                        # hand the inline fallback BACK to the
                        # watchdog with a fresh budget: under
                        # w.bounded it would otherwise run unwatched —
                        # a fallback that hangs on the same sick
                        # device must still be quarantinable
                        w.t0 = time.monotonic()
                        w.bounded = False
                        try:
                            self._run_fallback(key, group)
                        finally:
                            w.bounded = True
                # progress heartbeat: the watchdog bounds STALL time
                # per stage, not the sum of a wide window's joins
                w.t0 = time.monotonic()
            w.bounded = False
        if w.done:
            # quarantined mid-dispatch: items already failed, slot
            # already reclaimed, a fresh collector owns the queue —
            # this (zombie) thread drops everything on the floor
            return
        # bytes the window's fused programs read from HBM (r14):
        # per-kind scan-volume counters feed capacity math, and
        # bytes / (readback-start -> readback-complete) is the
        # LIVE bandwidth the config23 roofline bench measures
        # offline — the gauge tracks how far serving sits from
        # that roof (see _finish_window for why the clock starts
        # at the read, not the dispatch)
        win_bytes = 0
        for key, group, _, _ in pending:
            nbytes = self._group_bytes(key[0], group)
            if nbytes:
                self.stats.count("kernel_bytes_scanned_total",
                                 nbytes, kind=key[0])
                win_bytes += nbytes
            # ledger entries (r19) built here so the charge reuses the
            # group-bytes estimate: each item's weight is its equal
            # split of its group's scan (the group's items share one
            # fused pass — the plane is read once for all of them)
            share = nbytes / max(1, len(group))
            for p in group:
                w.charge.append((p.tenant, p.kind, p.plane, share,
                                 p.trace))
        w.pending = pending
        w.distinct_futs = distinct_futs
        w.win_bytes = win_bytes
        if not (pending or distinct_futs):
            # every dispatch fell back or was failed: nothing to read
            if self._window_done(w):
                self.flight.record("deliver", f"w{w.wid}", "",
                                   float(len(w.items)))
            return
        with self._pipe_lock:
            w.stage = "readback"
            w.t0 = time.monotonic()
        self.flight.record("readback", f"w{w.wid}")
        for p in batch:
            p.stage = "readback"
        if slot_held:
            # PIPELINED READBACK (r17): hand the dispatched window
            # to the readback worker and immediately collect the
            # next one — window N's device compute overlaps window
            # N-1's packed device->host read.
            with self._pipe_lock:
                if w.done:
                    return
                overlapped = self._inflight_windows > 0
                self._inflight_windows += 1
                w.inflight = True
                depth = self._inflight_windows
            self.stats.observe("readback_overlap_ratio",
                               1.0 if overlapped else 0.0)
            self.stats.gauge("dispatch_pipeline_depth", depth)
            self._ensure_reader()
            self._readq.put(w)
        else:
            err = None
            try:
                self._finish_window(w)
            except Exception as e:  # noqa: BLE001 — final guard (r18):
                err = e            # fail, never wedge, the whole window
            if err is not None:
                self._fail_window_items(
                    w, _wrap_readback_error(err))
            if self._window_done(w):
                self.flight.record("deliver", f"w{w.wid}", "",
                                   float(len(w.items)))
                if err is None and not w.faulted:
                    self.governor.record_success()

    def _fail_stalled_group(self, key, group, bound: float) -> None:
        """One group's dispatch exceeded the watchdog bound while the
        rest of the window proceeded: fail ONLY its items (structured,
        naming the stage) and notify the governor — the wedged pool
        worker parks until the hang resolves."""
        self._trips += 1
        self._quarantined += 1
        self.stats.count("pipeline_watchdog_trips_total", 1,
                         stage="dispatch")
        self.stats.count("pipeline_quarantined_windows_total", 1)
        # flight events name the SAME stage the structured error below
        # carries — the dump's quarantine line and the caller's
        # exception must agree on what stalled (pinned in tests).
        # Recorded + dumped BEFORE the governor trip so the governor's
        # own degrade incident cannot dump first and rate-limit the
        # quarantine artifact away.
        self.flight.record("watchdog_trip", key[0], "dispatch", bound)
        self.flight.record("quarantine", key[0], "dispatch", bound)
        self.flight.incident("quarantine", key[0], "dispatch")
        self.governor.record_trip()
        err = _stall_error(
            f"{key[0]} dispatch stalled past the "
            f"{bound:g}s watchdog bound and was quarantined "
            f"(dispatch_watchdog_seconds)", stage="dispatch",
            elapsed=bound)
        for p in group:
            self._deliver_error(p, err)

    # -- window registry + watchdog (r18) ------------------------------------

    def _register_window(self, batch: list, slot_held: bool) -> _Window:
        with self._pipe_lock:
            self._win_seq += 1
            w = _Window(self._win_seq, batch, slot_held)
            if self.watchdog_s > 0:
                self._windows[w.wid] = w
        self.flight.record("dispatch", f"w{w.wid}", "",
                           float(len(batch)))
        return w

    def _window_done(self, w: _Window) -> bool:
        """Idempotently close a window: unregister it, release its
        pipeline slot, settle the depth gauge.  Returns False when the
        window was already closed (quarantined, or a zombie worker
        finishing late) — the caller must not treat it as its own."""
        with self._pipe_lock:
            if w.done:
                return False
            w.done = True
            self._windows.pop(w.wid, None)
            depth = None
            if w.inflight:
                w.inflight = False
                self._inflight_windows -= 1
                depth = self._inflight_windows
            slot = w.slot_held
            w.slot_held = False
        if depth is not None:
            self.stats.gauge("dispatch_pipeline_depth", depth)
        if slot:
            self._pipe_slots.release()
        # belt: a quarantined window never reaches _finish_window's
        # pop, so its captured group dispatch seconds drain here
        for _k, g, _o, _f in w.pending:
            self._disp_s.pop(id(g), None)
        return True

    def _fail_window_items(self, w: _Window, err: Exception) -> None:
        """Fail every UNFINISHED item in the window (finished and
        abandoned ones are skipped by the delivery guard)."""
        for p in w.items:
            self._deliver_error(p, err)

    def _ensure_watchdog(self) -> None:
        if self.watchdog_s <= 0:
            return  # knob off: the exact pre-r18 thread census
        if self._watchdog is None or not self._watchdog.is_alive():
            self._watchdog = threading.Thread(
                target=self._watchdog_loop,
                name="pilosa-pipeline-watchdog", daemon=True)
            self._watchdog.start()

    # consecutive idle ticks after which the monitor thread parks
    # itself (restarted by the next enqueue): a short-lived executor
    # must not leak a polling thread for the process lifetime
    WATCHDOG_IDLE_TICKS = 8

    def _watchdog_loop(self) -> None:
        """Monitor thread: bound every in-flight window's per-stage
        age; quarantine overage.  Happy-path cost is one short sleep
        and a scan of at most pipeline_depth+1 dict entries per tick —
        nothing touches the dispatch hot path.  Windows whose group
        joins the collector is bounding itself (``w.bounded``) are
        skipped: their per-group timeout is the enforcer there, and a
        whole-window quarantine would take co-batched innocents down.
        Exits after WATCHDOG_IDLE_TICKS quiet ticks (the next enqueue
        revives it) so an idle batcher costs no polling."""
        idle = 0
        while True:
            # interval re-derived per tick so a runtime watchdog_s
            # change (tests, live tuning) takes effect without a
            # thread restart
            time.sleep(max(0.02, min(self.watchdog_s / 4.0, 1.0))
                       if self.watchdog_s > 0 else 0.25)
            if self.watchdog_s <= 0:
                with self._lock:
                    if self._watchdog is threading.current_thread():
                        self._watchdog = None
                return
            now = time.monotonic()
            with self._pipe_lock:
                stuck = [w for w in self._windows.values()
                         if not w.done and not w.bounded
                         and now - w.t0 > self.watchdog_s]
            for w in stuck:
                self._quarantine(w, now - w.t0)
            # dead-worker sweep (belt over the _run_collector wrapper):
            # a collector that died with items queued is restarted NOW,
            # not at the next enqueue
            with self._lock:
                backlog = bool(self._queue)
                t = self._thread
                quiet = (not self._queue and not self._busy
                         and not self._windows)
                if quiet:
                    idle += 1
                    if (idle >= self.WATCHDOG_IDLE_TICKS
                            and self._watchdog
                            is threading.current_thread()):
                        # park: _ensure_worker (under this same lock)
                        # restarts the monitor before any new item can
                        # enqueue, so no window ever runs unwatched
                        self._watchdog = None
                        return
                else:
                    idle = 0
            if backlog and t is not None and not t.is_alive():
                self._restart_collector()

    def _quarantine(self, w: _Window, age: float) -> None:
        """A window exceeded the watchdog bound in ``w.stage``: fail
        its unfinished items with a structured error naming the stage,
        reclaim its pipeline slot, and supersede the wedged stage
        worker with a fresh thread so the queue keeps draining (the
        zombie exits on its own when the hang resolves)."""
        stage = w.stage
        # read BEFORE _window_done clears it: was the window handed to
        # the readback worker, or was it finishing INLINE on the
        # collector (probe windows, depth<=1 fallbacks)?  The restart
        # must supersede whichever thread is actually wedged.
        handed = w.inflight
        if not self._window_done(w):
            return  # finished while we decided: no trip
        self._trips += 1
        self._quarantined += 1
        self.stats.count("pipeline_watchdog_trips_total", 1, stage=stage)
        self.stats.count("pipeline_quarantined_windows_total", 1)
        # same-stage contract as _fail_stalled_group: the quarantine
        # flight event's detail is the stage the error names.  Flight
        # events + incident dump run BEFORE the governor hears about
        # the trip: its own degrade incident would otherwise dump
        # first and rate-limit this one away — the artifact must carry
        # the quarantine line (pinned in tests)
        self.flight.record("watchdog_trip", f"w{w.wid}", stage, age)
        self.flight.record("quarantine", f"w{w.wid}", stage, age)
        self.flight.incident("quarantine", f"w{w.wid}", stage)
        self.governor.record_trip()
        err = _stall_error(
            f"dispatch-pipeline window stalled in {stage} for "
            f"{age:.2f}s (dispatch_watchdog_seconds="
            f"{self.watchdog_s:g}); the window was quarantined and "
            f"its pipeline slot reclaimed", stage=stage, elapsed=age)
        self._fail_window_items(w, err)
        if stage == "readback" and handed and self._readq is not None:
            self._restart_reader()
        else:
            self._restart_collector()

    def _restart_collector(self) -> None:
        self._thread = threading.Thread(target=self._run_collector,
                                        name="pilosa-count-batcher",
                                        daemon=True)
        self._thread.start()
        # wake a zombie parked on the kick (it exits on supersession)
        # and hand any backlog straight to the fresh worker
        self._kick.set()

    def _restart_reader(self) -> None:
        self._read_thread = threading.Thread(
            target=self._read_loop, name="pilosa-batch-readback",
            daemon=True)
        self._read_thread.start()
        # a parked zombie (defensive: restarts normally happen while
        # the old reader is wedged mid-window) wakes on the sentinel
        # and exits on supersession
        self._readq.put(None)

    # -- readback worker -----------------------------------------------------

    def _ensure_reader(self) -> None:
        if self._read_thread is None or not self._read_thread.is_alive():
            self._read_thread = threading.Thread(
                target=self._read_loop, name="pilosa-batch-readback",
                daemon=True)
            self._read_thread.start()

    def _read_loop(self) -> None:
        while True:
            if self._read_thread is not threading.current_thread():
                return  # superseded by a quarantine restart (r18)
            w = self._readq.get()
            if w is None or w.done:
                continue  # wake sentinel / already-quarantined window
            err = None
            try:
                self._finish_window(w)
            except Exception as e:  # noqa: BLE001 — final guard (r18):
                err = e
            if err is not None:
                # before r18 this swallow could leave a window's
                # _Pending.event unset forever when _finish_window
                # raised OUTSIDE _readback's per-item fallbacks; now
                # every unfinished item is failed loudly
                self._fail_window_items(w, _wrap_readback_error(err))
            if self._window_done(w):
                self.flight.record("deliver", f"w{w.wid}", "",
                                   float(len(w.items)))
                if err is None and not w.faulted:
                    self.governor.record_success()

    def _finish_window(self, w: _Window) -> None:
        """Read one dispatched window back and finish its items — the
        half of the old loop tail that runs on the readback worker
        when pipelining is on (inline when off)."""
        if fault.ACTIVE:
            # chaos seam (r18): a stalled device→host read
            fault.fire("exec.readback_hang")
        # bandwidth wall clock starts HERE, not at dispatch: a
        # pipelined window's queue wait overlaps the previous window's
        # read (the feature working as intended) and must not deflate
        # the gauge — the read itself still blocks on any residual
        # compute, so bytes/wall remains the live achieved bandwidth
        t0 = time.perf_counter()
        self._readback(w)
        wall = time.perf_counter() - t0
        # cost-ledger charge (r19): this window's measured device time
        # = per-group dispatch seconds (captured in _dispatch_one) +
        # the packed readback wall, apportioned to the items by their
        # bytes-scanned weight.  Exact-sum split — the ledger pins
        # sum(shares) == window total bit-for-bit.
        if w.charge:
            disp = 0.0
            for _key, group, _out, _fin in w.pending:
                disp += self._disp_s.pop(id(group), 0.0)
            self.ledger.charge_window(disp + wall, w.charge)
        if self.placement_key is not None and w.pending:
            # meshed window: the packed read blocks on the program's
            # residual compute INCLUDING its cross-shard collectives,
            # so the readback wall is the observable collective +
            # transfer cost per window on the mesh
            self.stats.observe("mesh_collective_seconds", wall)
        if w.win_bytes:
            # per-window scan-volume distribution (byte-scale
            # buckets) + the live bandwidth the window achieved
            self.stats.observe("kernel_window_bytes",
                               float(w.win_bytes))
            if wall > 0:
                self.stats.gauge("kernel_bandwidth_gbps",
                                 round(w.win_bytes / wall / 1e9, 4))
        for f in w.distinct_futs:
            try:
                f.result()
            except Exception:  # noqa: BLE001 — _run_distinct sets its
                pass           # items' events/errors itself

    def _dispatch_one(self, key, group):
        """Build + enqueue one group's fused program; returns
        ``(device_out, finish)`` with the device->host read deferred to
        the window's single packed readback.  Raises on dispatch
        failure (the caller falls back per item).  Dispatch time is
        observed per kind — a first-time XLA compile shows up as a
        spike in ``kernel_dispatch_seconds{kind=...}``, warm dispatches
        as the enqueue floor."""
        t0 = time.perf_counter()
        kind = key[0]
        if fault.ACTIVE:
            # chaos seams (r18): a hung XLA compile / stalled dispatch
            # (delay action) and a faulting dispatch (error action) —
            # the sites the watchdog, quarantine and governor are
            # proven against
            fault.fire("exec.dispatch_hang", kind=kind)
            fault.fire("exec.dispatch_error", kind=kind)
        if kind == "count":
            ret = self._dispatch_counts(group)
        elif kind == "rowcounts":
            ret = self._dispatch_rowcounts(group)
        elif kind == "rowcounts-delta":
            ret = self._dispatch_rowcounts_delta(group)
        elif kind == "selcounts":
            ret = self._dispatch_selcounts(group)
        elif kind == "selcounts-loop":
            ret = self._dispatch_selcounts_loop(group)
        elif kind == "tree":
            ret = self._dispatch_tree(group)
        elif kind == "bsirange":
            ret = self._dispatch_bsirange(group)
        elif kind == "groupby":
            ret = self._dispatch_groupby(group)
        else:
            ret = self._dispatch_aggs(kind, group)
        elapsed = time.perf_counter() - t0
        self.stats.observe("kernel_dispatch_seconds", elapsed,
                           kind=kind)
        # the window charge picks this up at readback (keyed by group
        # identity — the pending tuples carry the same list object)
        self._disp_s[id(group)] = elapsed
        return ret

    @staticmethod
    def _group_bytes(kind: str, group: list[_Pending]) -> int:
        """Estimated HBM bytes one group's fused program reads.  count
        leaves each enter the program (sum of leaf footprints);
        selcounts gathers only the UNION of requested rows; the
        dedup'd kinds (rowcounts/sum/minmax/distinct) scan each unique
        plane[, filter] once however many items share it."""
        if kind == "selcounts":
            plane = group[0].leaves[0]
            rows = {s for p in group for s in p.nodes}
            return len(rows) * plane.shape[0] * plane.shape[-1] * 4
        if kind == "selcounts-loop":
            # per-(plane, overlay) pair: that pair's slot union
            unions: dict[tuple, set] = {}
            planes: dict[tuple, object] = {}
            for p in group:
                k = (id(p.leaves[0]),
                     id(p.delta) if p.delta is not None else 0)
                unions.setdefault(k, set()).update(p.nodes)
                planes[k] = p.leaves[0]
            return sum(
                len(rows) * planes[k].shape[0] * planes[k].shape[-1] * 4
                for k, rows in unions.items())
        if kind == "tree":
            # one gather of the slot UNION + each unique extra once
            plane = group[0].leaves[0]
            rows = {s for p in group for s in p.nodes[0]}
            extras = {id(a): a for p in group for a in p.nodes[2]}
            d = group[0].delta
            return (len(rows) * plane.shape[0] * plane.shape[-1] * 4
                    + sum(getattr(a, "nbytes", 0)
                          for a in extras.values())
                    + (d.nbytes if d is not None else 0))
        if kind == "rowcounts-delta":
            # one base scan + the overlay gather per unique (plane,
            # overlay, filter) key — items in this group are identical
            p0 = group[0]
            d = p0.delta
            return (sum(getattr(a, "nbytes", 0) for a in p0.leaves)
                    + (d.nbytes if d is not None else 0))
        if kind == "count":
            return sum(getattr(a, "nbytes", 0)
                       for p in group for a in p.leaves)
        if kind == "bsirange":
            # one plane pass per unique predicate signature + the
            # overlay gather once
            plane = group[0].leaves[0]
            d = group[0].delta
            return (len({p.nodes[2] for p in group}) * plane.nbytes
                    + (d.nbytes if d is not None else 0))
        if kind == "groupby":
            return CountBatcher._groupby_bytes(group[0].nodes[0])
        seen: set = set()
        total = 0
        for p in group:
            k = tuple(id(a) for a in p.leaves)
            if k in seen:
                continue
            seen.add(k)
            total += sum(getattr(a, "nbytes", 0) for a in p.leaves)
        d = group[0].delta
        if kind in ("sum", "minmax") and d is not None:
            total += d.nbytes
        return total

    def _run_fallback(self, key, group):
        if key[0] == "count":
            self._fallback_counts(group)
        elif key[0] in ("rowcounts", "rowcounts-delta"):
            self._fallback_rowcounts(group)
        elif key[0] in ("selcounts", "selcounts-loop"):
            # the loop super-group degrades to the same per-item path
            self._fallback_selcounts(group)
        elif key[0] == "tree":
            self._fallback_tree(group)
        elif key[0] == "bsirange":
            self._fallback_bsirange(group)
        elif key[0] == "groupby":
            self._fallback_groupby(group)
        else:
            self._fallback_aggs(key[0], group)

    def _readback(self, w: _Window) -> None:
        """One device->host transfer for the whole collection window:
        pack every group's int32 output into a single flat array, read
        it once, slice per group.  A single-group window reads its
        output directly (the pack would only add a dispatch); any pack
        or finish failure degrades to per-group reads, then to the
        per-item fallbacks."""
        pending = w.pending
        if not pending:
            return
        if len(pending) == 1:
            key, group, out, finish = pending[0]
            try:
                finish(np.asarray(out))
            except Exception:  # noqa: BLE001 — per-item fallback
                w.faulted = True
                self.governor.record_fault()
                w.t0 = time.monotonic()  # fresh budget for the fallback
                self._run_fallback(key, group)
            else:
                # only after a delivered finish (which copied): a
                # retire failure must never re-run a group whose
                # results callers are already reading
                self._pp.retire(out)
            return
        # canonical pack order: groups arrive in batch order, so the
        # same kinds in a different order would otherwise compile a
        # fresh concatenate program per PERMUTATION of shapes —
        # churning the shared program LRU for zero benefit
        pending.sort(key=lambda item: (item[0][0], str(item[2].shape)))
        packed_dev = None
        try:
            total = sum(int(np.prod(out.shape, dtype=np.int64))
                        for _, _, out, _ in pending)
            packed_dev = self.fused.run_readback_pack(
                tuple(out for _, _, out, _ in pending),
                scratch=self._pp.scratch((total,), "int32"))
            packed = np.asarray(packed_dev)
            self.stats.count("batcher_readback_packed", 1)
            self.stats.count("batcher_readback_groups", len(pending))
        except Exception:  # noqa: BLE001 — per-group reads
            packed = packed_dev = None
        off = 0
        for key, group, out, finish in pending:
            try:
                if packed is None:
                    host = np.asarray(out)
                else:
                    size = int(np.prod(out.shape, dtype=np.int64))
                    host = packed[off:off + size].reshape(out.shape)
                    off += size
                finish(host)
            except Exception:  # noqa: BLE001 — per-item fallback
                w.faulted = True
                self.governor.record_fault()
                w.t0 = time.monotonic()  # fresh budget for the fallback
                self._run_fallback(key, group)
        # every finish copied out of `packed` (astype/int/fancy-index),
        # so the packed device buffer can re-enter the donated chain
        self._pp.retire(packed_dev)

    def _dispatch_counts(self, group: list[_Pending]):
        from pilosa_tpu.exec.fused import pow2_bucket, shift_leaves
        all_nodes, all_leaves, spans = [], [], []
        for p in group:
            start = len(all_nodes)
            for node in p.nodes:
                all_nodes.append(shift_leaves(node, len(all_leaves)))
            all_leaves.extend(p.leaves)
            spans.append((start, len(all_nodes)))
        # pad the NODE count to a pow2 bucket by repeating node 0
        # (already leaf-shifted; see fused.pow2_bucket)
        n = len(all_nodes)
        all_nodes.extend([all_nodes[0]] * (pow2_bucket(n) - n))
        per_shard = self.fused.run_count_batch(
            tuple(all_nodes), tuple(all_leaves),
            scratch=self._pp.scratch(
                (len(all_nodes), group[0].leaves[0].shape[0]),
                "int32"))

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p, (a, b) in zip(group, spans):
                if self._skip(p):
                    continue
                self._deliver(p, [int(row.sum()) for row in host[a:b]])
        return per_shard, finish

    def _fallback_counts(self, group: list[_Pending]) -> None:
        for p in group:
            if self._skip(p):
                continue
            try:
                self._deliver(p, [
                    int(kernels.shard_totals(
                        self.fused.run(node, p.leaves, "count")))
                    for node in p.nodes])
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    def _dispatch_selcounts(self, group: list[_Pending]):
        """The window's selected-row Counts over one plane: gather the
        UNION of every item's requested slots once (N concurrent
        requests over overlapping rows pay one pass over the union,
        the multi-query analogue of the rowcounts dedup), popcount,
        reduce shards on device.  The group key carries the delta
        identity, so every item here shares one (plane, overlay) pair
        and the merge happens once for the union.  The union gathers
        in SORTED slot order (ascending memory stride, r17) with a
        donated ping-pong output slot."""
        from pilosa_tpu.exec.fused import pow2_bucket
        plane = group[0].leaves[0]
        order = sorted({s for p in group for s in p.nodes})
        pos = {s: i for i, s in enumerate(order)}
        out = self.fused.run_selected_counts(
            plane, tuple(order), delta=group[0].delta,
            scratch=self._pp.scratch((pow2_bucket(len(order)),),
                                     "int32"),
            sorted_idx=True)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p in group:
                if self._skip(p):
                    continue
                self._deliver(p, host[[pos[s] for s in p.nodes]])
        return out, finish

    def _dispatch_selcounts_loop(self, group: list[_Pending]):
        """A merged same-shape selcounts super-group (r24 loop fusion):
        re-split by (plane, overlay) identity into the original
        per-pair slot unions, then ONE loop program serves every pair
        — K same-shape scans, one enqueue, one packed readback.  The
        iteration count lands in the ``dispatch_loop_iters``
        histogram."""
        pairs: dict[tuple, list[_Pending]] = {}
        for p in group:
            pairs.setdefault(
                (id(p.leaves[0]),
                 id(p.delta) if p.delta is not None else 0),
                []).append(p)
        subs = list(pairs.values())
        orders = [sorted({s for p in sub for s in p.nodes})
                  for sub in subs]
        out = self.fused.run_selected_counts_loop(
            tuple(sub[0].leaves[0] for sub in subs),
            tuple(tuple(o) for o in orders),
            tuple(sub[0].delta for sub in subs),
            sorted_idx=True)
        self.stats.observe("dispatch_loop_iters", float(len(subs)))
        poss = [{s: i for i, s in enumerate(o)} for o in orders]

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for j, sub in enumerate(subs):
                pos = poss[j]
                for p in sub:
                    if self._skip(p):
                        continue
                    self._deliver(p, host[j][[pos[s] for s in p.nodes]])
        return out, finish

    def _dispatch_tree(self, group: list[_Pending]):
        """The window's compound-tree Counts over one (plane, overlay)
        pair: union every item's gathered slots and extra operands
        (``exec.tree.assemble_items``), remap the postfix programs
        into the shared operand space and run ONE fused program — one
        memory pass over the union, K answers, packed readback."""
        from pilosa_tpu.exec.tree import assemble_items
        plane = group[0].leaves[0]
        slots, progs, extras = assemble_items([p.nodes for p in group])
        out = self.fused.run_tree_counts(plane, slots, progs, extras,
                                         delta=group[0].delta)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for k, p in enumerate(group):
                if self._skip(p):
                    continue
                self._deliver(p, int(host[k]))
        return out, finish

    def _fallback_tree(self, group: list[_Pending]) -> None:
        for p in group:
            if self._skip(p):
                continue
            try:
                slots, prog, extras = p.nodes
                out = self.fused.run_tree_counts(
                    p.leaves[0], slots, (prog,), extras, delta=p.delta)
                self._deliver(p, int(np.asarray(out).astype(np.int64)[0]))
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    def _dispatch_rowcounts_delta(self, group: list[_Pending]):
        """Whole-plane row counts of base⊕delta: the group key is the
        (plane, overlay, filter) identity triple, so the whole group
        is ONE scan + one overlay adjustment shared by every item."""
        p0 = group[0]
        flt = p0.leaves[1] if len(p0.leaves) == 2 else None
        out = self.fused.run_rowcounts_delta(p0.leaves[0], p0.delta,
                                             filter_words=flt)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p in group:
                if self._skip(p):
                    continue
                self._deliver(p, host)
        return out, finish

    def _fallback_selcounts(self, group: list[_Pending]) -> None:
        import jax.numpy as jnp
        for p in group:
            if self._skip(p):
                continue
            try:
                idx = jnp.asarray(p.nodes, dtype=jnp.int32)
                if p.delta is not None:
                    from pilosa_tpu.ingest.delta import \
                        adjusted_selected_counts
                    d = p.delta
                    self._deliver(p, np.asarray(adjusted_selected_counts(
                        p.leaves[0], idx, d.rows, d.words,
                        d.vals)).astype(np.int64))
                else:
                    self._deliver(p, kernels.shard_totals(
                        kernels.selected_row_counts(p.leaves[0], idx)))
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    @staticmethod
    def _dedupe(group: list[_Pending]):
        """Unique items by leaf identity + the caller index of each
        item's unique representative — N requests over the same
        resident plane compute once and share the read."""
        uniq: dict[tuple, int] = {}
        items: list[_Pending] = []
        assign: list[int] = []
        for p in group:
            k = tuple(id(a) for a in p.leaves)
            slot = uniq.get(k)
            if slot is None:
                slot = uniq[k] = len(items)
                items.append(p)
            assign.append(slot)
        return items, assign

    def _dispatch_rowcounts(self, group: list[_Pending]):
        from pilosa_tpu.exec.fused import pow2_bucket
        items, assign = self._dedupe(group)
        # canonical flag order + pow2 pad (repeating item 0): bounded
        # program set per plane shape, like the aggregate batches
        order = sorted(range(len(items)), key=lambda i: len(items[i].leaves))
        items = [items[i] for i in order]
        back = {old: new for new, old in enumerate(order)}
        assign = [back[a] for a in assign]
        padded = items + [items[0]] * (pow2_bucket(len(items))
                                       - len(items))
        flags = tuple(len(p.leaves) == 2 for p in padded)
        leaves = tuple(a for p in padded for a in p.leaves)
        out = self.fused.run_rowcounts_batch(
            flags, leaves,
            scratch=self._pp.scratch(
                (len(flags), leaves[0].shape[-2]), "int32"))

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p, slot in zip(group, assign):
                if self._skip(p):
                    continue
                self._deliver(p, host[slot])
        return out, finish

    def _fallback_rowcounts(self, group: list[_Pending]) -> None:
        for p in group:
            if self._skip(p):
                continue
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                if p.delta is not None:
                    from pilosa_tpu.ingest.delta import \
                        adjusted_row_counts
                    d = p.delta
                    self._deliver(p, np.asarray(adjusted_row_counts(
                        p.leaves[0], d.rows, d.words, d.vals, flt,
                        reduce_shards=False)).astype(np.int64).sum(
                            axis=0))
                else:
                    self._deliver(p, kernels.shard_totals(
                        kernels.row_counts(p.leaves[0], flt)))
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    def _run_distinct(self, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        t0 = time.perf_counter()
        items, assign = self._dedupe(group)
        results: list = [None] * len(items)
        errors: list = [None] * len(items)

        def scan(i: int) -> None:
            p = items[i]
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                pos, neg = bsik.distinct_presence(p.leaves[0], flt)
                results[i] = (np.asarray(pos), np.asarray(neg))
            except Exception as e:  # noqa: BLE001
                errors[i] = e

        if len(items) == 1:
            scan(0)
        else:
            # NON-identical items (different planes/filters) keep the
            # pre-batcher concurrency: the scans are multi-dispatch
            # block loops, so running them serially in this worker
            # would make the last caller wait out every other scan.
            # Plain threads, NOT _group_pool: this method itself runs
            # inside that pool, and a nested map could deadlock with
            # every pool worker occupied by group runs; thread spawn
            # is noise next to a presence scan.
            ts = [threading.Thread(target=scan, args=(i,))
                  for i in range(len(items))]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        for p, slot in zip(group, assign):
            if errors[slot] is not None:
                self._deliver_error(p, errors[slot])
            else:
                self._deliver(p, results[slot])
        # distinct can't join the packed readback (multi-dispatch host
        # loop), so its dispatch observation covers the whole scan —
        # read included — and its bytes land on the same counter
        self.stats.observe("kernel_dispatch_seconds",
                           time.perf_counter() - t0, kind="distinct")
        nbytes = self._group_bytes("distinct", group)
        if nbytes:
            self.stats.count("kernel_bytes_scanned_total", nbytes,
                             kind="distinct")

    @staticmethod
    def _dedupe_pad(items: list[_Pending], assign: list[int],
                    key_rank) -> tuple[list[_Pending], list[int]]:
        """Canonical-order + pow2-pad a deduped item list (shared by
        the per-plane aggregate dispatches): sort unique items by
        ``key_rank`` so the static program shape is order-independent,
        remap the caller assignment, pad by repeating item 0."""
        from pilosa_tpu.exec.fused import pow2_bucket
        order = sorted(range(len(items)), key=lambda i: key_rank(items[i]))
        items = [items[i] for i in order]
        back = {old: new for new, old in enumerate(order)}
        assign = [back[a] for a in assign]
        padded = items + [items[0]] * (pow2_bucket(len(items))
                                       - len(items))
        return padded, assign

    def _dispatch_aggs(self, kind: str, group: list[_Pending]):
        """The window's BSI Sum/Min/Max items over ONE (plane,
        overlay) pair (the group key carries both identities, r20):
        identical items (same filter) dedupe to one scan, distinct
        filters fuse into one program referencing the plane ONCE, and
        a pending overlay merges in-program (base side excludes the
        touched word columns; the mini side answers them) — aggregates
        stay rebuild- and fold-free under sustained BSI ingest."""
        from pilosa_tpu.engine import bsi as bsik
        plane = group[0].leaves[0]
        delta = group[0].delta
        uniq: dict[int, int] = {}
        items: list[_Pending] = []
        assign: list[int] = []
        for p in group:
            k = id(p.leaves[1]) if len(p.leaves) == 2 else 0
            slot = uniq.get(k)
            if slot is None:
                slot = uniq[k] = len(items)
                items.append(p)
            assign.append(slot)
        padded, assign = self._dedupe_pad(items, assign,
                                          lambda p: len(p.leaves))
        flags = tuple(len(p.leaves) == 2 for p in padded)
        filters = tuple(p.leaves[1] for p in padded
                        if len(p.leaves) == 2)
        if kind == "sum":
            out = self.fused.run_sum_plane_batch(plane, flags, filters,
                                                 delta=delta)
            decode = bsik.decode_sum_packed
        else:
            out = self.fused.run_minmax_plane_batch(plane, flags,
                                                    filters,
                                                    delta=delta)
            decode = bsik.decode_minmax_packed

        def finish(host: np.ndarray) -> None:
            for p, slot in zip(group, assign):
                if self._skip(p):
                    continue
                self._deliver(p, decode(host[slot]))
        return out, finish

    def _dispatch_bsirange(self, group: list[_Pending]):
        """The window's BSI Range-counts over one (plane, overlay)
        pair: dedupe by predicate signature, one fused program with
        the plane as a single operand, int32[K] totals into the
        window's packed readback."""
        plane = group[0].leaves[0]
        delta = group[0].delta
        uniq: dict[tuple, int] = {}
        items: list[_Pending] = []
        assign: list[int] = []
        for p in group:
            sig = p.nodes[2]
            slot = uniq.get(sig)
            if slot is None:
                slot = uniq[sig] = len(items)
                items.append(p)
            assign.append(slot)
        padded, assign = self._dedupe_pad(items, assign,
                                          lambda p: p.nodes[2])
        specs = tuple(p.nodes[0] for p in padded)
        operands = tuple(a for p in padded for a in p.nodes[1])
        out = self.fused.run_range_batch(plane, specs, operands,
                                         delta=delta)

        def finish(host: np.ndarray) -> None:
            host = host.astype(np.int64)
            for p, slot in zip(group, assign):
                if self._skip(p):
                    continue
                self._deliver(p, int(host[slot]))
        return out, finish

    def _dispatch_groupby(self, group: list[_Pending]):
        """One GroupBy block per group (the key's sig dedupes
        identical concurrent blocks to a single program); the flat
        int32 output joins the window's packed readback and every
        item unflattens the same host arrays."""
        from pilosa_tpu.exec import groupby as gb
        p0 = group[0]
        args, (agg_kind, meta), _sig = p0.nodes
        planes, ci, lp, fw, ap, dl = args
        out = self.fused.run_groupby_batch(planes, ci, lp, fw, ap,
                                           agg_kind, delta=dl)

        def finish(host: np.ndarray) -> None:
            d = gb.unflatten_block(host, *meta, agg_kind)
            for p in group:
                if self._skip(p):
                    continue
                self._deliver(p, d)
        return out, finish

    def _fallback_groupby(self, group: list[_Pending]) -> None:
        from pilosa_tpu.exec import groupby as gb
        for p in group:
            if self._skip(p):
                continue
            try:
                args, (agg_kind, _meta), _sig = p.nodes
                planes, ci, lp, fw, ap, dl = args
                ad = ((dl.col_shard, dl.col_word, dl.col_vals,
                       dl.col_mask) if dl is not None else None)
                out = gb._groupby_program(planes, ci, lp, fw, ap,
                                          agg_kind, agg_delta=ad)
                self._deliver(p, {k: np.asarray(v)
                                  for k, v in out.items()})
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    def _fallback_bsirange(self, group: list[_Pending]) -> None:
        """Per-item eager range count (base/mini split applied with
        eager jnp ops — no fused program involved)."""
        import jax.numpy as jnp

        from pilosa_tpu.engine import bsi as bsik
        for p in group:
            if self._skip(p):
                continue
            try:
                (op_keys, has_filter), operands, _sig = p.nodes
                preds = [(operands[2 * i], operands[2 * i + 1], k)
                         for i, k in enumerate(op_keys)]
                flt = operands[-1] if has_filter else None
                from pilosa_tpu.ingest.delta import bsi_sides
                sides = bsi_sides(p.leaves[0], flt, p.delta)
                total = 0
                for pl, fw in sides:
                    words = None
                    for masks, neg, okey in preds:
                        cmp = bsik.range_cmp(pl, masks, neg, fw)[okey]
                        words = cmp if words is None \
                            else jnp.bitwise_and(words, cmp)
                    total += int(kernels.shard_totals(
                        kernels.count(words)))
                self._deliver(p, total)
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)

    def _fallback_aggs(self, kind: str, group: list[_Pending]) -> None:
        from pilosa_tpu.engine import bsi as bsik
        for p in group:
            if self._skip(p):
                continue
            try:
                flt = p.leaves[1] if len(p.leaves) == 2 else None
                from pilosa_tpu.ingest.delta import bsi_sides
                sides = bsi_sides(p.leaves[0], flt, p.delta)
                if kind == "sum":
                    total = cnt = 0
                    for pl, fw in sides:
                        t, c = bsik.sum_count(pl, fw)
                        total += t
                        cnt += c
                    self._deliver(p, (total, cnt))
                else:
                    tuples = []
                    for pl, fw in sides:
                        tuples.extend(bsik.min_max(pl, fw))
                    self._deliver(p, tuples)
            except Exception as e2:  # noqa: BLE001
                self._deliver_error(p, e2)


def _wrap_readback_error(exc: Exception) -> Exception:
    """A failure escaping ``_finish_window`` OUTSIDE the per-item
    fallbacks: wrap as a structured stall error (stage=readback) so
    the window's unfinished items fail loudly instead of wedging."""
    err = _stall_error(f"window readback failed: {exc!r}",
                       stage="readback")
    err.__cause__ = exc
    return err
