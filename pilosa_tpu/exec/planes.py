"""Device-resident plane cache: host fragments → packed uint32 arrays in HBM.

The device is a cache over host truth (SURVEY.md §8): a (field, view) is
materialized as ``uint32[n_shards, R_pad, W]`` (set fields) or
``uint32[n_shards, depth+2, W]`` (BSI), placed via an optional
``jax.sharding.Sharding`` so the shard axis lands across the mesh — the
TPU analogue of the reference's shard→node placement
(``cluster.go#shardNodes``).

Invalidation: entries remember the source fragments' generation counters
and rebuild when any changed (fragment mutations bump them).  Row-count
padding to the next power of two bounds XLA recompiles (one compile per
row bucket, SURVEY.md §8 "static shapes vs dynamic row sets").

Eviction: byte-budgeted LRU — the working-set management half of the
"host→HBM streaming" hard part; fields that exceed the budget are
rebuilt per query rather than cached.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import jax
import numpy as np

from pilosa_tpu.engine.bsi import OFFSET_ROW
from pilosa_tpu.engine.words import WORDS_PER_SHARD
from pilosa_tpu.store.field import Field

PAD_SHARD = -1  # shard-list padding entry (meshed execution): all-zero words

DEFAULT_BUDGET = 4 << 30


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


@jax.jit
def _scatter_cells(plane, cell_rows, cell_words, cell_vals, reset_rows,
                   reset_vals):
    flat = plane.reshape(-1, plane.shape[-1])
    flat = flat.at[reset_rows].set(reset_vals, mode="drop")
    flat = flat.at[cell_rows, cell_words].set(cell_vals, mode="drop")
    return flat.reshape(plane.shape)


def _apply_plane_cells(plane, cell_rows, cell_words, cell_vals,
                       reset_rows, reset_vals):
    """Scatter changed cells / whole rows into a resident device plane.
    Index arrays pow2-pad with out-of-range values (``mode="drop"``) so
    the compiled program set stays bounded per plane shape."""
    total = plane.shape[0] * plane.shape[1]
    w = plane.shape[-1]
    n1, n2 = _pow2(len(cell_rows)), _pow2(len(reset_rows))
    cr = np.full(n1, total, np.int32)
    cw = np.zeros(n1, np.int32)
    cv = np.zeros(n1, np.uint32)
    cr[:len(cell_rows)] = cell_rows
    cw[:len(cell_words)] = cell_words
    cv[:len(cell_vals)] = cell_vals
    rr = np.full(n2, total, np.int32)
    rv = np.zeros((n2, w), np.uint32)
    rr[:len(reset_rows)] = reset_rows
    rv[:len(reset_vals)] = reset_vals
    return _scatter_cells(plane, cr, cw, cv, rr, rv)


def merge_row_cards(frags) -> tuple[np.ndarray, np.ndarray]:
    """Merge per-fragment (row_ids, cardinalities) across shards:
    (uint64[R] sorted ids, int64[R] summed cards).  Shared by the sparse
    build and the executor's unfiltered-TopN host path."""
    id_parts, card_parts = [], []
    for frag in frags:
        ids, cards = frag.row_cardinalities()
        if len(ids):
            id_parts.append(ids)
            card_parts.append(cards)
    if not id_parts:
        return np.empty(0, np.uint64), np.empty(0, np.int64)
    all_ids = np.unique(np.concatenate(id_parts))
    totals = np.zeros(len(all_ids), np.int64)
    for ids, cards in zip(id_parts, card_parts):
        totals[np.searchsorted(all_ids, ids)] += cards
    return all_ids, totals


@dataclass
class PlaneSet:
    """One materialized (field, view): device plane + row-slot mapping.

    ``delta`` (r15 ingest): a bounded device-side write overlay
    (:class:`pilosa_tpu.ingest.delta.DeltaOverlay`) carrying cells
    written since the base plane was built.  ``plane`` itself is the
    IMMUTABLE base; delta-aware kernels answer base⊕delta at dispatch
    time, and consumers that need a clean plane go through
    :meth:`PlaneCache.field_plane`, which folds first."""

    plane: jax.Array          # uint32[n_shards, R_pad, W]
    shards: tuple[int, ...]   # axis-0 ids, PAD_SHARD entries are zeros
    row_ids: np.ndarray       # uint64[R] real rows (slots beyond are pad)
    slot_of: dict[int, int]
    delta: object | None = None  # ingest.delta.DeltaOverlay when dirty

    @property
    def n_rows(self) -> int:
        return len(self.row_ids)

    def slots_for(self, row_ids) -> list:
        """Plane row slots for ``row_ids`` (None per absent row — a
        row with no set bit anywhere has no slot and callers lower it
        as an all-zero operand).  Resolution happens fresh per query:
        a row that gains its first bit after the plane was built
        reaches the plane through the normal staleness machinery
        (delta absorb / rebuild) before this map is consulted."""
        return [self.slot_of.get(int(r)) if r is not None else None
                for r in row_ids]


@dataclass
class SparseSet:
    """Container-blocked sparse residency (``engine.sparse``): one
    (field, view) as CSR bit arrays — memory scales with set bits, not
    rows × shard width (SURVEY.md §8 "dense blowup").

    Two layouts: unmeshed (``mesh is None``) arrays are flat
    (``word_idx int32[N_pad]`` of global flat filter indices); meshed
    arrays are DEVICE-BLOCKED (``int32[D, Nd_pad]`` with word indices
    local to each device's filter block, axis 0 sharded over the mesh)
    so each chip gathers only from its resident filter words and counts
    merge with one ``psum`` (``engine.sparse`` mesh form)."""

    word_idx: jax.Array       # int32[N_pad] | int32[D, Nd_pad]
    mask: jax.Array           # uint32 same shape (0 = padding)
    row_ptr: jax.Array        # int32[R_pad+1] | int32[D, R_pad+1]
    row_ids: np.ndarray       # uint64[R] sorted global rows
    row_cards: np.ndarray     # int64[R] full per-row cardinalities
    shards: tuple[int, ...]
    nbytes: int
    n_rows_pad: int           # pow2 row bucket (static program shape)
    mesh: object = None       # jax.sharding.Mesh when device-blocked
    axis: str | None = None   # mesh axis name

    @property
    def n_rows(self) -> int:
        return len(self.row_ids)


class PlaneCache:
    def __init__(self, place=None, budget_bytes: int = DEFAULT_BUDGET,
                 placement=None, stats=None, sidecars: bool = True,
                 delta_cells: int = 65536,
                 delta_compact_fraction: float = 0.5,
                 governor=None, flight=None):
        """``place(np_array) -> jax.Array`` controls device placement /
        mesh sharding; default is plain ``jax.device_put``.
        ``placement`` (the MeshPlacement the executor runs under, if
        any) additionally drives the sparse build's device blocking.
        ``stats`` (an obs registry) receives the plane-build metrics;
        ``sidecars`` toggles the warm dense-plane cache (``<fragment>
        .dense`` images written on cold builds, loaded at near
        raw-copy speed after a restart).

        ``delta_cells`` (r15 ingest) bounds the per-plane device delta
        overlay: writes to a resident whole-view plane absorb into a
        (cell → word value) overlay the query kernels merge at
        dispatch time instead of rebuilding or re-scattering the base;
        0 disables (pre-r15 incremental-scatter behavior).
        ``delta_compact_fraction``: overlay fill ratio past which the
        background compactor folds the overlay into the base plane and
        swaps generations atomically.

        ``governor`` (r17 tenancy): an optional
        :class:`pilosa_tpu.tenancy.ResidencyGovernor` — when present,
        serving hits feed its telemetry and every eviction pass orders
        by its keep-score (recent hits × bytes × rebuild cost) before
        the LRU stamp; without it (or before any telemetry) ordering
        is the stamped LRU exactly."""
        from pilosa_tpu.exec._lru import Stamps
        from pilosa_tpu.obs import NULL_FLIGHT, NopStats
        self.place = place or (placement.place if placement is not None
                               else jax.device_put)
        self.placement = placement
        self.budget = budget_bytes
        self._stats = stats or NopStats()
        self.sidecars = sidecars
        self.governor = governor
        # flight recorder (r19): evictions land on the incident
        # timeline with their reason — "why did that plane vanish at
        # 03:14" is answerable from the dump
        self.flight = flight or NULL_FLIGHT
        # compile-ladder warmer (r24): set by the executor after
        # construction; _insert_entry notes standard-plane residency
        self.warmer = None
        # bound once: the ledger's plane-attribution stamp runs on the
        # lock-free serving fast path
        from pilosa_tpu.obs.ledger import set_plane_context
        self._set_plane_ctx = set_plane_context
        # eviction accounting (r17 tenancy): every entry drop through
        # _evict_entry tallies here and on plane_evictions_total{reason}
        self.evictions = 0
        self._evictions_by_reason: dict[str, int] = {}
        # plane-build accounting (also on /status via stats()):
        # warm = fragment expansions served from a dense sidecar
        self.warm_hits = 0
        self.warm_misses = 0
        self.builds = 0
        self.build_failures = 0
        self.build_seconds_total = 0.0
        self.build_bytes_total = 0
        # serving-path residency accounting (r14 device telemetry):
        # hit = a query answered from a resident entry (fast-path,
        # locked revalidation, or in-place incremental refresh), miss
        # = a full build or a streamed answer while a background build
        # runs.  Plain int increments — racing serving threads may
        # lose the odd count, which a RATIO gauge never notices; a
        # lock here would sit on the lock-free fast path.
        self.hits = 0
        self.misses = 0
        self._failed_logged: set = set()
        # plain dict (NOT OrderedDict): the serving hot path revalidates
        # entries lock-free (GIL-atomic dict reads + a recency-stamp
        # write), so the one cache RLock stops serializing every
        # concurrent plane fetch; recency for eviction lives in _stamps
        # (shared race-handling with FusedCache — exec/_lru.py)
        self._entries: dict[tuple, tuple[tuple, object, int]] = {}
        self._stamps = Stamps()
        self._bytes_cache: dict[tuple, tuple[tuple, int]] = {}
        self._zeros: dict[int, jax.Array] = {}
        self._bytes = 0
        self._lock = threading.RLock()
        self.incremental_applied = 0  # delta-scatter refreshes (stats)
        # device delta overlays (r15 ingest): host mirrors of each
        # resident plane's pending write cells, keyed like _entries;
        # the stored tuple is (base plane array, DeltaMirror) — a
        # rebuilt base invalidates its mirror by identity.  Meshed
        # placements participate too (r21): the overlay's flat-index
        # math is LOGICAL-array math, so with the overlay arrays
        # replicated across the mesh (``MeshPlacement.replicate``)
        # base⊕delta stays one GSPMD program over the sharded base —
        # sustained ingest keeps the zero-rebuild guarantee on 1 chip
        # or 8.
        self.delta_cells = int(delta_cells)
        self.delta_compact_fraction = float(delta_compact_fraction)
        self._delta_mirrors: dict[tuple, tuple] = {}
        self._compacting: dict[tuple, threading.Thread] = {}
        self.delta_absorbs = 0
        self.delta_compactions = 0
        self.last_compaction_seconds = 0.0
        # keys leased to in-flight queries, per serving thread: eviction
        # must skip these — the query's frames hold live device refs, so
        # evicting frees no HBM and only forces a rebuild on next use
        # (the r4 OOM-retry thrash class)
        self._leases: dict[int, set] = {}
        # serve-while-build (r5): big dense planes build on background
        # threads in chunked, donated device updates; queries stream
        # until the flip.  key -> Thread (single-flight per key)
        self._building: dict[tuple, threading.Thread] = {}

    # -- in-flight leases ----------------------------------------------------

    def begin_query(self) -> None:
        """Open a lease set for this thread; every plane `_get` hands
        out until `end_query` stays pinned against eviction."""
        with self._lock:
            self._leases[threading.get_ident()] = set()

    def end_query(self) -> None:
        with self._lock:
            self._leases.pop(threading.get_ident(), None)

    def _pinned(self) -> set:
        # caller holds self._lock
        if not self._leases:
            return set()
        return set().union(*self._leases.values())

    def _eviction_order(self, pinned: set, keys=None) -> list:
        """Unpinned cache keys in EXPLICIT eviction order (evict the
        head first).  Primary key: the governor's keep-score ascending
        (cheap-to-rebuild, cold, small entries go first); tie-break —
        and the whole order when no governor is attached or an entry
        has no telemetry yet — is the recency stamp, i.e. the original
        approximate LRU.  Caller holds ``self._lock``."""
        g = self.governor
        ks = [k for k in (self._entries if keys is None else keys)
              if k not in pinned and k in self._entries]
        if g is None:
            return sorted(ks, key=lambda k: self._stamps.get(k))
        return sorted(ks, key=lambda k: (g.keep_score(
            k, self._entries[k][2]), self._stamps.get(k)))

    def _evict_entry(self, key, reason: str) -> int:
        """Drop one entry (caller holds ``self._lock`` and has checked
        pins); returns the bytes freed.  The single exit point every
        eviction path shares, so ``plane_evictions_total{reason}`` and
        the governor's recency reset can't be missed by a new path."""
        _, _, nbytes = self._entries.pop(key)
        self._stamps.pop(key)
        self._delta_mirrors.pop(key, None)
        self._bytes -= nbytes
        self.evictions += 1
        self._evictions_by_reason[reason] = \
            self._evictions_by_reason.get(reason, 0) + 1
        self._stats.count("plane_evictions_total", 1, reason=reason)
        self.flight.record("evict", f"{key[1]}/{key[2]}", reason,
                           float(nbytes))
        if self.governor is not None:
            self.governor.note_evict(key)
        return nbytes

    def evict_unpinned(self, target_bytes: int | None = None,
                       reason: str = "oom") -> int:
        """Free entries NOT leased by an in-flight query — the memory
        that eviction can actually reclaim — in explicit eviction
        order, stopping once ``target_bytes`` are freed (None = free
        everything unpinned, the OOM-recovery contract).  OOM recovery
        uses this instead of `invalidate`: dropping leased entries
        under concurrent load evicts planes whose HBM cannot be freed
        and makes every other in-flight query rebuild from scratch.
        Returns the bytes freed."""
        with self._lock:
            self._bytes_cache.clear()
            pinned = self._pinned()
            freed = 0
            for key in self._eviction_order(pinned):
                if target_bytes is not None and freed >= target_bytes:
                    break
                freed += self._evict_entry(key, reason)
            return freed

    def evict_tenant(self, index: str, need_bytes: int,
                     reason: str = "quota") -> int:
        """Free up to ``need_bytes`` of ONE tenant's unpinned entries
        in eviction order — the page-in admission path makes room
        within a tenant's own byte quota without touching neighbors'
        residency.  Returns the bytes freed."""
        with self._lock:
            pinned = self._pinned()
            keys = [k for k in self._entries if k[1] == index]
            freed = 0
            for key in self._eviction_order(pinned, keys):
                if freed >= need_bytes:
                    break
                freed += self._evict_entry(key, reason)
            return freed

    def tenant_bytes(self, index: str) -> int:
        """Resident cache bytes attributed to one tenant (all key
        kinds carry the index at position 1)."""
        with self._lock:
            return sum(v[2] for k, v in self._entries.items()
                       if k[1] == index)

    # -- public -------------------------------------------------------------

    def field_plane(self, index: str, field: Field, view_name: str,
                    shards: tuple[int, ...]) -> PlaneSet:
        """Whole-view plane (TopN / Rows / GroupBy path)."""
        key = ("plane", index, field.name, view_name, shards)
        build = (self._build_plane_meshed if self.placement is not None
                 else self._build_plane)
        return self._get(key, field, view_name, shards, build)

    def bsi_plane(self, index: str, field: Field,
                  shards: tuple[int, ...]) -> PlaneSet:
        """BSI bit-plane: rows are the fixed exists/sign/bit layout.
        Always CLEAN (a pending overlay folds first) — consumers that
        can answer base⊕delta go through :meth:`bsi_plane_delta`."""
        view_name = field.bsi_view_name
        key = ("bsi", index, field.name, view_name, shards,
               field.options.bit_depth)
        return self._get(key, field, view_name, shards, self._build_bsi)

    def bsi_plane_delta(self, index: str, field: Field,
                        shards: tuple[int, ...]) -> PlaneSet:
        """BSI bit-plane for the delta-aware aggregate consumers
        (r20): a STALE resident plane absorbs its write gap into a
        bounded device overlay (``ingest.delta.BsiOverlay``) and the
        returned PlaneSet carries it as ``.delta`` — Sum/Min/Max/
        Range-count kernels answer base⊕delta at dispatch, so
        sustained ingest on an int field stops forcing folds or
        rebuilds on the aggregate path."""
        view_name = field.bsi_view_name
        key = ("bsi", index, field.name, view_name, shards,
               field.options.bit_depth)
        # lock-free fast path: fresh entry serves as-is, overlay and
        # all (the aggregate kernels merge it in-program)
        hit = self._entries.get(key)
        if hit is not None and hit[0] == self._gens_fast(field, view_name,
                                                         shards):
            self._touch(key)
            self._lease_fast(key)
            self.hits += 1
            return hit[1]
        if hit is not None and self.delta_cells > 0:
            gens = self._gens(field, view_name, shards)
            with self._lock:
                cur = self._entries.get(key)
                if cur is not None and cur[0] == gens:
                    self._touch(key)
                    self._lease(key)
                    self.hits += 1
                    return cur[1]
            if cur is not None:
                ps = self._delta_update(key, field, view_name, shards,
                                        cur)
                if ps is not None:
                    with self._lock:
                        self._lease(key)
                    self.hits += 1
                    return ps
        return self._get(key, field, view_name, shards, self._build_bsi)

    # Planes at or under this build inline (the latency of spawning a
    # builder + answering via the streaming path isn't worth it); above
    # it, field_plane_nowait hands the build to a background thread.
    SYNC_BUILD_MAX = 256 << 20

    # Bytes per background-build transfer chunk: bounds host staging
    # memory (2× with the r10 double buffer) AND splits the multi-GB
    # single device_put (the r3/r4 tunnel-wedge exposure) into
    # restartable pieces.
    BUILD_CHUNK_BYTES = 256 << 20

    def field_plane_nowait(self, index: str, field: Field, view_name: str,
                           shards: tuple[int, ...]) -> PlaneSet | None:
        """Resident whole-view plane if fresh, else None — with the
        build running on a background thread (single-flight per key).
        Callers answer through their streaming/per-row fallback until
        the flip; restart-to-first-answer stops costing the full plane
        residency wait (VERDICT r4 weak #6: ~4.4 min at 1B cols).
        Upstream serves straight from mmap with no warm-up
        (``fragment.Open``, SURVEY §4.1) — availability first."""
        key = ("plane", index, field.name, view_name, shards)
        # lock-free fast path (mirrors _get): fresh resident plane
        hit = self._entries.get(key)
        if hit is not None and hit[0] == self._gens_fast(field, view_name,
                                                         shards):
            self._touch(key)
            self._lease_fast(key)
            self.hits += 1
            return hit[1]
        gens = self._gens(field, view_name, shards)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0] == gens:
                self._touch(key)
                self._lease(key)
                self.hits += 1
                return hit[1]
            if key in self._building:
                self.misses += 1
                return None
        if hit is not None:
            # a STALE resident plane absorbs its write gap into the
            # device delta overlay (base⊕delta answered at dispatch,
            # zero base rewrites) or folds — never spawn a full
            # GB-scale rebuild (and degrade to streaming) for a few
            # written cells
            ps = self._delta_update(key, field, view_name, shards, hit)
            if ps is not None:
                with self._lock:
                    self._lease(key)
                self.hits += 1
                return ps
        est = self.plane_bytes(field, view_name, shards)
        if est > self.budget:
            # the entry-resident fast checks upstream skip the budget
            # walk, so growth past budget is caught here: never spawn
            # a build the cache would refuse to keep
            self.misses += 1
            return None
        if est <= self.SYNC_BUILD_MAX or self.placement is not None:
            # small plane, or meshed placement: inline — meshed builds
            # go through _build_plane_meshed (parallel expansion, one
            # sharded device_put, the pipeline's build metrics); the
            # chunked donated-update pipeline isn't wired for mesh
            # shardings
            return self.field_plane(index, field, view_name, shards)
        self.misses += 1
        with self._lock:
            if key in self._building:
                return None
            t = threading.Thread(
                target=self._background_build,
                args=(key, field, view_name, shards, gens),
                name="plane-build", daemon=True)
            self._building[key] = t
        t.start()
        return None

    def time_plane_nowait(self, index: str, field: Field,
                          shards: tuple[int, ...]):
        """One time field's bucketed device plane
        (:class:`pilosa_tpu.timeviews.TimePlaneSet`) if resident and
        serving-fresh, else None after kicking a build — the r23 time
        family's residency entry point, mirroring
        :meth:`field_plane_nowait`'s lock discipline.

        Validity is the SUFFIX-TAGGED per-bucket-view generation
        tuple (``timeviews.time_gens``): a bumped fragment absorbs
        into the (row, bucket)-keyed delta overlay (zero rebuilds
        under sustained event ingest); a new bucket, new row, or
        whole-row clear rebuilds.  Callers fall back to the
        op-at-a-time ``_time_row_span`` oracle on None."""
        from pilosa_tpu import timeviews
        key = ("tplane", index, field.name, shards)
        # lock-free fast path: fresh entry serves as-is, overlay and
        # all (run_time_range merges it in-program)
        hit = self._entries.get(key)
        if hit is not None and hit[0] == timeviews.time_gens(
                field, shards, fast=True):
            self._touch(key)
            self._lease_fast(key)
            self.hits += 1
            return hit[1]
        gens = timeviews.time_gens(field, shards)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0] == gens:
                self._touch(key)
                self._lease(key)
                self.hits += 1
                return hit[1]
        if hit is not None:
            tps = self._time_absorb(key, field, shards, hit)
            if tps is not None:
                with self._lock:
                    self._lease(key)
                self.hits += 1
                return tps
        plan = timeviews.plan_time_plane(field, shards)
        self.misses += 1
        if plan is None:
            return None  # no time views yet: nothing to serve from
        nbytes = plan[-1]
        if nbytes > self.budget:
            return None  # caller stays on the oracle path
        import time as _time
        t0 = _time.perf_counter()
        tps = timeviews.build_time_plane(field, shards, self.place,
                                         plan=plan)
        dt = _time.perf_counter() - t0
        self.builds += 1
        self.build_seconds_total += dt
        self.build_bytes_total += nbytes
        self._stats.observe("plane_build_seconds", dt)
        self._stats.count("plane_build_bytes_total", nbytes)
        self._stats.gauge("time_view_buckets", float(len(plan[0])))
        self._insert_entry(key, gens, tps, nbytes, lease=True)
        return tps

    def _time_absorb(self, key, field: Field, shards: tuple[int, ...],
                     hit, attempts: int = 3):
        """Absorb the write gap of a stale "tplane" entry into its
        bounded device overlay (cells keyed by flat (row, bucket)
        slot) and advance the suffix-tagged generations — the step
        that keeps sustained time-bucketed ingest ZERO-rebuild.  None
        = can't absorb (disabled, new bucket/row, whole-row clear,
        overlay full, journal gap): the caller rebuilds — time planes
        have no fold path (the bucketed row axis doesn't match any
        single view's scatter), and rebuilds are sized by the live
        (row × bucket) set, not the field's full history.  Losing the
        entry-swap race to a concurrent reader's absorb retries
        against the new entry (up to ``attempts``) rather than
        degrading to a rebuild — under write+read concurrency that
        race is routine, a rebuild is not."""
        if self.delta_cells <= 0:
            return None
        from pilosa_tpu.ingest.delta import DeltaMirror
        from pilosa_tpu.timeviews import TimePlaneSet
        while True:
            old_gens, tps, nbytes = hit
            got = self._time_collect_changes(field, shards, hit,
                                             self.delta_cells)
            if got is None:
                return None
            cells, actual = got
            with self._lock:
                cur = self._entries.get(key)
                if cur is None or cur[1] is not tps:
                    if cur is not None and cur[0] == actual:
                        return cur[1]  # raced absorb, serving-fresh
                    if cur is None or attempts <= 0:
                        return None
                    attempts -= 1
                    hit = cur  # re-collect against the new entry
                    continue
                if actual == tuple(old_gens):
                    return tps  # no real gap (benign generation race)
                mir = self._delta_mirrors.get(key)
                if mir is None or mir[0] is not tps.plane:
                    mir = (tps.plane, DeltaMirror(self.delta_cells))
                    self._delta_mirrors[key] = mir
                mirror = mir[1]
                if not mirror.would_fit(cells):
                    return None  # overlay full: rebuild supersedes it
                mirror.absorb(cells)
                overlay = mirror.build_overlay(
                    self._overlay_put(),
                    tps.plane.shape[0] * tps.plane.shape[1])
                new_tps = TimePlaneSet(tps.plane, tps.shards,
                                       tps.row_ids, tps.slot_of,
                                       tps.buckets, tps.bucket_starts,
                                       tps.unit, delta=overlay)
                self._entries[key] = (actual, new_tps, nbytes)
                self._stamps.insert(key)
            self.delta_absorbs += 1
            return new_tps

    def _time_collect_changes(self, field: Field,
                              shards: tuple[int, ...], hit, cap: int):
        """Gather a "tplane" entry's write gap across its bucket
        views as overwrite cells ``({(flat_row, word): value},
        covered-through suffix-tagged gens)``; None = rebuild (bucket
        directory changed, new fragment/row, whole-row clear, over
        cap)."""
        from pilosa_tpu import timeviews
        from pilosa_tpu.store.view import VIEW_STANDARD
        old_gens, tps, _nbytes = hit
        if tuple(timeviews.bucket_suffixes(field)) != tps.buckets \
                or tuple(s for s, _ in old_gens) != tps.buckets:
            return None  # bucket appeared/vanished: geometry changed
        rb_pad = tps.plane.shape[1]
        nb = tps.n_buckets
        cells: dict = {}
        actual = []
        for bi, (suf, gens) in enumerate(old_gens):
            view = field.views.get(VIEW_STANDARD + "_" + suf)
            if view is None or len(gens) != len(shards):
                return None
            new_gens = list(gens)
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    if gens[si] != -1:
                        return None  # fragment vanished: rebuild
                    continue
                with frag.lock:
                    if gens[si] == -1:
                        return None  # new fragment: row set unknown
                    if frag.generation == gens[si]:
                        continue
                    changed = frag.changed_cells_since(gens[si])
                    if changed is None:
                        return None
                    for r, words in changed.items():
                        slot = tps.slot_of.get(int(r))
                        if slot is None:
                            return None  # new row: shape changed
                        if words is None:
                            return None  # whole-row clear: rebuild
                        flat = si * rb_pad + slot * nb + bi
                        row_words = np.asarray(
                            frag.row(int(r)).words(), np.uint32)
                        w_arr = np.fromiter(words, np.int64,
                                            len(words))
                        for w, v in zip(w_arr.tolist(),
                                        row_words[w_arr].tolist()):
                            cells[(flat, int(w))] = int(v)
                        if len(cells) > cap:
                            return None
                    new_gens[si] = frag.generation
            actual.append((suf, tuple(new_gens)))
        return cells, tuple(actual)

    def time_plane_status(self) -> list[dict]:
        """Resident "tplane" entries for the /status timeViews block:
        one row per (index, field) with bucket/row/byte geometry and
        overlay state — the operator's view of which time fields are
        serving at device speed."""
        out = []
        with self._lock:
            entries = list(self._entries.items())
        for key, (gens, tps, nbytes) in entries:
            if key[0] != "tplane":
                continue
            out.append({
                "index": key[1],
                "field": key[2],
                "shards": len(key[3]),
                "buckets": int(tps.n_buckets),
                "unit": tps.unit,
                "rows": int(len(tps.row_ids)),
                "bytes": int(nbytes),
                "delta": tps.delta is not None,
            })
        return out

    def wait_builds(self, timeout: float = 300.0) -> None:
        """Join in-flight background builds (OOM recovery's exclusive
        stage must not race GBs of invisible build residency)."""
        import time as _time
        end = _time.monotonic() + timeout
        while _time.monotonic() < end:
            with self._lock:
                t = next(iter(self._building.values()), None)
            if t is None:
                return
            t.join(max(0.1, end - _time.monotonic()))

    def _background_build(self, key, field: Field, view_name: str,
                          shards: tuple[int, ...], gens) -> None:
        try:
            ps = self._build_plane_chunked(field, view_name, shards)
            # publish BEFORE clearing _building (in the finally): a
            # wait_builds() caller must never observe "no builds" while
            # the plane is still about to be inserted — OOM recovery
            # invalidates right after that wait.
            # gens from BEFORE assembly: a mid-build write makes the
            # entry stale and the next query refreshes incrementally.
            self._insert_entry(key, gens, ps, ps.plane.size * 4)
        except Exception:  # noqa: BLE001 — build failure ≠ serving failure
            # queries keep streaming and the next request retries, but
            # a wedged build must be observable: count every failure
            # and log the traceback once per key (not once per retry)
            with self._lock:
                self.build_failures += 1
                first_for_key = key not in self._failed_logged
                if first_for_key:
                    if len(self._failed_logged) > 64:
                        self._failed_logged.clear()
                    self._failed_logged.add(key)
            self._stats.count("plane_build_failures_total", 1)
            if first_for_key:
                import logging
                logging.getLogger("pilosa_tpu.exec").exception(
                    "background plane build failed for %s "
                    "(queries keep streaming; next request retries)", key)
        finally:
            with self._lock:
                self._building.pop(key, None)

    # Builder threads for parallel fragment expansion: each expansion
    # is one native rc_expand_rows_into call that releases the GIL, so
    # the roaring→dense decode of a whole chunk runs at N-core speed
    # instead of one fragment at a time (BENCH_r05: 364 s of host-side
    # expansion in front of a 2.9 s raw copy).
    BUILD_WORKERS = 8

    def _build_plane_chunked(self, field: Field, view_name: str,
                             shards: tuple[int, ...]) -> PlaneSet:
        """Assemble a dense plane on device as a PIPELINE (r10):
        fragments of a chunk expand concurrently on a thread pool
        (bulk ``Fragment.expand_rows_into`` — native decode straight
        into the staging slab, dense sidecars served at memcpy speed),
        and chunks double-buffer so chunk N's host expansion overlaps
        chunk N−1's ``device_put`` + donated ``dynamic_update_slice``.
        Device memory stays 1× the plane (+1 chunk) and no single
        transfer exceeds BUILD_CHUNK_BYTES.

        Chunk axis: whole shards when a shard's slab fits a chunk (the
        common many-shards case — lets each fragment expand ONCE and
        write/read its dense sidecar), else row blocks across all
        shards (few huge shards)."""
        import time as _time
        t0 = _time.perf_counter()
        row_ids = self._union_row_ids(field, view_name, shards)
        r_pad = _pow2(max(1, len(row_ids)))
        slot_of = {int(r): i for i, r in enumerate(row_ids)}
        slab = r_pad * WORDS_PER_SHARD * 4
        if slab <= self.BUILD_CHUNK_BYTES:
            ps = self._build_shard_chunks(field, view_name, shards,
                                          row_ids, r_pad, slot_of)
        else:
            ps = self._build_row_chunks(field, view_name, shards,
                                        row_ids, r_pad, slot_of)
        dt = _time.perf_counter() - t0
        nbytes = ps.plane.size * 4
        with self._lock:  # concurrent background builds both tally
            self.builds += 1
            self.build_seconds_total += dt
            self.build_bytes_total += nbytes
        self._stats.observe("plane_build_seconds", dt)
        self._stats.count("plane_build_bytes_total", nbytes)
        return ps

    def _expand_tasks(self, pool, tasks, tally: bool = True) -> None:
        """Run fragment-expansion closures on the builder pool and
        tally sidecar warm/cold accounting (one count per FRAGMENT —
        callers whose chunks revisit fragments pass tally=False);
        re-raises the first failure (a build must never silently ship
        a half-expanded chunk)."""
        from concurrent.futures import wait
        futs = [pool.submit(t) for t in tasks]
        wait(futs)
        hits = misses = 0
        for f in futs:
            mode = f.result()
            if not tally:
                continue
            if mode == "warm":
                hits += 1
            elif self.sidecars:  # a miss only exists with the cache on
                misses += 1
        if hits or misses:
            # counters shared with concurrent builds + stats() readers
            with self._lock:
                self.warm_hits += hits
                self.warm_misses += misses
            if hits:
                self._stats.count("plane_cache_warm_hits_total", hits)
            if misses:
                self._stats.count("plane_cache_warm_misses_total", misses)

    def _build_shard_chunks(self, field: Field, view_name: str,
                            shards: tuple[int, ...], row_ids: np.ndarray,
                            r_pad: int, slot_of: dict) -> PlaneSet:
        """Shard-major pipeline: each chunk is a group of whole shards,
        so every fragment expands exactly once (all rows, one native
        call) and its dense sidecar is written/read in one piece."""
        import jax.numpy as jnp
        from concurrent.futures import ThreadPoolExecutor
        from functools import partial

        slab = r_pad * WORDS_PER_SHARD * 4
        spc = max(1, min(len(shards), self.BUILD_CHUNK_BYTES // slab))
        full = jnp.zeros((len(shards), r_pad, WORDS_PER_SHARD),
                         dtype=jnp.uint32)

        @partial(jax.jit, donate_argnums=(0,))
        def update(full, chunk, start):
            return jax.lax.dynamic_update_slice(
                full, chunk, (start, 0, 0))

        view = field.view(view_name)
        slots = np.arange(len(row_ids), dtype=np.uint64)
        # sidecar disk writes overlap the build on one writer thread
        # (bounded queue: a slow disk backpressures the expansion pool
        # instead of buffering unbounded blob bytes).  Safe deferred:
        # each item is immutable bytes stamped under the fragment lock.
        import queue as _queue
        from pilosa_tpu.store.fragment import Fragment
        wq: _queue.Queue | None = None
        wt = None
        submit = None
        if self.sidecars:
            wq = _queue.Queue(maxsize=8)

            def submit(path, hdr, blob):  # noqa: E306 — writer feed
                wq.put((path, hdr, blob))

            def _writer():
                while True:
                    item = wq.get()
                    if item is None:
                        return
                    Fragment.write_sidecar_file(*item)

            wt = threading.Thread(target=_writer, name="plane-sidecar",
                                  daemon=True)
            wt.start()
        # double buffers keyed (parity, group length): the tail group
        # may be narrower — its own buffer, its own compiled shape
        bufs: dict[tuple, np.ndarray] = {}
        inflight: dict[int, object] = {}
        try:
            with ThreadPoolExecutor(max_workers=self.BUILD_WORKERS) as pool:
                for gi, s0 in enumerate(range(0, len(shards), spc)):
                    glen = min(spc, len(shards) - s0)
                    par = gi % 2
                    buf = bufs.get((par, glen))
                    if buf is None:
                        buf = bufs[(par, glen)] = np.zeros(
                            (glen, r_pad, WORDS_PER_SHARD), np.uint32)
                    else:
                        # reusing a staging buffer: its previous H2D
                        # copy must have completed — the placed chunk
                        # that consumed it being ready guarantees that
                        if inflight.get(par) is not None:
                            inflight[par].block_until_ready()
                        buf[:] = 0
                    tasks = []
                    if view is not None and len(row_ids):
                        for li in range(glen):
                            s = shards[s0 + li]
                            if s == PAD_SHARD:
                                continue
                            frag = view.fragment(s)
                            if frag is None:
                                continue
                            tasks.append(partial(
                                frag.expand_rows_into, row_ids, buf[li],
                                slots, sidecar=self.sidecars,
                                sidecar_submit=submit))
                    self._expand_tasks(pool, tasks)
                    placed = self.place(buf)
                    full = update(full, placed, np.int32(s0))
                    # track the NON-donated placed chunk: it being
                    # ready proves the H2D copy out of buf completed
                    # (full itself is donated into the next update and
                    # can't be polled)
                    inflight[par] = placed
        finally:
            if wq is not None:
                wq.put(None)
                wt.join()
        full.block_until_ready()
        return PlaneSet(full, shards, row_ids, slot_of)

    def _build_row_chunks(self, field: Field, view_name: str,
                          shards: tuple[int, ...], row_ids: np.ndarray,
                          r_pad: int, slot_of: dict) -> PlaneSet:
        """Row-block pipeline for planes whose per-shard slab exceeds
        BUILD_CHUNK_BYTES: chunks span all shards × a row block (the
        pre-r10 tiling, now with parallel expansion + overlapped H2D).
        Sidecars are OFF here: a row block never covers a fragment's
        full row set (so images could never be written), and warm
        reads would re-open + re-crc the entire multi-hundred-MB image
        once per chunk — O(chunks × image bytes) of redundant work."""
        import jax.numpy as jnp
        from concurrent.futures import ThreadPoolExecutor
        from functools import partial

        block = max(1, self.BUILD_CHUNK_BYTES
                    // (len(shards) * WORDS_PER_SHARD * 4))
        # pow2 ≤ r_pad so chunks tile evenly — dynamic_update_slice
        # CLAMPS an out-of-bounds start, which would misplace the tail
        block = min(r_pad, 1 << max(0, block.bit_length() - 1))
        full = jnp.zeros((len(shards), r_pad, WORDS_PER_SHARD),
                         dtype=jnp.uint32)

        @partial(jax.jit, donate_argnums=(0,))
        def update(full, chunk, start):
            return jax.lax.dynamic_update_slice(
                full, chunk, (0, start, 0))

        view = field.view(view_name)
        bufs: list = [None, None]
        inflight: list = [None, None]
        with ThreadPoolExecutor(max_workers=self.BUILD_WORKERS) as pool:
            for ci, start in enumerate(range(0, r_pad, block)):
                chunk_rows = row_ids[start:start + block]
                if not len(chunk_rows):
                    break  # the pow2 tail is already zeros
                par = ci % 2
                buf = bufs[par]
                if buf is None:
                    buf = bufs[par] = np.zeros(
                        (len(shards), block, WORDS_PER_SHARD), np.uint32)
                else:
                    if inflight[par] is not None:
                        inflight[par].block_until_ready()
                    buf[:] = 0
                slots = np.arange(len(chunk_rows), dtype=np.uint64)
                tasks = []
                if view is not None:
                    for si, s in enumerate(shards):
                        if s == PAD_SHARD:
                            continue
                        frag = view.fragment(s)
                        if frag is None:
                            continue
                        tasks.append(partial(
                            frag.expand_rows_into, chunk_rows, buf[si],
                            slots))
                self._expand_tasks(pool, tasks, tally=False)
                placed = self.place(buf)
                full = update(full, placed, np.int32(start))
                inflight[par] = placed  # non-donated: pollable copy fence
        full.block_until_ready()
        return PlaneSet(full, shards, row_ids, slot_of)

    def has_plane(self, index: str, field: Field, view_name: str,
                  shards: tuple[int, ...]) -> bool:
        """Whether a whole-view plane entry can serve: fresh
        (generations match) or — with delta overlays on — stale but
        absorbable (the nowait fetch folds the write gap into the
        overlay without a rebuild).  Callers skip their admission/
        budget walks on True; growth past the budget is re-checked by
        ``field_plane_nowait`` before any rebuild spawns."""
        key = ("plane", index, field.name, view_name, shards)
        hit = self._entries.get(key)  # GIL-atomic; no lock needed
        if hit is None:
            return False
        if hit[0] == self._gens_fast(field, view_name, shards):
            return True
        return self.delta_cells > 0

    def has_entry(self, index: str, field: Field, view_name: str,
                  shards: tuple[int, ...]) -> bool:
        """A whole-view plane entry exists (fresh, delta-dirty, or
        stale).  The TopN admission path uses this to keep the
        per-request ``plane_bytes`` fragment walk off the hot path
        under sustained writes."""
        return ("plane", index, field.name, view_name,
                shards) in self._entries

    def rows_plane(self, index: str, field: Field, view_name: str,
                   row_ids: np.ndarray,
                   shards: tuple[int, ...]) -> PlaneSet:
        """Plane over EXACTLY the requested rows (GroupBy/UnionRows:
        memory bounded by the selection, not the field's cardinality)."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        key = ("rows", index, field.name, view_name,
               tuple(int(r) for r in row_ids), shards)
        return self._get(key, field, view_name, shards,
                         lambda f, v, s: self._build_rows(f, v, s, row_ids))

    def _build_rows(self, field: Field, view_name: str,
                    shards: tuple[int, ...],
                    row_ids: np.ndarray) -> PlaneSet:
        r_pad = _pow2(max(1, len(row_ids)))
        host = np.zeros((len(shards), r_pad, WORDS_PER_SHARD),
                        dtype=np.uint32)
        slot_of = {int(r): i for i, r in enumerate(row_ids)}
        view = field.view(view_name)
        if view is not None:
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    continue
                frag.plane_rows(list(slot_of.keys()), host[si],
                                slots=list(slot_of.values()))
        return PlaneSet(self.place(host), shards, row_ids, slot_of)

    def _sparse_mesh(self):
        """(D, mesh, axis) when the sparse build should device-block:
        a 1-D shard mesh with >1 device (2-D word-split meshes keep the
        flat layout replicated — sparse CP-splitting is not built)."""
        p = self.placement
        if (p is not None and getattr(p, "words_size", 1) == 1
                and getattr(p, "n_devices", 1) > 1
                and getattr(p, "mesh", None) is not None):
            return p.n_devices, p.mesh, p.axis
        return None

    def sparse_bytes(self, field: Field, view_name: str,
                     shards: tuple[int, ...]) -> int:
        """Sparse-residency footprint with the SAME padding the build
        applies — the budget gate must never admit a set the cache then
        refuses (which would silently re-build per query).  Meshed:
        every device block pads to the LARGEST device's pow2 bucket, so
        the estimate groups per-shard cardinalities by device."""
        view = field.view(view_name)
        mesh_info = self._sparse_mesh()
        d = mesh_info[0] if mesh_info else 1
        per_dev = np.zeros(d, np.int64)
        total_rows = 0
        if view is not None:
            spd = max(1, len(shards) // d)
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is not None:
                    per_dev[min(si // spd, d - 1)] += frag.cardinality()
                    total_rows += len(frag.row_cardinalities()[0])
        r_term = (_pow2(max(1, total_rows)) + 1) * 4 * d
        return d * _pow2(max(1, int(per_dev.max()))) * 8 + r_term

    def sparse_plane(self, index: str, field: Field, view_name: str,
                     shards: tuple[int, ...]) -> SparseSet:
        """Device-resident sparse triplets for a high-row-cardinality
        view (cached/invalidation like dense planes)."""
        key = ("sparse", index, field.name, view_name, shards)
        return self._get(key, field, view_name, shards, self._build_sparse)

    def _build_sparse(self, field: Field, view_name: str,
                      shards: tuple[int, ...]) -> SparseSet:
        from pilosa_tpu.engine.words import SHARD_WIDTH
        view = field.view(view_name)
        mesh_info = self._sparse_mesh()
        d = mesh_info[0] if mesh_info else 1
        if len(shards) % d:
            raise AssertionError(
                f"sparse build: {len(shards)} shards not padded to the "
                f"{d}-device mesh (executor pads via placement)")
        spd = len(shards) // d
        per_shard = []  # (si, positions)
        frags = []
        if view is not None:
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    continue
                frags.append(frag)
                per_shard.append((si, frag.positions()))
        all_ids, row_cards = merge_row_cards(frags)
        r_pad = _pow2(max(1, len(all_ids)))

        # per-device bit lists; unmeshed is the d == 1 special case.
        # word indices are LOCAL to the device's filter block (si % spd)
        # so each chip's gather never leaves its resident words.
        wi_parts: list[list] = [[] for _ in range(d)]
        mask_parts: list[list] = [[] for _ in range(d)]
        slot_parts: list[list] = [[] for _ in range(d)]
        for si, pos in per_shard:
            if not len(pos):
                continue
            dev = si // spd
            rows = pos // np.uint64(SHARD_WIDTH)
            cols = (pos % np.uint64(SHARD_WIDTH)).astype(np.int64)
            wi_parts[dev].append(((si % spd) * WORDS_PER_SHARD
                                  + (cols >> 5)).astype(np.int32))
            mask_parts[dev].append(
                (np.uint32(1) << (cols & 31).astype(np.uint32)))
            slot_parts[dev].append(
                np.searchsorted(all_ids, rows).astype(np.int32))

        def assemble(parts_w, parts_m, parts_s):
            if parts_w:
                wi = np.concatenate(parts_w)
                mk = np.concatenate(parts_m)
                sl = np.concatenate(parts_s)
                order = np.argsort(sl, kind="stable")  # CSR row order
                return wi[order], mk[order], sl[order]
            return (np.empty(0, np.int32), np.empty(0, np.uint32),
                    np.empty(0, np.int32))

        blocks = [assemble(wi_parts[i], mask_parts[i], slot_parts[i])
                  for i in range(d)]
        n_pad = _pow2(max(1, max(len(b[0]) for b in blocks)))
        wi_out = np.zeros((d, n_pad), np.int32)
        mk_out = np.zeros((d, n_pad), np.uint32)  # mask 0 = padding
        rp_out = np.empty((d, r_pad + 1), np.int32)
        for i, (wi, mk, sl) in enumerate(blocks):
            wi_out[i, :len(wi)] = wi
            mk_out[i, :len(mk)] = mk
            # CSR boundaries; pad rows collapse to empty segments at N
            rp_out[i] = np.searchsorted(
                sl, np.arange(r_pad + 1, dtype=np.int64))
        nbytes = d * n_pad * 8 + d * (r_pad + 1) * 4
        if mesh_info:
            _, mesh, axis = mesh_info
            from jax.sharding import NamedSharding, PartitionSpec as P
            sh = NamedSharding(mesh, P(axis, None))
            return SparseSet(
                word_idx=jax.device_put(wi_out, sh),
                mask=jax.device_put(mk_out, sh),
                row_ptr=jax.device_put(rp_out, sh), row_ids=all_ids,
                row_cards=row_cards, shards=shards, nbytes=nbytes,
                n_rows_pad=r_pad, mesh=mesh, axis=axis)
        return SparseSet(
            word_idx=self.place(wi_out[0]), mask=self.place(mk_out[0]),
            row_ptr=self.place(rp_out[0]), row_ids=all_ids,
            row_cards=row_cards, shards=shards, nbytes=nbytes,
            n_rows_pad=r_pad)

    def row_words(self, index: str, field: Field, view_name: str,
                  row_id: int, shards: tuple[int, ...]) -> jax.Array:
        """One row across shards: uint32[n_shards, W] (Row-call fast path —
        avoids materializing the whole plane for wide fields)."""
        key = ("row", index, field.name, view_name, row_id, shards)
        ps = self._get(key, field, view_name, shards,
                       lambda f, v, s: self._build_row(f, v, s, row_id))
        return ps.plane

    def plane_bytes(self, field: Field, view_name: str,
                    shards: tuple[int, ...]) -> int:
        """Estimated dense-plane footprint (for budget decisions).

        Generation-cached: the estimate runs on EVERY query of the
        field (admission check), and recomputing it for a 5M-row
        sparse field measured ~7 s/query at 954 shards (config10 —
        the same class as the r3 warm-path metadata fixes)."""
        gens = self._gens(field, view_name, shards)
        key = (field.path, view_name, shards)
        with self._lock:
            hit = self._bytes_cache.get(key)
            if hit is not None and hit[0] == gens:
                return hit[1]
        est = (len(shards)
               * _pow2(max(1, len(self._union_row_ids(field, view_name,
                                                      shards))))
               * WORDS_PER_SHARD * 4)
        with self._lock:
            self._bytes_cache[key] = (gens, est)
            while len(self._bytes_cache) > 256:
                self._bytes_cache.pop(next(iter(self._bytes_cache)))
        return est

    @staticmethod
    def _union_row_ids(field: Field, view_name: str,
                       shards: tuple[int, ...]) -> np.ndarray:
        """Sorted distinct row ids across shards, vectorized (one
        np.unique over concatenated per-fragment arrays instead of a
        Python set union + sort)."""
        view = field.view(view_name)
        parts = []
        if view is not None:
            for s in shards:
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is not None:
                    parts.append(frag.row_ids_array())
        if not parts:
            return np.empty(0, np.uint64)
        return np.unique(np.concatenate(parts))

    def iter_row_blocks(self, field: Field, view_name: str,
                        shards: tuple[int, ...], block_rows: int):
        """Stream a view's rows through the device in fixed-size blocks:
        yields (row_ids[block], device uint32[n_shards, block, W]).

        The working-set half of SURVEY.md §8's "dense blowup" hard part:
        fields whose full plane exceeds the HBM budget never materialize
        it — each block reuses one compiled shape.  The final block is
        zero-padded (padded rows yield zero counts; callers slice)."""
        view = field.view(view_name)
        row_ids = self._union_row_ids(field, view_name, shards)
        for start in range(0, len(row_ids), block_rows):
            chunk = row_ids[start:start + block_rows]
            host = np.zeros((len(shards), block_rows, WORDS_PER_SHARD),
                            dtype=np.uint32)
            slot_of = {int(r): i for i, r in enumerate(chunk)}
            if view is not None:
                for si, s in enumerate(shards):
                    if s == PAD_SHARD:
                        continue
                    frag = view.fragment(s)
                    if frag is None:
                        continue
                    frag.plane_rows(list(slot_of.keys()), host[si],
                                    slots=list(slot_of.values()))
            yield chunk, self.place(host)

    def zeros(self, n_shards: int) -> jax.Array:
        """Cached all-zero bitmap uint32[n_shards, W] (empty Row / empty
        Union results) — built and transferred once per shard count, not
        per query."""
        key = n_shards
        with self._lock:
            hit = self._zeros.get(key)
        if hit is not None:
            return hit
        placed = self.place(np.zeros((n_shards, WORDS_PER_SHARD),
                                     dtype=np.uint32))
        with self._lock:
            self._zeros[key] = placed
        return placed

    def stats(self) -> dict:
        """Occupancy snapshot for /status and /metrics (one lock; the
        only supported external view of the cache's internals)."""
        with self._lock:
            hits, misses = self.hits, self.misses
            return {"bytes": self._bytes, "budgetBytes": self.budget,
                    "entries": len(self._entries),
                    "pinnedEntries": len(self._pinned()),
                    # HBM residency (r14): open lease sets = in-flight
                    # queries holding device refs eviction must skip;
                    # hitRatio = fraction of plane requests answered
                    # from a resident entry (vs built or streamed)
                    "leases": len(self._leases),
                    "hits": hits, "misses": misses,
                    "hitRatio": (round(hits / (hits + misses), 4)
                                 if hits + misses else 0.0),
                    "incrementalRefreshes": self.incremental_applied,
                    # r17 tenancy: explicit-order eviction accounting
                    # (budget pass, OOM recovery, quota make-room,
                    # stale page drops)
                    "evictions": self.evictions,
                    "evictionsByReason": dict(
                        self._evictions_by_reason),
                    # plane-build pipeline (r10): cold-build volume and
                    # the dense-sidecar warm cache's hit ratio
                    "builds": self.builds,
                    "buildSeconds": round(self.build_seconds_total, 3),
                    "buildBytes": self.build_bytes_total,
                    "buildFailures": self.build_failures,
                    "warmHits": self.warm_hits,
                    "warmMisses": self.warm_misses,
                    # meshed (ISSUE 16): builds land sharded across a
                    # placement (the inline meshed build path)
                    "meshed": self.placement is not None,
                    # r15 ingest: device delta overlays (writes served
                    # as base⊕delta without rebuild stalls)
                    "delta": self.delta_stats()}

    def invalidate(self, index: str | None = None) -> None:
        with self._lock:
            # footprint estimates drop wholesale either way: their
            # generation guard can false-match after an index is
            # deleted and recreated at the same path (generations
            # restart at 0), and recomputing them is cheap
            self._bytes_cache.clear()
            if index is None:
                self._entries.clear()
                self._stamps.clear()
                self._delta_mirrors.clear()
                self._bytes = 0
                return
            for key in [k for k in self._entries if k[1] == index]:
                _, _, nbytes = self._entries.pop(key)
                self._stamps.pop(key)
                self._delta_mirrors.pop(key, None)
                self._bytes -= nbytes

    # -- internal -----------------------------------------------------------

    def _gens(self, field: Field, view_name: str,
              shards: tuple[int, ...]) -> tuple:
        view = field.view(view_name)
        if view is None:
            return ()
        # PAD_SHARD (-1) is never a fragment key, so it maps to -1
        # like any absent shard
        return view.generations(shards)

    def _gens_fast(self, field: Field, view_name: str,
                   shards: tuple[int, ...]) -> tuple:
        """Lock-free generation read for the revalidation fast path:
        skips the field lock (``views`` dict read is GIL-atomic) AND
        the view lock (:meth:`View.generations_fast`) — the two
        per-query lock round trips the r6 concurrency work removed."""
        view = field.views.get(view_name)
        if view is None:
            return ()
        return view.generations_fast(shards)

    def _touch(self, key) -> None:
        # lock-free recency (the eviction tie-break) + governor value
        # telemetry (plain dict increment — a lost count under racing
        # threads never matters to a relative ordering)
        self._stamps.touch(key)
        if self.governor is not None:
            self.governor.note_hit(key)

    def _lease(self, key) -> None:
        # caller holds self._lock
        lease = self._leases.get(threading.get_ident())
        if lease is not None:
            lease.add(key)

    def _lease_fast(self, key) -> None:
        """Lock-free lease: replace this thread's lease set wholesale
        (existing-key dict write — atomic, no resize).  ``_pinned``
        snapshots the values under the cache lock and unions fully-
        formed set objects, so it sees either the old or the new set,
        never a torn one."""
        tid = threading.get_ident()
        lease = self._leases.get(tid)
        if lease is not None and key not in lease:
            self._leases[tid] = lease | {key}

    def _get(self, key, field: Field, view_name: str,
             shards: tuple[int, ...], build) -> PlaneSet:
        # cost-ledger plane attribution (r19): stamp the serving
        # thread with the plane this query is about to scan — one
        # thread-local write, nothing else on the fast path
        self._set_plane_ctx(f"{key[1]}/{key[2]}")
        # lock-free fast path: the common serving case is a fresh
        # resident plane — one dict read + one generation compare,
        # no cache lock, no view lock.  Delta-dirty entries never
        # return here: every _get caller needs a CLEAN plane (the
        # delta-aware consumers go through field_plane_nowait), so a
        # pending overlay folds first.
        hit = self._entries.get(key)
        if hit is not None and hit[0] == self._gens_fast(field, view_name,
                                                         shards) \
                and getattr(hit[1], "delta", None) is None:
            self._touch(key)
            self._lease_fast(key)
            self.hits += 1
            return hit[1]
        gens = self._gens(field, view_name, shards)
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None and hit[0] == gens \
                    and getattr(hit[1], "delta", None) is None:
                self._touch(key)
                self._lease(key)
                self.hits += 1
                return hit[1]
        if hit is not None and key[0] == "plane":
            # fold overlay + journal gap into the base in one scatter
            ps = self._fold(key, field, view_name, shards, hit)
            if ps is not None:
                with self._lock:
                    self._lease(key)
                self.hits += 1
                return ps
        elif hit is not None and key[0] in ("bsi", "rows", "row"):
            ps = None
            if getattr(hit[1], "delta", None) is None:
                ps = self._incremental(key, field, view_name, shards, hit)
            if ps is None and key[0] == "bsi":
                # a pending BSI overlay (r20) or a gap past the
                # incremental cap: fold overlay + journal gap into the
                # base in one scatter (bounded by delta_cells +
                # MAX_INCR_CELLS) — never silently drop overlay cells
                # by scattering around them, never rebuild for a
                # coverable gap
                ps = self._fold(key, field, view_name, shards, hit)
            if ps is not None:
                with self._lock:
                    self._lease(key)
                self.hits += 1
                return ps
        self.misses += 1
        ps = build(field, view_name, shards)
        nbytes = getattr(ps, "nbytes", None)
        if nbytes is None:
            nbytes = ps.plane.size * 4
        self._insert_entry(key, gens, ps, nbytes, lease=True)
        return ps

    def _insert_entry(self, key, gens, ps, nbytes: int,
                      lease: bool = False) -> None:
        """Cache a built plane and run the pinned-aware LRU eviction
        pass (shared by the query-path build and background builds —
        both must trim to budget or the cache sits over it until the
        next miss)."""
        with self._lock:
            if nbytes > self.budget:
                return
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[2]
            # a rebuilt base supersedes any pending overlay: the
            # fresh build re-read every fragment, so the mirror's
            # cells are already IN the new plane
            self._delta_mirrors.pop(key, None)
            self._entries[key] = (gens, ps, nbytes)
            self._stamps.insert(key)
            self._bytes += nbytes
            if lease:
                self._lease(key)
            # budget eviction skips leased entries: their device refs
            # are alive in query frames, so popping them frees no
            # HBM and forces the other query to rebuild mid-flight.
            # (_pinned() unions every lease set — only pay for it
            # when an eviction pass actually runs).  Order is the
            # explicit _eviction_order: governor keep-score when one
            # is attached, recency stamp otherwise.
            if self._bytes > self.budget and len(self._entries) > 1:
                pinned = self._pinned()
                for k in self._eviction_order(pinned):
                    if (self._bytes <= self.budget
                            or len(self._entries) <= 1):
                        break
                    if k == key:
                        continue
                    self._evict_entry(k, "budget")
            self._stamps.cleanup(self._entries)
        # compile-ladder warm-up (r24): a standard plane just became
        # resident — hand its shape to the background warmer so the
        # delta-aware program ladder compiles off the serving path
        # (outside the lock: note_resident is cheap but never worth
        # holding the cache lock for)
        if self.warmer is not None and key[0] == "plane":
            try:
                self.warmer.note_resident(tuple(ps.plane.shape))
            except Exception:  # noqa: BLE001 — warming is best-effort
                pass

    # Incremental cap: beyond this many changed (row, word) cells a
    # full rebuild is cheaper than the scatter
    MAX_INCR_CELLS = 4096

    def _incremental(self, key, field: Field, view_name: str,
                     shards: tuple[int, ...], hit):
        """Refresh a cached device plane IN PLACE from fragments'
        mutation journals instead of rebuilding + re-uploading — the
        device half of SURVEY.md §4.5 ingest (host delta queues →
        device scatter).  Returns the refreshed PlaneSet, or None when
        the journal can't cover the gap (fall back to rebuild)."""
        old_gens, ps, nbytes = hit
        kind = key[0]
        view = field.view(view_name)
        if view is None or len(old_gens) != len(shards):
            # no view yet, or the entry was cached before the view
            # existed (_gens returns () then): rebuild
            return None
        if kind == "row":
            the_row = key[4]
        r_pad = 1 if kind == "row" else ps.plane.shape[1]
        cell_rows, cell_words, cell_vals = [], [], []
        reset_rows, reset_vals = [], []
        actual = list(old_gens)
        for si, s in enumerate(shards):
            if s == PAD_SHARD:
                continue
            frag = view.fragment(s)
            if frag is None:
                if old_gens[si] != -1:
                    return None  # fragment vanished: rebuild
                continue
            with frag.lock:
                if old_gens[si] == -1:
                    return None  # new fragment: row set unknown
                if frag.generation == old_gens[si]:
                    continue
                cells = frag.changed_cells_since(old_gens[si])
                if cells is None:
                    return None
                for r, words in cells.items():
                    # running cap: don't assemble millions of cells
                    # only to discard them
                    if (len(cell_rows) + 64 * len(reset_rows)
                            > self.MAX_INCR_CELLS):
                        return None
                    if kind == "plane":
                        slot = ps.slot_of.get(int(r))
                        if slot is None:
                            return None  # new row: shape/row set changed
                    elif kind == "bsi":
                        if r >= r_pad:
                            return None  # bit depth grew
                        slot = int(r)
                    elif kind == "rows":
                        slot = ps.slot_of.get(int(r))
                        if slot is None:
                            continue  # outside the selection
                    else:  # "row"
                        if int(r) != the_row:
                            continue
                        slot = 0
                        words = None  # refresh the whole single row
                    flat = si * r_pad + slot
                    row_words = frag.row(int(r)).words()
                    if words is None:
                        reset_rows.append(flat)
                        reset_vals.append(np.array(row_words, np.uint32))
                    else:
                        w_arr = np.fromiter(words, np.int64, len(words))
                        cell_rows.extend([flat] * len(w_arr))
                        cell_words.extend(int(w) for w in w_arr)
                        cell_vals.extend(
                            np.asarray(row_words)[w_arr].tolist())
                actual[si] = frag.generation
        n_cells = len(cell_rows) + 64 * len(reset_rows)
        if n_cells > self.MAX_INCR_CELLS:
            return None
        new_plane = _apply_plane_cells(
            ps.plane if kind != "row" else ps.plane[:, None, :],
            np.asarray(cell_rows, np.int32), np.asarray(cell_words, np.int32),
            np.asarray(cell_vals, np.uint32),
            np.asarray(reset_rows, np.int32),
            (np.stack(reset_vals) if reset_vals
             else np.zeros((0, ps.plane.shape[-1]), np.uint32)))
        if kind == "row":
            new_plane = new_plane[:, 0, :]
        new_plane = self._repin(new_plane, ps.plane)
        new_ps = PlaneSet(new_plane, ps.shards, ps.row_ids, ps.slot_of)
        with self._lock:
            cur = self._entries.get(key)
            if cur is not None and cur[1] is ps:  # not replaced meanwhile
                self._entries[key] = (tuple(actual), new_ps, nbytes)
                self._stamps.insert(key)
        self.incremental_applied += 1
        return new_ps

    # -- delta overlays (r15 ingest) ----------------------------------------

    def _collect_changes(self, field: Field, view_name: str,
                         shards: tuple[int, ...], hit, cap: int):
        """Gather the write gap between a "plane" entry's covered
        generations and fragment truth as overwrite cells:
        ``({(flat_row, word): current word value}, [(flat_row,
        full-row words)] resets, covered-through gens)``, or None when
        the journal can't cover it (gap, new rows, over cap) — the
        caller compacts or rebuilds."""
        old_gens, ps, _nbytes = hit
        view = field.view(view_name)
        if view is None or len(old_gens) != len(shards):
            return None
        r_pad = ps.plane.shape[1]
        cells: dict = {}
        resets: list = []
        actual = list(old_gens)
        for si, s in enumerate(shards):
            if s == PAD_SHARD:
                continue
            frag = view.fragment(s)
            if frag is None:
                if old_gens[si] != -1:
                    return None  # fragment vanished: rebuild
                continue
            with frag.lock:
                if old_gens[si] == -1:
                    return None  # new fragment: row set unknown
                if frag.generation == old_gens[si]:
                    continue
                changed = frag.changed_cells_since(old_gens[si])
                if changed is None:
                    return None
                for r, words in changed.items():
                    slot = ps.slot_of.get(int(r))
                    if slot is None:
                        return None  # new row: shape/row set changed
                    flat = si * r_pad + slot
                    row_words = np.asarray(frag.row(int(r)).words(),
                                           np.uint32)
                    if words is None:
                        resets.append((flat, row_words))
                    else:
                        w_arr = np.fromiter(words, np.int64, len(words))
                        for w, v in zip(w_arr.tolist(),
                                        row_words[w_arr].tolist()):
                            cells[(flat, int(w))] = int(v)
                    if len(cells) + 64 * len(resets) > cap:
                        return None
                actual[si] = frag.generation
        return cells, resets, tuple(actual)

    def _delta_update(self, key, field: Field, view_name: str,
                      shards: tuple[int, ...], hit):
        """Bring a stale "plane" entry back to serving truth without a
        rebuild: absorb the gap into the device overlay (base stays
        immutable; queries answer base⊕delta), or fold overlay+gap
        into the base when the overlay can't take it.  None = rebuild
        (journal gap / new rows)."""
        ps = self._delta_absorb(key, field, view_name, shards, hit)
        if ps is not None:
            return ps
        hit = self._entries.get(key)
        if hit is None:
            return None
        return self._fold(key, field, view_name, shards, hit)

    def _overlay_put(self):
        """Placement for overlay device arrays: replicated across the
        mesh when one exists, plain ``device_put`` otherwise."""
        p = self.placement
        if p is not None and hasattr(p, "replicate"):
            return p.replicate
        return jax.device_put

    def _repin(self, arr, like):
        """Keep a refreshed plane on its predecessor's sharding: the
        scatter's output layout is GSPMD's choice, and fused program
        keys carry sharding identity (``exec.fused.sharding_key``) —
        a drifted layout would recompile every family for the plane."""
        if self.placement is None:
            return arr
        try:
            if arr.sharding == like.sharding:
                return arr
            return jax.device_put(arr, like.sharding)
        except Exception:  # noqa: BLE001 — best-effort pinning
            return arr

    def _delta_absorb(self, key, field: Field, view_name: str,
                      shards: tuple[int, ...], hit):
        """Absorb journal cells into the plane's bounded device
        overlay and advance the entry's covered generations — the
        serving-path write step: no base-plane rewrite, no
        generation-stale window.  None = can't absorb (disabled,
        whole-row ops, overlay full, journal gap)."""
        if self.delta_cells <= 0:
            return None
        old_gens, ps, nbytes = hit
        got = self._collect_changes(field, view_name, shards, hit,
                                    self.delta_cells)
        if got is None:
            return None
        cells, resets, actual = got
        if resets:
            return None  # whole-row replacements fold instead
        from pilosa_tpu.ingest.delta import DeltaMirror
        with self._lock:
            cur = self._entries.get(key)
            if cur is None or cur[1] is not ps:
                # raced another absorb/fold/rebuild: report the
                # current entry if it is already serving-fresh
                if cur is not None and cur[0] == actual:
                    return cur[1]
                return None
            if actual == tuple(old_gens):
                return ps  # no real gap (benign generation race)
            mir = self._delta_mirrors.get(key)
            if mir is None or mir[0] is not ps.plane:
                mir = (ps.plane, DeltaMirror(self.delta_cells))
                self._delta_mirrors[key] = mir
            mirror = mir[1]
            if not mirror.would_fit(cells):
                return None  # overlay full: fold/compact
            mirror.absorb(cells)
            # overlay arrays are tiny — under a mesh they replicate
            # (one copy per chip) so the merge with the shard-sharded
            # base compiles without a host round trip
            put = self._overlay_put()
            if key[0] == "bsi":
                # bit-sliced planes overlay by touched word COLUMN
                # (the aggregate kernels read whole columns) — see
                # ingest.delta.BsiOverlay
                overlay = mirror.build_bsi_overlay(
                    put, ps.plane.shape[1],
                    ps.plane.shape[0])
            else:
                overlay = mirror.build_overlay(
                    put,
                    ps.plane.shape[0] * ps.plane.shape[1])
            new_ps = PlaneSet(ps.plane, ps.shards, ps.row_ids,
                              ps.slot_of, delta=overlay)
            self._entries[key] = (actual, new_ps, nbytes)
            self._stamps.insert(key)
            fill = len(mirror) / max(1, self.delta_cells)
        self.delta_absorbs += 1
        if fill >= self.delta_compact_fraction:
            self._compact_async(key, field, view_name, shards)
        return new_ps

    # raced sentinel: a concurrent absorb/fold replaced the entry
    # mid-fold — retry against the new entry (NOT a rebuild signal)
    _RACED = object()

    def _fold(self, key, field: Field, view_name: str,
              shards: tuple[int, ...], hit):
        """Fold the entry's overlay plus any remaining journal gap
        into the base plane in ONE scatter (the existing
        ``dynamic_update_slice``/scatter machinery) and atomically
        swap the entry to a clean PlaneSet at the new generations —
        the compaction step.  Retries when a concurrent absorb swaps
        the entry mid-fold (under sustained writes the race is the
        common case, and giving up would force a spurious rebuild; on
        a starved CPU the swaps come slower than the retries, so the
        bound is sized for an oversubscribed box, not the happy path).
        None = the gap genuinely isn't coverable (rebuild)."""
        for _ in range(8):
            out = self._fold_once(key, field, view_name, shards, hit)
            if out is not self._RACED:
                return out
            hit = self._entries.get(key)
            if hit is None:
                return None
        return None

    def _fold_once(self, key, field: Field, view_name: str,
                   shards: tuple[int, ...], hit):
        import time as _time
        old_gens, ps, nbytes = hit
        got = self._collect_changes(field, view_name, shards, hit,
                                    self.delta_cells
                                    + self.MAX_INCR_CELLS)
        if got is None:
            return None
        cells, resets, actual = got
        t0 = _time.perf_counter()
        with self._lock:
            cur = self._entries.get(key)
            if cur is None or cur[1] is not ps:
                if cur is not None and cur[0] == actual \
                        and getattr(cur[1], "delta", None) is None:
                    return cur[1]
                return self._RACED if cur is not None else None
            mir = self._delta_mirrors.get(key)
            mirror_cells = (mir[1].snapshot()
                            if mir is not None and mir[0] is ps.plane
                            else {})
        if ps.delta is not None and not mirror_cells:
            # overlay without its mirror (dropped out from under us —
            # e.g. an invalidate raced): the cells can't be recovered
            # host-side, so rebuild rather than silently lose them
            return None
        if not cells and not resets and not mirror_cells:
            if actual == tuple(old_gens):
                return ps
            # generations advanced with empty journal coverage — swap
            # the covered gens forward without touching the plane
            new_ps = ps
        else:
            reset_rows = [fr for fr, _ in resets]
            reset_set = set(reset_rows)
            merged = {k: v for k, v in mirror_cells.items()
                      if k[0] not in reset_set}
            merged.update(cells)  # journal truth supersedes the mirror
            new_plane = _apply_plane_cells(
                ps.plane,
                np.fromiter((k[0] for k in merged), np.int64,
                            len(merged)).astype(np.int32),
                np.fromiter((k[1] for k in merged), np.int64,
                            len(merged)).astype(np.int32),
                np.fromiter(merged.values(), np.uint32, len(merged)),
                np.asarray(reset_rows, np.int32),
                (np.stack([rv for _, rv in resets]) if resets
                 else np.zeros((0, ps.plane.shape[-1]), np.uint32)))
            new_plane = self._repin(new_plane, ps.plane)
            new_ps = PlaneSet(new_plane, ps.shards, ps.row_ids,
                              ps.slot_of)
        with self._lock:
            cur = self._entries.get(key)
            if cur is None or cur[1] is not ps:
                return self._RACED if cur is not None else None
            self._entries[key] = (actual, new_ps, nbytes)
            self._stamps.insert(key)
            self._delta_mirrors.pop(key, None)
        self.incremental_applied += 1
        if mirror_cells or ps.delta is not None:
            self.delta_compactions += 1
            self.last_compaction_seconds = _time.perf_counter() - t0
            self._stats.count("delta_compactions_total", 1)
        return new_ps

    def _compact_async(self, key, field: Field, view_name: str,
                       shards: tuple[int, ...]) -> None:
        """Kick the background compactor for one plane (single-flight
        per key): folds the overlay into the base off the serving path
        and swaps generations atomically."""
        with self._lock:
            if key in self._compacting:
                return
            t = threading.Thread(
                target=self._compact_run,
                args=(key, field, view_name, shards),
                name="delta-compact", daemon=True)
            self._compacting[key] = t
        t.start()

    def _compact_run(self, key, field: Field, view_name: str,
                     shards: tuple[int, ...]) -> None:
        try:
            hit = self._entries.get(key)
            if hit is not None:
                self._fold(key, field, view_name, shards, hit)
        except Exception:  # noqa: BLE001 — compaction ≠ serving
            import logging
            logging.getLogger("pilosa_tpu.exec").exception(
                "delta compaction failed for %s (queries keep "
                "answering base⊕delta; next trigger retries)", key)
        finally:
            with self._lock:
                self._compacting.pop(key, None)

    def delta_stats(self) -> dict:
        """The /status ``ingest`` block's overlay half."""
        with self._lock:
            cells = sum(len(m) for _, m in self._delta_mirrors.values())
            bits = sum(m.bits for _, m in self._delta_mirrors.values())
            pending = len(self._compacting)
        cap = max(1, self.delta_cells)
        return {"deltaCells": cells, "deltaCap": self.delta_cells,
                "deltaOverlayBits": bits,
                "deltaFillRatio": (round(cells / cap, 4)
                                   if self.delta_cells else 0.0),
                "absorbs": self.delta_absorbs,
                "compactions": self.delta_compactions,
                "pendingCompactions": pending,
                "lastCompactionSeconds": round(
                    self.last_compaction_seconds, 6)}

    def _build_plane(self, field: Field, view_name: str,
                     shards: tuple[int, ...]) -> PlaneSet:
        """Monolithic single-transfer build — the pure-Python
        ``plane_rows`` path, kept untouched as the ORACLE the pipelined
        chunked build is tested bit-exact against."""
        import time as _time
        t0 = _time.perf_counter()
        view = field.view(view_name)
        row_ids = self._union_row_ids(field, view_name, shards)
        r_pad = _pow2(max(1, len(row_ids)))
        host = np.zeros((len(shards), r_pad, WORDS_PER_SHARD), dtype=np.uint32)
        slot_of = {int(r): i for i, r in enumerate(row_ids)}
        if view is not None:
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    continue
                rows_here = frag.row_ids()
                frag.plane_rows(rows_here, host[si],
                                slots=[slot_of[r] for r in rows_here])
        ps = PlaneSet(self.place(host), shards, row_ids, slot_of)
        dt = _time.perf_counter() - t0
        with self._lock:
            self.builds += 1
            self.build_seconds_total += dt
            self.build_bytes_total += host.nbytes
        self._stats.observe("plane_build_seconds", dt)
        self._stats.count("plane_build_bytes_total", host.nbytes)
        return ps

    def _build_plane_meshed(self, field: Field, view_name: str,
                            shards: tuple[int, ...]) -> PlaneSet:
        """Meshed inline build (ISSUE 16 satellite): fragments expand
        CONCURRENTLY on the builder pool (native decode straight into
        the host slab, dense sidecars honored) and the slab lands in
        ONE sharded ``device_put`` — the chunked donated-update
        pipeline assumes a single-device layout, so meshed builds get
        their own path that still pays into the PR 5 build telemetry
        (``plane_build_seconds``/``plane_build_bytes_total``) instead
        of bypassing it silently."""
        import time as _time
        from concurrent.futures import ThreadPoolExecutor
        from functools import partial
        t0 = _time.perf_counter()
        view = field.view(view_name)
        row_ids = self._union_row_ids(field, view_name, shards)
        r_pad = _pow2(max(1, len(row_ids)))
        host = np.zeros((len(shards), r_pad, WORDS_PER_SHARD),
                        dtype=np.uint32)
        slot_of = {int(r): i for i, r in enumerate(row_ids)}
        slots = np.arange(len(row_ids), dtype=np.uint64)
        tasks = []
        if view is not None and len(row_ids):
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue  # padding stays all-zero words
                frag = view.fragment(s)
                if frag is None:
                    continue
                tasks.append(partial(
                    frag.expand_rows_into, row_ids, host[si], slots,
                    sidecar=self.sidecars))
        if tasks:
            with ThreadPoolExecutor(
                    max_workers=self.BUILD_WORKERS) as pool:
                self._expand_tasks(pool, tasks)
        ps = PlaneSet(self.place(host), shards, row_ids, slot_of)
        dt = _time.perf_counter() - t0
        with self._lock:
            self.builds += 1
            self.build_seconds_total += dt
            self.build_bytes_total += host.nbytes
        self._stats.observe("plane_build_seconds", dt)
        self._stats.count("plane_build_bytes_total", host.nbytes)
        return ps

    def mesh_stats(self) -> dict | None:
        """/status ``mesh`` block (ISSUE 16): device count, shard
        axis, per-device resident plane bytes, padded-shard count —
        None when serving single-device.  Also refreshes the
        ``plane_shard_bytes{device}`` gauges so /metrics shows the
        HBM spread across the mesh."""
        p = self.placement
        if p is None:
            return None
        with self._lock:
            entries = [e[1] for e in self._entries.values()]
        per_dev: dict[str, int] = {}
        padded = 0
        seen = set()
        for ps in entries:
            for s in getattr(ps, "shards", ()):
                if s == PAD_SHARD:
                    padded += 1
            plane = getattr(ps, "plane", None)
            if plane is None or id(plane) in seen:
                continue
            seen.add(id(plane))
            try:
                for sh in plane.addressable_shards:
                    d = str(sh.device)
                    per_dev[d] = per_dev.get(d, 0) + int(sh.data.nbytes)
            except Exception:  # noqa: BLE001 — telemetry best effort
                continue
        for d, b in per_dev.items():
            self._stats.gauge("plane_shard_bytes", b, device=d)
        n_dev = int(getattr(p, "n_devices", 1)
                    * getattr(p, "words_size", 1))
        axis = getattr(p, "axis", None) or getattr(p, "shard_axis",
                                                   "shard")
        return {"devices": n_dev, "axis": axis,
                "perDeviceBytes": per_dev, "paddedShards": padded}

    def _build_bsi(self, field: Field, view_name: str,
                   shards: tuple[int, ...]) -> PlaneSet:
        depth = field.options.bit_depth
        n_rows = OFFSET_ROW + depth
        host = np.zeros((len(shards), n_rows, WORDS_PER_SHARD), dtype=np.uint32)
        view = field.view(view_name)
        if view is not None:
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is None:
                    continue
                rows_here = [r for r in frag.row_ids() if r < n_rows]
                frag.plane_rows(rows_here, host[si], slots=rows_here)
        row_ids = np.arange(n_rows, dtype=np.uint64)
        return PlaneSet(self.place(host), shards, row_ids,
                        {i: i for i in range(n_rows)})

    def _build_row(self, field: Field, view_name: str,
                   shards: tuple[int, ...], row_id: int) -> PlaneSet:
        host = np.zeros((len(shards), WORDS_PER_SHARD), dtype=np.uint32)
        view = field.view(view_name)
        if view is not None:
            for si, s in enumerate(shards):
                if s == PAD_SHARD:
                    continue
                frag = view.fragment(s)
                if frag is not None:
                    # plane_rows: snapshot rows come straight off the
                    # blob (bitmap containers memcpy) — no RowBits
                    frag.plane_rows([row_id], host[si:si + 1], slots=[0])
        return PlaneSet(self.place(host), shards,
                        np.array([row_id], np.uint64), {row_id: 0})
