"""Whole-query fusion: one compiled XLA program per call-tree shape.

SURVEY.md §8: "One compiled function per (call-shape, row-bucket)".
Eager per-op dispatch costs one device round trip per AST node; here the
bitmap-call tree is planned into (structure key, leaf arrays), the
structure is compiled once into a single jitted program (bitwise tree +
optional popcount-reduce fused end-to-end by XLA), and subsequent
queries with the same shape — any row IDs, any predicate values — reuse
it with zero retracing.

Predicate values enter as *traced* leaves (lane-broadcast masks and a
sign scalar, see ``engine.bsi.predicate_masks``), so ``amount > 5`` and
``amount > 99`` hit the same executable.
"""

from __future__ import annotations

import threading as _threading
import time as _time

import jax
import jax.numpy as jnp
import numpy as np

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels

# node encodings (hashable nested tuples):
#   ("leaf", i)                      leaf i is uint32[..., W] words
#   ("zeros",)                       all-empty bitmap
#   ("or-leaves", (i, j, ...))       union of row leaves (time ranges)
#   ("and"|"or"|"andnot"|"xor", (child, child, ...))   fold left
#   ("not", child, i_exists)
#   ("shift", child, n)
#   ("bsi", i_plane, i_masks, i_neg, op_key)
#   ("bsi-between", i_plane, i_lo_masks, i_lo_neg, lo_op,
#                   i_hi_masks, i_hi_neg, hi_op)


class Unfusable(Exception):
    """Raised by planners for shapes the fused path doesn't cover."""


def sharding_key(arr) -> object:
    """Hashable sharding identity for program keys (mesh serving).

    A jitted program specializes on its operands' shardings — GSPMD
    compiles the cross-shard reductions (``sum`` over the shard axis,
    shard-axis-sum-then-``top_k``) into ICI collectives — so the same
    shape under two placements is two programs.  Keys carry this
    alongside shape; single-device arrays map to None so the pre-mesh
    key space is unchanged."""
    sh = getattr(arr, "sharding", None)
    if sh is None:
        return None
    try:
        if len(sh.device_set) <= 1:
            return None
        mesh = getattr(sh, "mesh", None)
        spec = getattr(sh, "spec", None)
        if mesh is not None:
            return (tuple(mesh.shape.items()), str(spec))
        return str(sh)
    except Exception:  # noqa: BLE001 — identity, best effort
        return str(sh)


#: One launch at a time for collective-bearing (meshed) programs,
#: process-wide.  Multi-program collectives only compose when every
#: device sees the programs in the SAME order, so launches must not
#: interleave across threads; on the host-platform CPU backend the
#: hazard is harder still — two in-flight 8-device programs split the
#: per-device execution threads between two AllReduce rendezvous and
#: deadlock outright (each waits forever for participants the other
#: run is holding) — so there the launch also drains before the lock
#: is released.  Module-level: a process may hold several meshed
#: executors over the same devices.
_MESH_LAUNCH_LOCK = _threading.Lock()


def mesh_serialized(fn):
    """Wrap a meshed jitted program so launches serialize (and, on the
    CPU backend, complete) under ``_MESH_LAUNCH_LOCK``.  Applied at
    cache-insert time by ``FusedCache`` instances serving a placement,
    so every fused family — including the readback pack — flows
    through the one choke point."""
    drain = jax.default_backend() == "cpu"

    def call(*args, **kw):
        with _MESH_LAUNCH_LOCK:
            out = fn(*args, **kw)
            if drain:
                jax.block_until_ready(out)
            return out

    return call


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= max(n, 1).  Batch widths pad to pow2
    buckets (repeating element 0) so the compiled-program set stays
    bounded per shape — without it every distinct batch size compiles
    a fresh program and the compiles land on serving latency
    (measured: a recompile storm collapsed 32 concurrent HTTP clients
    to ~23 qps)."""
    bucket = 1
    while bucket < n:
        bucket *= 2
    return bucket


def _build(node, leaves):
    kind = node[0]
    if kind == "leaf":
        return leaves[node[1]]
    if kind == "zeros":
        return jnp.zeros_like(leaves[0])
    if kind == "or-leaves":
        acc = leaves[node[1][0]]
        for i in node[1][1:]:
            acc = jnp.bitwise_or(acc, leaves[i])
        return acc
    if kind in ("and", "or", "andnot", "xor"):
        op = {"and": jnp.bitwise_and, "or": jnp.bitwise_or,
              "xor": jnp.bitwise_xor,
              "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
              }[kind]
        acc = _build(node[1][0], leaves)
        for child in node[1][1:]:
            acc = op(acc, _build(child, leaves))
        return acc
    if kind == "not":
        return kernels.complement(_build(node[1], leaves), leaves[node[2]])
    if kind == "shift":
        return kernels.shift(_build(node[1], leaves), node[2])
    if kind == "bsi":
        _, i_plane, i_masks, i_neg, op_key = node
        cmp = bsik.range_cmp(leaves[i_plane], leaves[i_masks],
                             leaves[i_neg])
        return cmp[op_key]
    if kind == "bsi-between":
        (_, i_plane, i_lo, i_lo_neg, lo_op, i_hi, i_hi_neg, hi_op) = node
        lo = bsik.range_cmp(leaves[i_plane], leaves[i_lo],
                            leaves[i_lo_neg])[lo_op]
        hi = bsik.range_cmp(leaves[i_plane], leaves[i_hi],
                            leaves[i_hi_neg])[hi_op]
        return jnp.bitwise_and(lo, hi)
    raise AssertionError(f"bad node {node!r}")


def shift_leaves(node, offset: int):
    """Re-index a plan tree's leaf references by ``offset`` — used to
    concatenate several plans' leaf lists into one batched program."""
    kind = node[0]
    if kind == "leaf":
        return ("leaf", node[1] + offset)
    if kind == "zeros":
        return node
    if kind == "or-leaves":
        return ("or-leaves", tuple(i + offset for i in node[1]))
    if kind in ("and", "or", "andnot", "xor"):
        return (kind, tuple(shift_leaves(c, offset) for c in node[1]))
    if kind == "not":
        return ("not", shift_leaves(node[1], offset), node[2] + offset)
    if kind == "shift":
        return ("shift", shift_leaves(node[1], offset), node[2])
    if kind == "bsi":
        return ("bsi", node[1] + offset, node[2] + offset,
                node[3] + offset, node[4])
    if kind == "bsi-between":
        return ("bsi-between", node[1] + offset, node[2] + offset,
                node[3] + offset, node[4], node[5] + offset,
                node[6] + offset, node[7])
    raise AssertionError(f"bad node {node!r}")


class PingPong:
    """Retired-output pool for donated dispatch chains (r17).

    The chain families (selected counts, rowcounts batches, the
    window's readback pack) pass a RETIRED output buffer back as a
    donated scratch argument, so consecutive dispatches reuse its
    device memory instead of allocating a fresh output per window.
    Depth 2 per (shape, dtype) — ping-pong — so window N can dispatch
    against one buffer while window N-1's readback still owns the
    other; a buffer is only retired AFTER its host read completed
    (every consumer copies out), so donating it can never clobber
    bytes a reader still wants.

    ``scratch`` POPS (the same buffer must never reach two concurrent
    dispatches); returns None when no retired buffer of that shape
    exists — callers then run the un-donated program variant.  The
    pool is bounded (``MAX_SHAPES`` shapes LRU) so churning window
    shapes cannot pin arbitrary device memory."""

    MAX_SHAPES = 8
    DEPTH = 2

    def __init__(self):
        import threading
        from collections import OrderedDict
        self._pools: "OrderedDict[tuple, list]" = OrderedDict()
        self._lock = threading.Lock()

    def scratch(self, shape: tuple, dtype) -> "jax.Array | None":
        key = (tuple(shape), str(dtype))
        with self._lock:
            pool = self._pools.get(key)
            if pool:
                self._pools.move_to_end(key)
                return pool.pop()
        return None

    def retire(self, arr) -> None:
        """Hand a read-back output's device buffer to the pool.  The
        caller must not touch ``arr`` again — a later dispatch may
        donate (invalidate) it."""
        if arr is None:
            return
        key = (tuple(arr.shape), str(arr.dtype))
        with self._lock:
            pool = self._pools.setdefault(key, [])
            self._pools.move_to_end(key)
            if len(pool) < self.DEPTH:
                pool.append(arr)
            while len(self._pools) > self.MAX_SHAPES:
                self._pools.popitem(last=False)


def _pad_skeleton(prog: tuple) -> tuple:
    """A postfix program's STATIC opcode skeleton, NOP-padded to the
    pow2 length bucket — the one bucketing rule every tree entry
    (solo, window item) keys on, so the paths cannot drift apart.
    STATIC ops (Shift/Limit, r23) keep their argument in the skeleton
    as an ``(op, arg)`` entry: the argument is compiled structure
    (like the fused "shift" node's ``n``), so it must live in the
    program key, not the traced operands."""
    p_pad = pow2_bucket(max(1, len(prog)))
    return (tuple((op, arg) if op in kernels.TREE_STATIC_OPS else op
                  for op, arg in prog)
            + (kernels.TREE_NOP,) * (p_pad - len(prog)))


def _pad_extras(extras: tuple) -> tuple:
    """Extra-operand tuple padded to its pow2 bucket by repeating
    element 0 (pad lanes are never addressed by programs)."""
    if not extras:
        return ()
    e_pad = pow2_bucket(len(extras))
    return tuple(extras) + (extras[0],) * (e_pad - len(extras))


class FusedCache:
    """structure key -> jitted program, LRU-bounded: structure keys can
    embed user-controlled constants (e.g. Shift n), so the program set
    must not grow without bound.  One instance per executor.

    Concurrency (r6): the hot path is LOCK-FREE — a plain-dict lookup
    plus a recency-stamp write, both GIL-atomic — because the previous
    single lock was taken on every cached-program hit by every serving
    thread (32 streams × several programs per request).  Compilation
    serializes PER KEY (two threads racing the same new shape compile
    it once; different shapes compile concurrently); the global lock
    guards only insertion and eviction."""

    MAX_PROGRAMS = 256

    def __init__(self, stats=None, mesh_guard: bool = False,
                 ledger=None, flight=None, kernel_tier: str = "xla"):
        import os
        import threading
        from pilosa_tpu.exec._lru import Stamps
        from pilosa_tpu.obs import NULL_FLIGHT, NULL_LEDGER, NopStats
        # mesh_guard (r21): this cache compiles collective-bearing
        # programs (its executor serves a placement), so every program
        # is wrapped in ``mesh_serialized`` at insert time — launches
        # stay cross-device-ordered and the CPU backend's rendezvous
        # deadlock (see _MESH_LAUNCH_LOCK) cannot form.
        self._mesh_guard = mesh_guard
        self._programs: dict = {}     # key -> jitted fn (GIL-atomic reads)
        self._idx_cache: dict = {}    # padded slot tuple -> device int32
        self._stamps = Stamps()       # approx-LRU recency (lock-free touch)
        self._lock = threading.Lock()       # insert / evict only
        self._compiling: dict = {}          # key -> per-key compile lock
        self._threading = threading
        # program-set telemetry (r14): built/evicted counters plus the
        # fused_program_count scrape-time gauge make a recompile storm
        # (the class that once collapsed 32 clients to ~23 qps, see
        # pow2_bucket) visible on /metrics instead of only as latency
        self._stats = stats or NopStats()
        # compile observability (r19): per-family compile seconds with
        # first-compile trace exemplars land in the cost ledger, and
        # every compile is a flight-recorder event — a recompile storm
        # shows up on the incident timeline with the shapes that
        # caused it, not just as a climbing built counter
        self._ledger = ledger or NULL_LEDGER
        self.flight = flight or NULL_FLIGHT
        # kernel tier (r24): "pallas" routes the hottest fused families
        # (selcounts[-delta/-loop], rowcounts-batch/-delta, count-batch)
        # through the Pallas/Mosaic kernels; "xla" (default) is today's
        # proven path and stays the correctness oracle + the governor's
        # degraded fallback.  Real pallas selection gates on the TPU
        # backend at runtime — on any other backend the knob silently
        # falls back to XLA (counted) unless the TEST-ONLY interpret
        # escape hatch (PILOSA_PALLAS_INTERPRET=1) is set, which runs
        # the same kernels through the pallas interpreter on CPU so
        # tier-1 can pin bit-exactness without a device.
        self.kernel_tier = kernel_tier
        self._pallas_interpret = False
        tier = "xla"
        if kernel_tier == "pallas":
            if jax.default_backend() == "tpu":
                tier = "pallas"
            elif os.environ.get("PILOSA_PALLAS_INTERPRET",
                                "") not in ("", "0", "false"):
                tier = "pallas"
                self._pallas_interpret = True
            else:
                self._stats.count("pallas_fallback_total", 1,
                                  reason="backend")
        self._tier = tier
        # tier token appended to pallas-built program keys (like
        # sharding_key: same shape, different tier = different program);
        # xla keys stay byte-identical to the pre-tier key space
        self._tier_tok = ((("pallas-interpret" if self._pallas_interpret
                            else "pallas"),) if tier == "pallas" else ())
        self._pallas_bad: set = set()   # (family, shape) lowering fails
        self.pallas_fallbacks = 0

    @property
    def effective_tier(self) -> str:
        """The tier actually serving: "xla", "pallas", or
        "pallas-interpret" (the test escape hatch)."""
        if self._tier == "pallas":
            return ("pallas-interpret" if self._pallas_interpret
                    else "pallas")
        return "xla"

    @property
    def program_count(self) -> int:
        return len(self._programs)

    # -- kernel-tier routing (r24) ---------------------------------------

    def _pallas_ok(self, sig) -> bool:
        return self._tier == "pallas" and sig not in self._pallas_bad

    def _pallas_failed(self, sig, exc) -> None:
        self._pallas_bad.add(sig)
        self.pallas_fallbacks += 1
        self._stats.count("pallas_fallback_total", 1, reason="lowering")
        self.flight.record("pallas_fallback", str(sig[0]),
                           type(exc).__name__)

    def _tier_run(self, sig, dispatch):
        """Dispatch through the pallas tier when it covers ``sig`` (a
        ``(family, plane shape)`` pair); a Mosaic lowering failure
        marks the shape bad, counts ``pallas_fallback_total``, and
        silently re-dispatches the XLA-tier program."""
        if self._pallas_ok(sig):
            try:
                return dispatch(True)
            except Unfusable:
                raise
            except Exception as e:  # noqa: BLE001 — lowering/compile
                self._pallas_failed(sig, e)
        return dispatch(False)

    def _sel_kernel(self, pallas: bool, sorted_idx: bool):
        """The selected-row gather base kernel for one tier: ``(plane,
        idx) → int32[S, N]``."""
        if pallas:
            from pilosa_tpu.engine import pallas_kernels
            interp = self._pallas_interpret
            return lambda p, ix: pallas_kernels.selected_row_counts(
                p, ix, interpret=interp)
        return lambda p, ix: kernels.selected_row_counts(
            p, ix, sorted_idx=sorted_idx)

    def _rc_kernel(self, pallas: bool):
        """The whole-plane row-counts base kernel for one tier:
        ``(plane[, filter]) → int32[S, R]``."""
        if pallas:
            from pilosa_tpu.engine import pallas_kernels
            interp = self._pallas_interpret
            return lambda p, fw=None: pallas_kernels.row_counts(
                p, fw, interpret=interp)
        return kernels.row_counts

    def _cnt_kernel(self, pallas: bool):
        """The whole-bitmap count kernel for one tier.  The pallas form
        is 2D-only; plan trees that fold to other ranks (zeros nodes
        over BSI leaves) stay on the XLA reduce inside the same
        program."""
        if not pallas:
            return kernels.count
        from pilosa_tpu.engine import pallas_kernels
        interp = self._pallas_interpret

        def cnt(words):
            if words.ndim != 2:
                return kernels.count(words)
            return pallas_kernels.count(words, interpret=interp)
        return cnt

    def _get_fast(self, key):
        fn = self._programs.get(key)
        if fn is not None:
            self._stamps.touch(key)
        return fn

    def _insert(self, key, fn) -> None:
        evicted = 0
        with self._lock:
            self._programs[key] = fn
            self._stamps.insert(key)
            if len(self._programs) > self.MAX_PROGRAMS:
                excess = len(self._programs) - self.MAX_PROGRAMS
                stamps = self._stamps.snapshot()
                for k, _ in sorted(stamps, key=lambda kv: kv[1])[:excess]:
                    if k == key:
                        continue
                    if self._programs.pop(k, None) is not None:
                        evicted += 1
                    self._stamps.pop(k)
                    self._compiling.pop(k, None)
            self._stamps.cleanup(self._programs)
        self._stats.count("fused_programs_built_total", 1)
        if evicted:
            self._stats.count("fused_programs_evicted_total", evicted)

    @staticmethod
    def _family(key) -> str:
        """The program key's fused-family tag for compile attribution:
        the head tuple's leading string (``"selcounts"``,
        ``"tree-item"``, a plan node kind, ...) or the trailing want /
        batch tag — every form is a BOUNDED vocabulary, so the
        ``fused_compile_seconds{family}`` series set stays small."""
        try:
            head = key[0]
            if isinstance(head, tuple) and head \
                    and isinstance(head[0], str):
                return head[0]
            tail = key[-1]
            if isinstance(tail, str):
                return tail
        except (IndexError, TypeError):
            pass
        return "fused"

    def _timed_first_call(self, key, fn):
        """jax.jit is LAZY — tracing + XLA compilation happen on the
        program's FIRST invocation, not at jit() time — so compile
        seconds are measured by wrapping exactly that call.  After the
        first call the raw fn replaces the wrapper in the program dict
        (GIL-atomic), so the steady-state hit path pays nothing."""
        family = self._family(key)
        once = []

        def first(*args, **kw):
            t0 = _time.perf_counter()
            out = fn(*args, **kw)
            if not once:
                once.append(True)
                dt = _time.perf_counter() - t0
                if self._programs.get(key) is first:
                    self._programs[key] = fn  # un-wrap: off hot path
                self._ledger.note_compile(family, dt, first=True)
                self.flight.record("compile", family, "", dt)
            return out

        return first

    def _cached(self, key, build, donate: tuple = ()):
        fn = self._get_fast(key)
        if fn is not None:
            return fn
        # per-structure-key compile lock: setdefault is atomic, so two
        # racers share one lock and the loser reuses the winner's program
        lock = self._compiling.setdefault(key, self._threading.Lock())
        with lock:
            fn = self._programs.get(key)
            if fn is None:
                # ``donate``: argument positions donated to the
                # program (the r17 ping-pong scratch slots) — XLA
                # aliases the output onto the donated buffer, so a
                # chained dispatch writes into the retired output of
                # two windows ago instead of allocating.  Donation is
                # part of the program, hence part of the key.
                fn = jax.jit(build(), donate_argnums=donate)
                if self._mesh_guard:
                    fn = mesh_serialized(fn)
                fn = self._timed_first_call(key, fn)
                self._insert(key, fn)
        return fn

    def run(self, node, leaves, want: str):
        """Execute a planned tree: ``want`` is "words" (bitmap) or
        "count" (fused popcount-reduce scalar)."""
        key = (node, sharding_key(leaves[0]) if leaves else None, want)

        def build():
            if want == "count":
                # per-shard int32 counts; the caller finishes the tiny
                # cross-shard sum in int64 on host (engine int32 policy)
                def program(*ls):
                    return kernels.count(_build(node, ls))
            else:
                def program(*ls):
                    return _build(node, ls)
            return program

        return self._cached(key, build)(*leaves)

    def run_count_batch(self, nodes: tuple, leaves, scratch=None):
        """K Count trees in ONE program: returns int32[K, n_shards] —
        one dispatch and one host read amortize fixed per-read costs
        across every Count in the request (critical on transports with
        a per-read floor; see BASELINE.md).  ``scratch`` (r17): a
        retired int32[K, n_shards] output to donate for the
        chained-dispatch form."""
        n_leaves = len(leaves)
        out_shape = (len(nodes), leaves[0].shape[0])
        donate_ok = (scratch is not None
                     and tuple(scratch.shape) == out_shape)

        def dispatch(pallas: bool):
            cnt = self._cnt_kernel(pallas)

            def build():
                def program(*ls):
                    return jnp.stack([cnt(_build(n, ls))
                                      for n in nodes])
                return program
            tok = self._tier_tok if pallas else ()
            key = ((nodes, donate_ok, sharding_key(leaves[0])) + tok,
                   "count-batch")
            if donate_ok:
                return self._cached(key, build,
                                    donate=(n_leaves,))(*leaves, scratch)
            return self._cached(key, build)(*leaves)

        return self._tier_run(("count", leaves[0].shape), dispatch)

    def run_rowcounts_batch(self, flags: tuple, leaves, scratch=None):
        """K whole-plane row-count items (same plane shape) in ONE
        program: per item, ``row_counts`` over the plane (AND a filter
        bitmap when flagged) reduced over the shard axis in int32 —
        exact while n_shards·2^20 < 2^31; callers gate on that.
        ``flags[k]`` = item k has a filter leaf; leaves alternate
        plane[, filter] per item.  Returns int32[K, R_pad]: one stacked
        array = one read for the whole coalescing window (the dense
        TopN / same-field count-batch serving spine).  ``scratch``
        (r17): a retired int32[K, R_pad] output to donate for the
        chained-dispatch form."""
        n_leaves = len(leaves)
        out_shape = (len(flags), leaves[0].shape[-2])
        donate_ok = (scratch is not None
                     and tuple(scratch.shape) == out_shape)

        def dispatch(pallas: bool):
            rc = self._rc_kernel(pallas)

            def build():
                def program(*ls):
                    rows = []
                    i = 0
                    for has_filter in flags:
                        plane = ls[i]
                        flt = ls[i + 1] if has_filter else None
                        i += 2 if has_filter else 1
                        rows.append(jnp.sum(rc(plane, flt),
                                            axis=0, dtype=jnp.int32))
                    return jnp.stack(rows)
                return program
            tok = self._tier_tok if pallas else ()
            key = (flags, leaves[0].shape, sharding_key(leaves[0]),
                   donate_ok) + tok + ("rowcounts-batch",)
            # (donate flag inside the key, tag kept LAST — callers
            # introspect the program set by trailing tag)
            if donate_ok:
                return self._cached(key, build,
                                    donate=(n_leaves,))(*leaves, scratch)
            return self._cached(key, build)(*leaves)

        return self._tier_run(("rowcounts", leaves[0].shape), dispatch)

    # bounded device-resident slot-index cache (r17 solo fast lane):
    # a repeating solo query shape re-dispatches the same slot tuple
    # every request — keep its padded int32 operand resident so a
    # chained dispatch never re-uploads (re-lays-out) the indices
    _IDX_CACHE_MAX = 256

    def _slot_idx(self, padded: tuple) -> jax.Array:
        idx = self._idx_cache.get(padded)
        if idx is None:
            idx = jnp.asarray(padded, dtype=jnp.int32)
            with self._lock:
                self._idx_cache[padded] = idx
                while len(self._idx_cache) > self._IDX_CACHE_MAX:
                    self._idx_cache.pop(next(iter(self._idx_cache)))
        return idx

    def run_selected_counts(self, plane, slots, delta=None,
                            scratch=None,
                            sorted_idx: bool = False) -> jax.Array:
        """N selected-row Counts over one resident plane in ONE
        program: gather the requested rows, popcount, reduce the shard
        axis on device -> int32[N] (callers gate on the int32-exact
        shard bound, like :meth:`run_rowcounts_batch`).  ``slots`` are
        plane row indices (already slot-resolved); the width pads to a
        pow2 bucket by repeating slot 0 so the program set stays
        bounded per (plane shape, width bucket) — the slot VALUES are
        a traced int32 operand, so any row selection of the same width
        bucket reuses one executable.  Returns the device array
        un-read: the batcher packs it into the window's single
        readback.

        ``delta`` (an ``ingest.delta.DeltaOverlay``) merges the
        plane's pending write cells at dispatch time (base⊕delta):
        the overlay arrays are traced operands, so one program serves
        any overlay of the same pow2 cell bucket.

        ``scratch`` (r17): a retired int32[bucket] output buffer to
        donate — the chained-dispatch form (see :class:`PingPong`).
        ``sorted_idx`` statically promises ascending slot order
        (ascending-stride gather); the batcher's slot unions and the
        solo fast lane sort before calling."""
        bucket = pow2_bucket(len(slots))
        # pad with the LAST slot, not slot 0: keeps the padded tuple
        # non-decreasing when the live slots are sorted
        padded = tuple(slots) + (slots[-1],) * (bucket - len(slots))
        idx = self._slot_idx(padded)
        donate_ok = (scratch is not None
                     and tuple(scratch.shape) == (bucket,))
        if delta is not None:
            def dispatch(pallas: bool):
                key = self._selcounts_delta_key(
                    plane.shape, sharding_key(plane), bucket,
                    delta.rows.shape[0], sorted_idx, donate_ok, pallas)
                build = self._selcounts_delta_build(sorted_idx, pallas)
                args = (plane, idx, delta.rows, delta.words, delta.vals)
                if donate_ok:
                    return self._cached(key, build,
                                        donate=(5,))(*args, scratch)
                return self._cached(key, build)(*args)

            return self._tier_run(("selcounts", plane.shape), dispatch)

        def dispatch(pallas: bool):
            key = self._selcounts_key(plane.shape, sharding_key(plane),
                                      bucket, sorted_idx, donate_ok,
                                      pallas)
            build = self._selcounts_build(sorted_idx, pallas)
            if donate_ok:
                return self._cached(key, build,
                                    donate=(2,))(plane, idx, scratch)
            return self._cached(key, build)(plane, idx)

        return self._tier_run(("selcounts", plane.shape), dispatch)

    # selcounts key/build helpers: SHARED between the serving path and
    # the warm-up ladder (warm_delta_ladder), so a warmed program IS
    # the serving program — the two can never drift apart on key shape

    def _selcounts_key(self, shape, shard, bucket, sorted_idx,
                       donate_ok, pallas: bool):
        tok = self._tier_tok if pallas else ()
        return (("selcounts", shape, shard, bucket, sorted_idx,
                 donate_ok) + tok, "count")

    def _selcounts_build(self, sorted_idx: bool, pallas: bool):
        sel = self._sel_kernel(pallas, sorted_idx)

        def build():
            def program(p, ix, *sc):
                return jnp.sum(sel(p, ix), axis=0, dtype=jnp.int32)
            return program
        return build

    def _selcounts_delta_key(self, shape, shard, bucket, dbucket,
                             sorted_idx, donate_ok, pallas: bool):
        tok = self._tier_tok if pallas else ()
        return (("selcounts-delta", shape, shard, bucket, dbucket,
                 sorted_idx, donate_ok) + tok, "count")

    def _selcounts_delta_build(self, sorted_idx: bool, pallas: bool):
        from pilosa_tpu.ingest.delta import adjusted_selected_counts
        sel = self._sel_kernel(pallas, sorted_idx) if pallas else None

        def build():
            def program(p, ix, dr, dw, dv, *sc):
                return adjusted_selected_counts(
                    p, ix, dr, dw, dv, sorted_idx=sorted_idx,
                    selected_fn=sel)
            return program
        return build

    def run_selected_counts_loop(self, planes: tuple, slot_lists: tuple,
                                 deltas: tuple,
                                 sorted_idx: bool = True) -> jax.Array:
        """A window's same-shape selected-count sequence in ONE jitted
        program (r24 on-device dispatch loops): K (plane, slots[,
        overlay]) items collapse to one enqueue + one packed readback
        instead of K dispatches.  Returns int32[K_pad, bucket]; pad
        lanes repeat item 0 (rows) and each item's last slot (columns),
        so callers slice ``[j, :len(slots_j)]``.

        Two forms behind one key family: when every item reads the
        SAME resident plane (interleaved-ingest overlay snapshots),
        the program is a true ``lax.scan`` over the stacked slot /
        overlay operands — the pattern ``engine/bsi.py`` proves for
        percentile; distinct planes enter as separate traced operands
        (stacking resident planes would copy HBM) and the chain
        unrolls inside the jit, which still costs one enqueue.  The
        batcher's loop-fusion rule guarantees one overlay pow2 bucket
        (or none) across items."""
        k = len(planes)
        k_pad = pow2_bucket(k)
        bucket = pow2_bucket(max(len(sl) for sl in slot_lists))
        padded = [tuple(sl) + (sl[-1],) * (bucket - len(sl))
                  for sl in slot_lists]
        padded += [padded[0]] * (k_pad - k)
        idx = jnp.stack([self._slot_idx(p) for p in padded])
        planes = tuple(planes) + (planes[0],) * (k_pad - k)
        deltas = tuple(deltas) + (deltas[0],) * (k_pad - k)
        has_delta = deltas[0] is not None
        dbucket = deltas[0].rows.shape[0] if has_delta else 0
        same_plane = all(p is planes[0] for p in planes)
        shape, shard = planes[0].shape, sharding_key(planes[0])

        def dispatch(pallas: bool):
            from pilosa_tpu.ingest.delta import adjusted_selected_counts
            tok = self._tier_tok if pallas else ()
            key = (("selcounts-loop", shape, shard, k_pad, bucket,
                    dbucket, sorted_idx, same_plane) + tok, "count")
            sel = self._sel_kernel(pallas, sorted_idx)
            sel_fn = sel if pallas else None
            if same_plane and has_delta:
                drs = jnp.stack([d.rows for d in deltas])
                dws = jnp.stack([d.words for d in deltas])
                dvs = jnp.stack([d.vals for d in deltas])

                def build():
                    def program(p, ix, dr, dw, dv):
                        def step(c, xs):
                            ixj, drj, dwj, dvj = xs
                            return c, adjusted_selected_counts(
                                p, ixj, drj, dwj, dvj,
                                sorted_idx=sorted_idx,
                                selected_fn=sel_fn)
                        _, outs = jax.lax.scan(step, 0,
                                               (ix, dr, dw, dv))
                        return outs
                    return program
                return self._cached(key, build)(planes[0], idx,
                                                drs, dws, dvs)
            if same_plane:
                def build():
                    def program(p, ix):
                        def step(c, ixj):
                            return c, jnp.sum(sel(p, ixj), axis=0,
                                              dtype=jnp.int32)
                        _, outs = jax.lax.scan(step, 0, ix)
                        return outs
                    return program
                return self._cached(key, build)(planes[0], idx)
            if has_delta:
                def build():
                    def program(ix, *rest):
                        ps = rest[:k_pad]
                        outs = []
                        for j in range(k_pad):
                            dr, dw, dv = rest[k_pad + 3 * j:
                                              k_pad + 3 * j + 3]
                            outs.append(adjusted_selected_counts(
                                ps[j], ix[j], dr, dw, dv,
                                sorted_idx=sorted_idx,
                                selected_fn=sel_fn))
                        return jnp.stack(outs)
                    return program
                args = [idx] + list(planes)
                for d in deltas:
                    args += [d.rows, d.words, d.vals]
                return self._cached(key, build)(*args)

            def build():
                def program(ix, *ps):
                    return jnp.stack([
                        jnp.sum(sel(ps[j], ix[j]), axis=0,
                                dtype=jnp.int32)
                        for j in range(k_pad)])
                return program
            return self._cached(key, build)(idx, *planes)

        return self._tier_run(("selcounts", shape), dispatch)

    def run_rowcounts_delta(self, plane, delta, filter_words=None,
                            reduce: bool = True) -> jax.Array:
        """Whole-plane per-row counts of base⊕delta in ONE program:
        the clean ``row_counts`` scan of the immutable base plus a
        gather + scatter-add adjustment over the overlay cells —
        int32[R_pad] (``reduce``, callers gate on the int32-exact
        shard bound) or int32[S, R_pad].  Overlay arrays are traced
        operands; the program set is bounded per (plane shape, overlay
        bucket, filtered, reduce)."""
        has_filter = filter_words is not None

        def dispatch(pallas: bool):
            key = self._rowcounts_delta_key(
                plane.shape, sharding_key(plane), delta.rows.shape[0],
                has_filter, reduce, pallas)
            build = self._rowcounts_delta_build(has_filter, reduce,
                                                pallas)
            args = (plane, delta.rows, delta.words, delta.vals)
            if has_filter:
                args += (filter_words,)
            return self._cached(key, build)(*args)

        return self._tier_run(("rowcounts", plane.shape), dispatch)

    def _rowcounts_delta_key(self, shape, shard, dbucket, has_filter,
                             reduce, pallas: bool):
        tok = self._tier_tok if pallas else ()
        return (("rowcounts-delta", shape, shard, dbucket, has_filter,
                 reduce) + tok, "count")

    def _rowcounts_delta_build(self, has_filter: bool, reduce: bool,
                               pallas: bool):
        from pilosa_tpu.ingest.delta import adjusted_row_counts
        rc = self._rc_kernel(pallas) if pallas else None

        def build():
            if has_filter:
                def program(p, dr, dw, dv, fw):
                    return adjusted_row_counts(p, dr, dw, dv, fw,
                                               reduce_shards=reduce,
                                               row_counts_fn=rc)
            else:
                def program(p, dr, dw, dv):
                    return adjusted_row_counts(p, dr, dw, dv, None,
                                               reduce_shards=reduce,
                                               row_counts_fn=rc)
            return program
        return build

    # -- compile-ladder warm-up (r24) -------------------------------------

    #: slot width bucket the warmer pre-compiles for the selected-count
    #: delta family: bucket 1 is the post-ingest first-serve shape (a
    #: solo Count(Row) through the fast lane or a width-1 window)
    WARM_SLOT_BUCKET = 1

    def _warm_insert(self, key, build, avatars: tuple,
                     donate: tuple = ()):
        """AOT-compile ONE program from shape avatars and insert it
        pre-warmed: ``jit().lower().compile()`` runs tracing + XLA
        compilation HERE (off the serving path) instead of lazily on
        first call, and the Compiled object lands directly in the
        program dict (lower/compile does not populate jit's dispatch
        cache).  Returns compile seconds, or None when the key was
        already cached."""
        if self._get_fast(key) is not None:
            return None
        lock = self._compiling.setdefault(key, self._threading.Lock())
        with lock:
            if key in self._programs:
                return None
            t0 = _time.perf_counter()
            fn = jax.jit(build(), donate_argnums=donate)
            fn = fn.lower(*avatars).compile()
            dt = _time.perf_counter() - t0
            if self._mesh_guard:
                fn = mesh_serialized(fn)
            self._insert(key, fn)
        return dt

    def _warm_jobs(self, shape: tuple, overlay_bucket: int) -> list:
        """The delta-aware program ladder rungs for one resident plane
        shape × one pow2 overlay bucket: the serving forms a first
        post-ingest query hits (whole-plane rowcounts-delta with and
        without a filter; width-1 selected-counts-delta, donated and
        not).  Keys/builds come from the SAME helpers the serving path
        uses."""
        sds = jax.ShapeDtypeStruct
        s, _r, w = shape
        shard = None  # the warmer only runs un-placed (single-device)
        plane_av = sds(tuple(shape), jnp.uint32)
        flt_av = sds((s, w), jnp.uint32)
        dr = sds((overlay_bucket,), jnp.int32)
        dw = sds((overlay_bucket,), jnp.int32)
        dv = sds((overlay_bucket,), jnp.uint32)
        jobs = []
        sig = ("rowcounts", tuple(shape))
        pall = self._pallas_ok(sig)
        for has_filter in (False, True):
            jobs.append((
                sig,
                self._rowcounts_delta_key(tuple(shape), shard,
                                          overlay_bucket, has_filter,
                                          True, pall),
                self._rowcounts_delta_build(has_filter, True, pall),
                (plane_av, dr, dw, dv) + ((flt_av,) if has_filter
                                          else ()),
                ()))
        sig = ("selcounts", tuple(shape))
        pall = self._pallas_ok(sig)
        b = self.WARM_SLOT_BUCKET
        ix_av, scr_av = sds((b,), jnp.int32), sds((b,), jnp.int32)
        for donate_ok in (False, True):
            jobs.append((
                sig,
                self._selcounts_delta_key(tuple(shape), shard, b,
                                          overlay_bucket, True,
                                          donate_ok, pall),
                self._selcounts_delta_build(True, pall),
                (plane_av, ix_av, dr, dw, dv) + ((scr_av,)
                                                 if donate_ok else ()),
                (5,) if donate_ok else ()))
        return jobs

    def warm_delta_ladder(self, shape: tuple,
                          overlay_bucket: int) -> tuple[int, float]:
        """Pre-compile the delta-aware serving programs for one plane
        shape × pow2 overlay bucket (r24 compile-ladder warm-up) —
        returns (programs compiled, compile seconds).  A pallas-tier
        lowering failure during warm-up marks the shape bad exactly
        like a serving-path failure and the ladder re-warms the XLA
        fallback programs, so the first post-ingest serve stays
        compile-free either way."""
        n, secs = 0, 0.0
        retry = False
        for sig, key, build, avatars, donate in self._warm_jobs(
                shape, overlay_bucket):
            try:
                dt = self._warm_insert(key, build, avatars, donate)
            except Exception as e:  # noqa: BLE001 — lowering/compile
                if self._pallas_ok(sig):
                    self._pallas_failed(sig, e)
                    retry = True
                continue
            if dt is not None:
                n += 1
                secs += dt
        if retry:
            n2, s2 = self.warm_delta_ladder(shape, overlay_bucket)
            n, secs = n + n2, secs + s2
        return n, secs

    def _tree_cached(self, key, build):
        """``_cached`` + tree-family build telemetry: a climbing
        ``tree_programs_built_total`` under a REPEATING mix means the
        skeleton/bucket keying is not containing the program set (the
        recompile-storm class, r16 runbook)."""
        built = []

        def counting_build():
            built.append(True)
            return build()

        fn = self._cached(key, counting_build)
        if built:
            self._stats.count("tree_programs_built_total", 1)
        return fn

    def _tree_gather(self, plane, slots: tuple, delta) -> jax.Array:
        """The window's ONE memory pass over the plane: gather the
        union of requested row slots (traced int32, pow2-width
        bucket) and overlay pending delta cells (base⊕delta) →
        uint32[G_pad, S, W].  Every item program in the window reads
        from this shared array instead of touching the plane again."""
        g = len(slots)
        g_pad = pow2_bucket(max(1, g))
        padded = (tuple(slots) or (0,)) + \
            ((slots[0] if slots else 0),) * (g_pad - max(1, g))
        has_delta = delta is not None
        key = (("tree-gather", plane.shape, sharding_key(plane), g_pad,
                delta.rows.shape[0] if has_delta else None), "words")

        def build():
            def program(p, ix, *dl):
                sel = jnp.take(p, ix, axis=-2)       # [S, G_pad, W]
                if has_delta:
                    from pilosa_tpu.ingest.delta import \
                        overlay_gathered_rows
                    sel = overlay_gathered_rows(sel, ix, *dl,
                                                p.shape[-2])
                return jnp.moveaxis(sel, -2, 0)      # [G_pad, S, W]
            return program

        args = (plane, self._slot_idx(tuple(padded)))
        if has_delta:
            args += (delta.rows, delta.words, delta.vals)
        return self._tree_cached(key, build)(*args)

    def _tree_item(self, rows, ex_stack, prog: tuple, want: str):
        """One tree's postfix program against the window's gathered
        rows: the cache key is the item's opcode SKELETON (NOP-padded
        to a pow2 length bucket) — per-QUERY-shape, never
        per-window-combination — while the push args (which gathered
        row / which extra each push reads) stay traced, so any tree
        of the same skeleton reuses one compiled program.  ``want``
        "count" → int32[1] total (shard axis reduced on device);
        "words" → uint32[S, W]."""
        skeleton = _pad_skeleton(prog)
        row_args = [arg for op, arg in prog
                    if op == kernels.TREE_PUSH]
        ex_args = [arg for op, arg in prog
                   if op == kernels.TREE_PUSHX]
        has_ex = ex_stack is not None
        key = (("tree-item", rows.shape, sharding_key(rows),
                ex_stack.shape if has_ex else None, skeleton), want)

        def build():
            def program(r, ra, xa, *ex):
                words = kernels.tree_fold(
                    r, skeleton, ra, ex[0] if has_ex else None, xa)
                if want == "words":
                    return words
                return jnp.sum(kernels.count(words),
                               dtype=jnp.int32)[None]
            return program

        args = (rows,
                self._slot_idx(tuple(row_args) or (0,)),
                self._slot_idx(tuple(ex_args) or (0,)))
        if has_ex:
            args += (ex_stack,)
        return self._tree_cached(key, build)(*args)

    def _tree_solo(self, plane, slots: tuple, prog: tuple,
                   extras: tuple, delta, want: str):
        """A SINGLE tree in one end-to-end program: each push reads
        its row STRAIGHT off the plane (a traced dynamic index XLA
        fuses into the bitwise chain — no intermediate gathered
        array), the delta overlay merges row-wise in the same chain,
        and counts popcount-reduce before leaving the device.  The
        solo serving path pays one round trip and one pass over
        exactly the rows the tree touches.  Push args carry SLOT
        values directly; the cache key is the skeleton + pow2 arg
        buckets, so any same-shape tree reuses the program."""
        extras = _pad_extras(extras)
        skeleton = _pad_skeleton(prog)
        # push args carry the slot VALUES (traced); the slots tuple's
        # role here is only dedup bookkeeping for the batcher union
        row_args = [slots[arg] for op, arg in prog
                    if op == kernels.TREE_PUSH]
        ex_args = [arg for op, arg in prog if op == kernels.TREE_PUSHX]
        has_delta = delta is not None
        key = (("tree-solo", plane.shape, sharding_key(plane),
                len(extras), skeleton,
                delta.rows.shape[0] if has_delta else None), want)

        def build():
            def program(p, ra, xa, *rest):
                if has_delta:
                    dr, dw, dv = rest[:3]
                    ex_arrays = rest[3:]
                else:
                    ex_arrays = rest
                r_pad = p.shape[-2]

                def row(slot):
                    val = jax.lax.dynamic_index_in_dim(
                        p, jnp.clip(slot, 0, r_pad - 1), p.ndim - 2,
                        keepdims=False)              # [S, W]
                    if has_delta:
                        from pilosa_tpu.ingest.delta import overlay_row
                        val = overlay_row(val, slot, dr, dw, dv, r_pad)
                    return val

                ex = jnp.stack(ex_arrays) if ex_arrays else None
                zero = jnp.zeros(p.shape[:-2] + p.shape[-1:],
                                 jnp.uint32)
                words = kernels.tree_fold(row, skeleton, ra, ex, xa,
                                          zero=zero)
                if want == "words":
                    return words
                return jnp.sum(kernels.count(words),
                               dtype=jnp.int32)[None]
            return program

        # push/extra args ride the device-resident idx cache: a
        # repeating solo tree shape re-binds ZERO operands per dispatch
        # (the pre-bound chain the r17 fast lane rides)
        args = (plane,
                self._slot_idx(tuple(row_args) or (0,)),
                self._slot_idx(tuple(ex_args) or (0,)))
        if has_delta:
            args += (delta.rows, delta.words, delta.vals)
        args += tuple(extras)
        return self._tree_cached(key, build)(*args)

    def _tree_program(self, plane, slots: tuple, progs: tuple,
                      extras: tuple, delta, want: str):
        """Shared assembly for the whole-tree entries (r16 tentpole).
        A single tree (the solo path, and every "words" call) fuses
        end-to-end into one program.  A multi-item window splits into
        ONE gather pass over the plane (slot union, pow2-width
        bucket, delta overlay merged in-program) plus one cached
        program per item SKELETON reading the gathered rows, with the
        item outputs packed into one device array so the window still
        costs a single readback.  Splitting gather from items keeps
        the compiled-program key space per-query-shape: a window key
        spanning every member's structure would compile one program
        per item COMBINATION, which collapsed a 4-way diverse mix to
        ~18 qps on CPU (measured) — the recompile-storm class.

        ``progs``' PUSH args address the ``slots`` union and PUSHX
        args the ``extras`` tuple (see ``exec.tree.assemble_items``)."""
        if len(progs) == 1:
            return self._tree_solo(plane, slots, progs[0], extras,
                                   delta, want)
        rows = self._tree_gather(plane, slots, delta)
        padded = _pad_extras(extras)
        ex_stack = jnp.stack(padded) if padded else None
        outs = tuple(self._tree_item(rows, ex_stack, prog, want)
                     for prog in progs)
        return self.run_readback_pack(outs)

    def run_tree_counts(self, plane, slots: tuple, progs: tuple,
                        extras: tuple = (), delta=None) -> jax.Array:
        """K compound-tree Counts over ONE resident plane in ONE fused
        XLA program: gather the union of requested row slots, overlay
        pending delta cells (base⊕delta — fused trees stay
        rebuild-free under sustained ingest), stack the extra operands
        (exists row, other-field rows, BSI predicate bitmaps) and fold
        each item's postfix program over the words.  Returns the
        device int32[K] totals un-read: the batcher packs them into
        the window's single readback."""
        return self._tree_program(plane, slots, progs, extras, delta,
                                  "count")

    def run_tree_words(self, plane, slots: tuple, prog: tuple,
                       extras: tuple = (), delta=None) -> jax.Array:
        """One compound tree's final BITMAP (uint32[S, W]) in one
        program — the ``want="words"`` form for bitmap-valued compound
        calls (Row trees, Store/filter sources)."""
        return self._tree_program(plane, slots, (prog,), extras, delta,
                                  "words")

    def run_time_range(self, plane, start: int, length: int,
                       delta=None) -> jax.Array:
        """One time field's ``[t0, t1)`` bitmap off its bucketed time
        plane (``pilosa_tpu.timeviews``) in ONE program: gather the
        CONTIGUOUS slot run ``[start, start + length)`` (clip-padded
        to the pow2 length bucket — dead lanes clip to the last slot
        and are masked AFTER the delta overlay, so the overlay's
        first-lane matching always lands on a live lane), overlay
        pending (row, bucket) delta cells, and OR-reduce the bucket
        lanes.  Returns uint32[S, W]; the program key is the plane
        shape + pow2 length bucket (start/length stay traced), so any
        range of the same padded width reuses one executable."""
        l_pad = pow2_bucket(max(1, length))
        has_delta = delta is not None
        key = (("trange", plane.shape, sharding_key(plane), l_pad,
                delta.rows.shape[0] if has_delta else None), "words")

        def build():
            def program(p, st, n, *dl):
                r_pad = p.shape[-2]
                lane = jnp.arange(l_pad, dtype=jnp.int32)
                idx = jnp.clip(st[0] + lane, 0, r_pad - 1)
                sel = jnp.take(p, idx, axis=-2)      # [S, L_pad, W]
                if has_delta:
                    from pilosa_tpu.ingest.delta import \
                        overlay_gathered_rows
                    sel = overlay_gathered_rows(sel, idx, *dl, r_pad)
                sel = jnp.where((lane < n[0])[None, :, None], sel,
                                jnp.uint32(0))
                return jax.lax.reduce(
                    sel, jnp.uint32(0),
                    lambda x, y: jnp.bitwise_or(x, y),
                    dimensions=(sel.ndim - 2,))
            return program

        args = (plane, self._slot_idx((int(start),)),
                self._slot_idx((int(length),)))
        if has_delta:
            args += (delta.rows, delta.words, delta.vals)
        return self._cached(key, build)(*args)

    def run_readback_pack(self, arrays: tuple,
                          scratch=None) -> jax.Array:
        """Concatenate the flattened int32 outputs of a collection
        window's programs into ONE device array — the whole window
        then costs a single device->host read instead of one per
        kind/shape group (on transports with a fixed per-read RPC
        floor, the read count IS the serving floor; BASELINE.md).
        ``scratch`` (r17): a retired packed output of the same total
        size to donate — consecutive windows of the same shape mix
        ping-pong through two standing packed buffers instead of
        allocating one per window."""
        shapes = tuple(a.shape for a in arrays)
        total = sum(int(np.prod(s, dtype=np.int64)) for s in shapes)
        donate_ok = (scratch is not None
                     and tuple(scratch.shape) == (total,))

        def build():
            def program(*xs):
                return jnp.concatenate(
                    [x.reshape(-1) for x in xs[:len(shapes)]])
            return program
        key = (shapes, sharding_key(arrays[0]), donate_ok,
               "readback-pack")
        if donate_ok:
            return self._cached(key, build,
                                donate=(len(arrays),))(*arrays, scratch)
        return self._cached(key, build)(*arrays)

    def run_sum_batch(self, flags: tuple, leaves):
        """K BSI Sum items (same bit depth) in ONE program.  ``flags[k]``
        = item k has a filter leaf; leaves alternate plane[, filter] per
        item.  Returns int32[K, n_shards, 2*depth+1]: per-bit positive
        counts, per-bit negative counts, non-null count — one stacked
        array = one host read; ``bsi.combine_sum`` finishes exactly."""
        def build():
            def program(*ls):
                rows = []
                i = 0
                for has_filter in flags:
                    plane = ls[i]
                    flt = ls[i + 1] if has_filter else None
                    i += 2 if has_filter else 1
                    pos, neg, cnt = bsik.bit_counts(plane, flt)
                    rows.append(jnp.concatenate(
                        [pos, neg, cnt[..., None]], axis=-1))
                return jnp.stack(rows)
            return program
        return self._cached((flags, sharding_key(leaves[0]),
                             "sum-batch"), build)(*leaves)

    def run_percentile(self, plane, filter_words, nth: float):
        """Percentile in two bounded programs (cached/evicted like every
        other fused program): total count, then the on-device rank
        binary search with an exact host-computed integer target (f64
        host ceil; device f32 would misround past 2^24).  Returns
        ((offset, count) array | None, total)."""
        import math

        has_filter = filter_words is not None
        args = (plane,) + ((filter_words,) if has_filter else ())

        def total_build():
            def program(*ls):
                return bsik.percentile_total(
                    ls[0], ls[1] if has_filter else None)
            return program

        def search_build():
            def program(*ls):
                return bsik.percentile_search(
                    ls[0], ls[1] if has_filter else None, ls[-1])
            return program

        key_t = (("pct-total", plane.shape, sharding_key(plane),
                  has_filter), "pct")
        total = int(self._cached(key_t, total_build)(*args))
        if total == 0:
            return None, 0
        target = min(total, max(1, math.ceil(nth / 100.0 * total)))
        key_s = (("pct-search", plane.shape, sharding_key(plane),
                  has_filter), "pct")
        out = self._cached(key_s, search_build)(*args, jnp.int32(target))
        return out, total

    # ------------------------------------------------- BSI plane batches
    #
    # r20 (the PQL-surface work): the per-PLANE aggregate families.
    # Unlike the legacy run_sum_batch layout (whose K items each carry
    # their own plane leaf — K copies of a multi-GB operand in the
    # program signature), these take ONE resident plane plus the
    # items' filter leaves, so concurrent aggregates over the same
    # plane co-batch into one program that references the plane once,
    # and a pending BSI write overlay (``ingest.delta.BsiOverlay``)
    # merges in-program: the base side scans the untouched columns
    # (touched word columns masked out of the filter), the mini side
    # runs the SAME kernel over the merged touched columns as a tiny
    # standalone plane — base⊕delta exact with zero plane rewrites.

    @staticmethod
    def _bsi_split(plane, flt, delta_ops):
        """(base filter, mini plane, mini filter) for one item: clean
        pass-through when the plane has no overlay."""
        from pilosa_tpu.ingest.delta import (bsi_excl_filter,
                                             bsi_mini_filter,
                                             bsi_mini_plane)
        if delta_ops is None:
            return flt, None, None
        cs, cw, cv, cm = delta_ops
        return (bsi_excl_filter(plane, cs, cw, flt),
                bsi_mini_plane(plane, cs, cw, cv, cm),
                bsi_mini_filter(plane, cs, cw, flt))

    def _delta_args(self, delta):
        if delta is None:
            return None, ()
        return (delta.col_shard.shape[0],
                (delta.col_shard, delta.col_word, delta.col_vals,
                 delta.col_mask))

    def run_sum_plane_batch(self, plane, flags: tuple, filters: tuple,
                            delta=None):
        """K BSI Sum items over ONE resident plane in one program —
        int32[K, n_shards, 2*depth+1], decoded by
        ``bsi.decode_sum_packed`` exactly like :meth:`run_sum_batch`.
        ``flags[k]`` = item k has a filter; ``filters`` holds the
        flagged items' uint32[S, W] bitmaps in order.  With ``delta``
        (a ``BsiOverlay``) the mini side's per-bit counts fold into
        shard 0's row (Sum is linear over columns), so the output
        shape and decode stay identical."""
        n_filters = len(filters)
        bucket, delta_ops = self._delta_args(delta)
        key = (("sum-plane", plane.shape, sharding_key(plane), flags,
                bucket), "agg")

        def build():
            def program(p, *rest):
                filts = rest[:n_filters]
                dops = rest[n_filters:] or None
                rows = []
                fi = 0
                for has_filter in flags:
                    flt = filts[fi] if has_filter else None
                    fi += 1 if has_filter else 0
                    excl, mini, mflt = self._bsi_split(p, flt, dops)
                    pos, neg, cnt = bsik.bit_counts(p, excl)
                    row = jnp.concatenate(
                        [pos, neg, cnt[..., None]], axis=-1)
                    if mini is not None:
                        mp, mn, mc = bsik.bit_counts(mini, mflt)
                        adj = jnp.concatenate(
                            [jnp.sum(mp, axis=0, dtype=jnp.int32),
                             jnp.sum(mn, axis=0, dtype=jnp.int32),
                             jnp.sum(mc, dtype=jnp.int32)[None]])
                        row = row.at[0].add(adj)
                    rows.append(row)
                return jnp.stack(rows)
            return program
        return self._cached(key, build)(plane, *filters, *delta_ops) \
            if delta_ops else self._cached(key, build)(plane, *filters)

    def run_minmax_plane_batch(self, plane, flags: tuple,
                               filters: tuple, delta=None):
        """K BSI Min/Max items over ONE resident plane — int32
        [K, n_shards (+ overlay columns), 2*depth+4], decoded by
        ``bsi.decode_minmax_packed`` (the host combine reduces over
        the whole leading axis and drops zero-count entries, so the
        mini side's touched columns just append as extra pseudo-shard
        rows)."""
        n_filters = len(filters)
        bucket, delta_ops = self._delta_args(delta)
        key = (("minmax-plane", plane.shape, sharding_key(plane),
                flags, bucket), "agg")

        def build():
            def pack(mm):
                return jnp.concatenate(
                    [mm["min_bits"].astype(jnp.int32),
                     mm["max_bits"].astype(jnp.int32),
                     mm["min_neg"].astype(jnp.int32)[..., None],
                     mm["min_cnt"][..., None],
                     mm["max_neg"].astype(jnp.int32)[..., None],
                     mm["max_cnt"][..., None]], axis=-1)

            def program(p, *rest):
                filts = rest[:n_filters]
                dops = rest[n_filters:] or None
                rows = []
                fi = 0
                for has_filter in flags:
                    flt = filts[fi] if has_filter else None
                    fi += 1 if has_filter else 0
                    excl, mini, mflt = self._bsi_split(p, flt, dops)
                    row = pack(bsik.min_max_bits(p, excl))
                    if mini is not None:
                        # mini plane [K, R, 1] → per-column tuples
                        # [K, 2d+4] appended as pseudo-shard rows;
                        # pad columns carry cnt 0 (mini filter zero)
                        # and drop in the host combine
                        mrow = pack(bsik.min_max_bits(mini, mflt))
                        row = jnp.concatenate([row, mrow], axis=0)
                    rows.append(row)
                return jnp.stack(rows)
            return program
        return self._cached(key, build)(plane, *filters, *delta_ops) \
            if delta_ops else self._cached(key, build)(plane, *filters)

    def run_range_batch(self, plane, specs: tuple, operands: tuple,
                        delta=None):
        """K BSI Range-counts over ONE resident plane in one program —
        int32[K] totals (shard axis reduced on device; callers gate on
        the int32-exact shard bound).  ``specs[k]`` is the item's
        STATIC shape ``(op_keys tuple of 1–2, has_filter)``; the
        predicate masks/signs and filter bitmaps are traced operands
        in ``operands`` (flattened per item: masks, neg per op, then
        the filter when flagged) — any predicate VALUE of the same
        comparison shape reuses one executable.  A two-op item ANDs
        its comparisons (between).  Delta-aware like the other
        plane-batch families."""
        bucket, delta_ops = self._delta_args(delta)
        n_ops = len(operands)
        key = (("range-plane", plane.shape, sharding_key(plane),
                specs, bucket), "count")

        def build():
            def program(p, *rest):
                ops = rest[:n_ops]
                dops = rest[n_ops:] or None
                totals = []
                i = 0
                for op_keys, has_filter in specs:
                    preds = []
                    for okey in op_keys:
                        preds.append((ops[i], ops[i + 1], okey))
                        i += 2
                    flt = ops[i] if has_filter else None
                    i += 1 if has_filter else 0
                    excl, mini, mflt = self._bsi_split(p, flt, dops)

                    def side(pl, fw):
                        words = None
                        for masks, neg, okey in preds:
                            cmp = bsik.range_cmp(pl, masks, neg,
                                                 fw)[okey]
                            words = cmp if words is None \
                                else jnp.bitwise_and(words, cmp)
                        return jnp.sum(kernels.count(words),
                                       dtype=jnp.int32)

                    total = side(p, excl)
                    if mini is not None:
                        total = total + side(mini, mflt)
                    totals.append(total)
                return jnp.stack(totals)
            return program
        return self._cached(key, build)(plane, *operands, *delta_ops) \
            if delta_ops else self._cached(key, build)(plane, *operands)

    def run_groupby_batch(self, planes: tuple, combo_idx, last_plane,
                          filter_words, agg_plane, agg: str | None,
                          delta=None):
        """One GroupBy combination block as a batcher-windowable
        program: the whole ``exec.groupby`` body with its output dict
        FLATTENED into one int32 array, so a GroupBy block joins the
        collection window's packed readback alongside counts and BSI
        aggregates instead of dispatching solo.  ``delta`` (the agg
        plane's ``BsiOverlay``) keeps aggregate GroupBys fold-free
        under sustained BSI ingest.  Unflatten with
        ``exec.groupby.unflatten_block``."""
        from pilosa_tpu.exec import groupby as gb
        has_filter = filter_words is not None
        has_agg = agg_plane is not None
        bucket, delta_ops = self._delta_args(
            delta if has_agg else None)
        key = (("groupby", tuple(p.shape for p in planes),
                sharding_key(last_plane),
                combo_idx.shape, last_plane.shape, has_filter,
                agg_plane.shape if has_agg else None, agg, bucket),
               "agg")

        def build():
            def program(*ls):
                n = len(planes)
                pl = ls[:n]
                ci, lp = ls[n], ls[n + 1]
                j = n + 2
                fw = ls[j] if has_filter else None
                j += 1 if has_filter else 0
                ap = ls[j] if has_agg else None
                j += 1 if has_agg else 0
                ad = ls[j:] or None
                out = gb.groupby_out(pl, ci, lp, fw, ap, agg,
                                     agg_delta=ad)
                return jnp.concatenate(
                    [out[name].astype(jnp.int32).reshape(-1)
                     for name in gb.block_part_names(agg)])
            return program
        args = planes + (combo_idx, last_plane)
        if has_filter:
            args += (filter_words,)
        if has_agg:
            args += (agg_plane,)
        args += delta_ops
        return self._cached(key, build)(*args)
