"""Whole-query fusion: one compiled XLA program per call-tree shape.

SURVEY.md §8: "One compiled function per (call-shape, row-bucket)".
Eager per-op dispatch costs one device round trip per AST node; here the
bitmap-call tree is planned into (structure key, leaf arrays), the
structure is compiled once into a single jitted program (bitwise tree +
optional popcount-reduce fused end-to-end by XLA), and subsequent
queries with the same shape — any row IDs, any predicate values — reuse
it with zero retracing.

Predicate values enter as *traced* leaves (lane-broadcast masks and a
sign scalar, see ``engine.bsi.predicate_masks``), so ``amount > 5`` and
``amount > 99`` hit the same executable.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pilosa_tpu.engine import bsi as bsik
from pilosa_tpu.engine import kernels

# node encodings (hashable nested tuples):
#   ("leaf", i)                      leaf i is uint32[..., W] words
#   ("zeros",)                       all-empty bitmap
#   ("or-leaves", (i, j, ...))       union of row leaves (time ranges)
#   ("and"|"or"|"andnot"|"xor", (child, child, ...))   fold left
#   ("not", child, i_exists)
#   ("shift", child, n)
#   ("bsi", i_plane, i_masks, i_neg, op_key)
#   ("bsi-between", i_plane, i_lo_masks, i_lo_neg, lo_op,
#                   i_hi_masks, i_hi_neg, hi_op)


class Unfusable(Exception):
    """Raised by planners for shapes the fused path doesn't cover."""


def _build(node, leaves):
    kind = node[0]
    if kind == "leaf":
        return leaves[node[1]]
    if kind == "zeros":
        return jnp.zeros_like(leaves[0])
    if kind == "or-leaves":
        acc = leaves[node[1][0]]
        for i in node[1][1:]:
            acc = jnp.bitwise_or(acc, leaves[i])
        return acc
    if kind in ("and", "or", "andnot", "xor"):
        op = {"and": jnp.bitwise_and, "or": jnp.bitwise_or,
              "xor": jnp.bitwise_xor,
              "andnot": lambda a, b: jnp.bitwise_and(a, jnp.bitwise_not(b)),
              }[kind]
        acc = _build(node[1][0], leaves)
        for child in node[1][1:]:
            acc = op(acc, _build(child, leaves))
        return acc
    if kind == "not":
        return kernels.complement(_build(node[1], leaves), leaves[node[2]])
    if kind == "shift":
        return kernels.shift(_build(node[1], leaves), node[2])
    if kind == "bsi":
        _, i_plane, i_masks, i_neg, op_key = node
        cmp = bsik.range_cmp(leaves[i_plane], leaves[i_masks],
                             leaves[i_neg])
        return cmp[op_key]
    if kind == "bsi-between":
        (_, i_plane, i_lo, i_lo_neg, lo_op, i_hi, i_hi_neg, hi_op) = node
        lo = bsik.range_cmp(leaves[i_plane], leaves[i_lo],
                            leaves[i_lo_neg])[lo_op]
        hi = bsik.range_cmp(leaves[i_plane], leaves[i_hi],
                            leaves[i_hi_neg])[hi_op]
        return jnp.bitwise_and(lo, hi)
    raise AssertionError(f"bad node {node!r}")


def shift_leaves(node, offset: int):
    """Re-index a plan tree's leaf references by ``offset`` — used to
    concatenate several plans' leaf lists into one batched program."""
    kind = node[0]
    if kind == "leaf":
        return ("leaf", node[1] + offset)
    if kind == "zeros":
        return node
    if kind == "or-leaves":
        return ("or-leaves", tuple(i + offset for i in node[1]))
    if kind in ("and", "or", "andnot", "xor"):
        return (kind, tuple(shift_leaves(c, offset) for c in node[1]))
    if kind == "not":
        return ("not", shift_leaves(node[1], offset), node[2] + offset)
    if kind == "shift":
        return ("shift", shift_leaves(node[1], offset), node[2])
    if kind == "bsi":
        return ("bsi", node[1] + offset, node[2] + offset,
                node[3] + offset, node[4])
    if kind == "bsi-between":
        return ("bsi-between", node[1] + offset, node[2] + offset,
                node[3] + offset, node[4], node[5] + offset,
                node[6] + offset, node[7])
    raise AssertionError(f"bad node {node!r}")


class FusedCache:
    """structure key -> jitted program, LRU-bounded: structure keys can
    embed user-controlled constants (e.g. Shift n), so the program set
    must not grow without bound.  One instance per executor."""

    MAX_PROGRAMS = 256

    def __init__(self):
        import threading
        from collections import OrderedDict
        self._programs: "OrderedDict" = OrderedDict()
        self._lock = threading.Lock()

    def run(self, node, leaves, want: str):
        """Execute a planned tree: ``want`` is "words" (bitmap) or
        "count" (fused popcount-reduce scalar)."""
        key = (node, want)
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                self._programs.move_to_end(key)
        if fn is None:
            if want == "count":
                # per-shard int32 counts; the caller finishes the tiny
                # cross-shard sum in int64 on host (engine int32 policy)
                def program(*ls):
                    return kernels.count(_build(node, ls))
            else:
                def program(*ls):
                    return _build(node, ls)
            fn = jax.jit(program)
            with self._lock:
                self._programs[key] = fn
                while len(self._programs) > self.MAX_PROGRAMS:
                    self._programs.popitem(last=False)
        return fn(*leaves)

    def run_count_batch(self, nodes: tuple, leaves):
        """K Count trees in ONE program: returns int32[K, n_shards] —
        one dispatch and one host read amortize fixed per-read costs
        across every Count in the request (critical on transports with
        a per-read floor; see BASELINE.md)."""
        key = (nodes, "count-batch")
        with self._lock:
            fn = self._programs.get(key)
            if fn is not None:
                self._programs.move_to_end(key)
        if fn is None:
            def program(*ls):
                return jnp.stack([kernels.count(_build(n, ls))
                                  for n in nodes])
            fn = jax.jit(program)
            with self._lock:
                self._programs[key] = fn
                while len(self._programs) > self.MAX_PROGRAMS:
                    self._programs.popitem(last=False)
        return fn(*leaves)
