"""Compile-ladder warm-up (r24): pre-compile the delta-aware program
ladder at plane-residency time, OFF the serving path.

The first write after a plane becomes resident forms a delta overlay,
and the first query after that write needs a delta-aware fused program
— today that compile (tens of ms on CPU, more under load) lands on the
serving path of exactly the query a fresh ingest cares most about.
The warmer closes that tax: when ``exec.planes`` pages a plane in, it
notes the plane shape here, and a single background thread AOT-compiles
(``jit().lower().compile()``) one program per pow2 overlay bucket per
resident fused family through ``FusedCache.warm_delta_ladder`` — the
same key-builder helpers the serving path uses, so a warmed program IS
the serving program and the first post-ingest serve hits a warm cache.

Observability: compile seconds book into the CostLedger tagged
``warmup`` with per-compile flight-recorder ``compile`` events, the
``fused_warmup_compile_seconds`` histogram and
``fused_warmup_programs_total`` counter tick per rung, and the
``warmup`` block under ``/status`` deviceHealth carries lifetime
totals.  Single-flight: one thread, one queue, shapes dedupe — a page-in
storm warms each shape once.
"""

from __future__ import annotations

import threading
from collections import deque

#: the overlay pow2 buckets the ladder pre-compiles, smallest first:
#: ``DeltaMirror.build_overlay`` pads cell counts to pow2, so these are
#: exactly the serve-time ``delta.rows.shape[0]`` values.  256 cells
#: covers the early-ingest window where the compile tax hurts; larger
#: overlays arrive seconds later, after the ladder (or compaction) has
#: caught up.
WARM_OVERLAY_BUCKETS = (1, 2, 4, 8, 16, 32, 64, 128, 256)


class ProgramWarmer:
    """Background single-flight warmer over one executor's FusedCache.

    ``note_resident(shape)`` enqueues a plane shape (deduped for the
    warmer's lifetime) and wakes the worker thread; the worker walks
    the overlay-bucket ladder through ``fused.warm_delta_ladder``.
    ``wait_idle`` lets tests and benches fence on a drained queue.
    """

    def __init__(self, fused, stats=None, ledger=None, flight=None):
        from pilosa_tpu.obs import NULL_FLIGHT, NULL_LEDGER, NopStats
        self.fused = fused
        self.stats = stats or NopStats()
        self.ledger = ledger or NULL_LEDGER
        self.flight = flight or NULL_FLIGHT
        self.enabled = True
        self._seen: set = set()
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._idle = threading.Event()
        self._idle.set()
        self._running = False
        self._closed = False
        # lifetime totals for /status (and a convenient test surface)
        self.programs_warmed = 0
        self.compile_seconds = 0.0
        self.shapes_warmed = 0

    # -- residency hook (called by PlaneCache._insert_entry) ----------------

    def note_resident(self, shape) -> None:
        """A plane of ``shape`` just became resident: queue its ladder
        (once per shape) and wake the worker.  Cheap and non-blocking —
        this rides the page-in path."""
        if not self.enabled or self._closed:
            return
        sig = tuple(shape)
        start = False
        with self._lock:
            if sig in self._seen:
                return
            self._seen.add(sig)
            self._q.append(sig)
            self._idle.clear()
            if not self._running:
                # single-flight: exactly one worker; the exit decision
                # below holds this same lock, so no enqueue strands
                self._running = True
                start = True
        if start:
            threading.Thread(target=self._run, name="pilosa-warmup",
                             daemon=True).start()

    # -- worker -------------------------------------------------------------

    def _run(self) -> None:
        while not self._closed:
            with self._lock:
                if not self._q:
                    self._running = False
                    self._idle.set()
                    return  # drained; next note_resident restarts
                sig = self._q.popleft()
            try:
                self._warm_shape(sig)
            except Exception:  # noqa: BLE001 — warming must never fault serving
                pass
        with self._lock:
            self._running = False
            self._idle.set()

    def _warm_shape(self, shape: tuple) -> None:
        n_total, s_total = 0, 0.0
        for bucket in WARM_OVERLAY_BUCKETS:
            if self._closed:
                break
            n, secs = self.fused.warm_delta_ladder(shape, bucket)
            if not n:
                continue
            n_total += n
            s_total += secs
            self.stats.observe("fused_warmup_compile_seconds", secs)
            self.stats.count("fused_warmup_programs_total", n)
            # compile attribution (r19 ledger): warm-up compiles book
            # under the "warmup" family — a serving-path compile storm
            # and background warming stay distinguishable
            self.ledger.note_compile("warmup", secs, first=False)
            self.flight.record("compile", "warmup",
                               f"{shape}x{bucket}", secs)
        with self._lock:
            self.programs_warmed += n_total
            self.compile_seconds += s_total
            self.shapes_warmed += 1

    # -- fencing / introspection --------------------------------------------

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the queue is drained (tests/benches fence here
        before asserting the zero-serving-compile property)."""
        return self._idle.wait(timeout)

    def close(self) -> None:
        self._closed = True

    def payload(self) -> dict:
        """The ``warmup`` block under /status deviceHealth."""
        with self._lock:
            return {
                "enabled": self.enabled,
                "shapesWarmed": self.shapes_warmed,
                "programsWarmed": self.programs_warmed,
                "compileSeconds": round(self.compile_seconds, 3),
                "pending": len(self._q),
            }
