"""Query execution (L2 of SURVEY.md §2): PQL AST → TPU kernels."""

from pilosa_tpu.exec.executor import ExecutionError, Executor
from pilosa_tpu.exec.result import (GroupCountsResult, Pair, PairsResult,
                                    RowIdsResult, RowResult, ValCount,
                                    result_to_json)

__all__ = [
    "Executor", "ExecutionError", "RowResult", "PairsResult", "Pair",
    "ValCount", "RowIdsResult", "GroupCountsResult", "result_to_json",
]
