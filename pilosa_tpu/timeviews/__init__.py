"""Time-quantum views as first-class device planes (r23 tentpole).

PAPER.md's PQL surface includes time-range queries over time-quantum
views; until this module every time-range Row was answered by
``Executor._time_row_span`` — a host-side loop unioning one device row
fetch per cover view.  Here a time field's finest-unit views land as
ONE plane with a per-quantum row axis: plane row slot
``slot_of[row_id] * n_buckets + bucket`` holds ``row_id``'s bits for
calendar bucket ``bucket`` (suffixes sorted ascending — digit order IS
calendar order at a fixed suffix length), so "row seen in [t0, t1)"
lowers to a fused OR-scan over one CONTIGUOUS slot range — static pow2
length bucket, traced start offset, the same program-key discipline as
every fused family — and time-bucketed ingest absorbs into the
existing delta-overlay machinery keyed per (row, bucket) flat slot.

Only the FINEST quantum unit's views materialize into the plane: every
timestamped write lands in ALL granularity views
(:func:`pilosa_tpu.store.timeq.views_by_time`), so the finest views
alone carry every bit, and a union over the finest buckets whose span
starts fall in ``[floor(from), floor(to))`` equals the oracle's
mixed-granularity minimal cover (``views_by_time_range``) bit for bit
— the equivalence ``tests/test_timeviews.py`` pins.
"""

from __future__ import annotations

from bisect import bisect_left
from dataclasses import dataclass

import numpy as np

from pilosa_tpu.engine.words import WORDS_PER_SHARD
from pilosa_tpu.store import timeq
from pilosa_tpu.store.view import VIEW_STANDARD

# view-name suffix length per quantum unit (standard_2017 /
# standard_201701 / standard_20170102 / standard_2017010203)
_SUFFIX_LEN = {"Y": 4, "M": 6, "D": 8, "H": 10}


def _pow2(n: int) -> int:
    return 1 << max(0, int(n) - 1).bit_length() if n > 1 else 1


def finest_unit(quantum: str) -> str:
    """The smallest granularity unit of a validated quantum string."""
    return timeq.validate_quantum(quantum)[-1]


def bucket_suffixes(field) -> list[str]:
    """Sorted finest-unit time-view suffixes present on ``field`` —
    the plane's bucket directory."""
    unit = finest_unit(field.options.time_quantum)
    n = _SUFFIX_LEN[unit]
    pre = VIEW_STANDARD + "_"
    out = []
    for name in list(field.views):
        suf = name[len(pre):] if name.startswith(pre) else ""
        if len(suf) == n and suf.isdigit():
            out.append(suf)
    return sorted(out)


@dataclass
class TimePlaneSet:
    """One time field's views as a single bucketed device plane.

    Like :class:`pilosa_tpu.exec.planes.PlaneSet`, ``plane`` is the
    IMMUTABLE base and ``delta`` an optional device write overlay
    (cells keyed by flat (row, bucket) slot) merged in-program."""

    plane: object             # uint32[S, RB_pad, W]; RB_pad = pow2(R*B)
    shards: tuple
    row_ids: np.ndarray       # uint64[R] sorted rows across all buckets
    slot_of: dict             # row id -> row index (slot = idx*B + b)
    buckets: tuple            # finest-unit view suffixes, ascending
    bucket_starts: tuple      # datetime span start per bucket
    unit: str                 # finest quantum unit (Y/M/D/H)
    delta: object | None = None

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)

    def bucket_range(self, start, end) -> tuple[int, int]:
        """Half-open bucket index range answering ``[start, end)``
        after flooring both endpoints to the finest unit (``None`` =
        unbounded), matching the oracle's truncation semantics: bucket
        ``b`` is in range iff ``floor(start) <= starts[b] <
        floor(end)``."""
        b0 = (0 if start is None else bisect_left(
            self.bucket_starts, timeq._floor(start, self.unit)))
        b1 = (self.n_buckets if end is None else bisect_left(
            self.bucket_starts, timeq._floor(end, self.unit)))
        return b0, max(b0, b1)


def time_gens(field, shards, fast: bool = False) -> tuple:
    """Per-bucket-view fragment generations, suffix-tagged — the
    "tplane" cache entry's validity snapshot.  Embedding the suffix in
    each element means a NEW bucket appearing (first write into a
    fresh calendar period) reads as a mismatch, not merely a bumped
    generation the delta absorber could paper over."""
    out = []
    for suf in bucket_suffixes(field):
        v = field.views.get(VIEW_STANDARD + "_" + suf)
        if v is None:
            gens = ()
        else:
            gens = (v.generations_fast(shards) if fast
                    else v.generations(shards))
        out.append((suf, gens))
    return tuple(out)


def plan_time_plane(field, shards):
    """Bucket directory + row union + padded geometry — the host-only
    admission half of the build, so the plane cache can budget-gate on
    ``nbytes`` before touching any fragment payloads.  Returns
    ``(buckets, bucket_starts, unit, row_ids, slot_of, rb_pad,
    nbytes)`` or ``None`` when the field has no time views yet."""
    buckets = tuple(bucket_suffixes(field))
    if not buckets:
        return None
    unit = finest_unit(field.options.time_quantum)
    bucket_starts = tuple(timeq.parse_view_time(s)[0] for s in buckets)
    ids = []
    for suf in buckets:
        v = field.views.get(VIEW_STANDARD + "_" + suf)
        if v is None:
            continue
        for shard in shards:
            frag = v.fragments.get(shard)
            if frag is not None:
                arr = frag.row_ids_array()
                if len(arr):
                    ids.append(np.asarray(arr, np.uint64))
    row_ids = (np.unique(np.concatenate(ids)) if ids
               else np.empty(0, np.uint64))
    slot_of = {int(r): i for i, r in enumerate(row_ids)}
    rb_pad = _pow2(max(1, len(row_ids) * len(buckets)))
    nbytes = len(shards) * rb_pad * WORDS_PER_SHARD * 4
    return buckets, bucket_starts, unit, row_ids, slot_of, rb_pad, nbytes


def build_time_plane(field, shards, place, plan=None):
    """Materialize the bucketed time plane: one host assembly pass per
    (bucket view, shard) through ``Fragment.plane_rows`` (rows absent
    from a bucket leave their slots all-zero), then one device
    placement.  Returns a :class:`TimePlaneSet`, or ``None`` when the
    field has no time views."""
    if plan is None:
        plan = plan_time_plane(field, shards)
    if plan is None:
        return None
    buckets, bucket_starts, unit, row_ids, slot_of, rb_pad, _ = plan
    nb = len(buckets)
    host = np.zeros((len(shards), rb_pad, WORDS_PER_SHARD), np.uint32)
    rows = [int(r) for r in row_ids]
    for b, suf in enumerate(buckets):
        v = field.views.get(VIEW_STANDARD + "_" + suf)
        if v is None:
            continue
        slots = [slot_of[r] * nb + b for r in rows]
        for si, shard in enumerate(shards):
            frag = v.fragments.get(shard)
            if frag is not None and rows:
                frag.plane_rows(rows, host[si], slots=slots)
    return TimePlaneSet(place(host), tuple(shards), row_ids, slot_of,
                        buckets, bucket_starts, unit)
