"""Multi-node cluster harnesses for tests and benches.

Reference: ``test/cluster.go#MustRunCluster`` (SURVEY.md §5) — the most
load-bearing fixture upstream: n real servers in one process, real
executors/holders, loopback HTTP between them.  Heartbeat intervals are
cranked down so liveness converges inside test timeouts.

:func:`run_process_cluster` is the OS-process variant (reference: the
v2 ``clustertests`` docker harness) — each node is a separate
``python -m pilosa_tpu.cli server`` process, so node work genuinely
overlaps (no shared GIL) and kill -9 is a real crash.
"""

from __future__ import annotations

import os
import signal
import socket
import subprocess
import sys
import time
from contextlib import contextmanager

from pilosa_tpu.api.client import Client
from pilosa_tpu.cli.config import Config
from pilosa_tpu.server import PilosaTPUServer


class TestCluster:
    __test__ = False  # not a pytest collectable

    def __init__(self, servers: list[PilosaTPUServer]):
        self.servers = servers
        self._ssl_by_server: dict[PilosaTPUServer, object] = {}

    @property
    def clients(self) -> list[Client]:
        return [Client("127.0.0.1", s.http.address[1],
                       ssl_context=self._client_ssl(s))
                for s in self.servers]

    def _client_ssl(self, s: PilosaTPUServer):
        # one context per server (tests poll .clients in loops; rebuilding
        # re-reads the PEM files every time)
        if s not in self._ssl_by_server:
            from pilosa_tpu.cli.config import client_ssl_of
            self._ssl_by_server[s] = client_ssl_of(s.cfg)
        return self._ssl_by_server[s]

    def client(self, i: int = 0) -> Client:
        return self.clients[i]

    def node_ids(self) -> list[str]:
        return [s.cluster.node_id for s in self.servers]

    def server_for(self, node_id: str) -> PilosaTPUServer:
        for s in self.servers:
            if s.cluster.node_id == node_id:
                return s
        raise KeyError(node_id)

    def await_membership(self, n: int, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(len(s.cluster.alive_ids()) == n for s in self.servers):
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {n} members: "
            f"{[s.cluster.alive_ids() for s in self.servers]}")

    def await_state(self, state: str, timeout: float = 10.0,
                    stable_for: float = 0.3) -> None:
        """Wait until every node reports ``state`` AND it stays that way
        for ``stable_for`` seconds — a join-triggered resize may start a
        beat after the first NORMAL reading."""
        deadline = time.monotonic() + timeout
        stable_since = None
        while time.monotonic() < deadline:
            if all(s.cluster.state == state for s in self.servers):
                if stable_since is None:
                    stable_since = time.monotonic()
                elif time.monotonic() - stable_since >= stable_for:
                    return
            else:
                stable_since = None
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster states {[s.cluster.state for s in self.servers]}")

    def close(self) -> None:
        for s in self.servers:
            s.close()


@contextmanager
def run_cluster(n: int, base_dir: str, replicas: int = 1,
                heartbeat: float = 0.2, anti_entropy: float = 0.0,
                mesh: bool = False, **cfg_kwargs):
    """Boot an n-node in-process cluster; yields a :class:`TestCluster`.
    Extra ``cfg_kwargs`` (e.g. a tls block) apply to every node."""
    servers: list[PilosaTPUServer] = []
    try:
        seed_bind = None
        for i in range(n):
            cfg = Config(
                bind="127.0.0.1:0",
                data_dir=f"{base_dir}/node{i}",
                seeds=[seed_bind] if seed_bind else [],
                replicas=replicas,
                cluster_enabled=True,
                heartbeat_interval=heartbeat,
                anti_entropy_interval=anti_entropy,
                mesh=mesh,
                **cfg_kwargs,
            )
            srv = PilosaTPUServer(cfg).open()
            servers.append(srv)
            if seed_bind is None:
                seed_bind = srv.cluster.node_id
        cluster = TestCluster(servers)
        cluster.await_membership(n)
        cluster.await_state("NORMAL")  # join-triggered resizes settled
        yield cluster
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass


def rss_mb() -> float:
    """Current process resident set (MB) — the bench/soak probes'
    shared helper."""
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                return int(line.split()[1]) / 1024.0
    return 0.0


def free_ports(n: int) -> list[int]:
    socks = [socket.socket() for _ in range(n)]
    for s in socks:
        s.bind(("127.0.0.1", 0))
    ports = [s.getsockname()[1] for s in socks]
    for s in socks:
        s.close()
    return ports


class ProcessNode:
    """One cluster node as a real OS process (CPU platform, TPU-grant
    bypass env)."""

    def __init__(self, port: int, data_dir: str, seed_port: int | None,
                 replicas: int, heartbeat: float, anti_entropy: float,
                 extra_env: dict[str, str] | None = None):
        self.port = port
        self.data_dir = data_dir
        self.seed_port = seed_port
        self.replicas = replicas
        self.heartbeat = heartbeat
        self.anti_entropy = anti_entropy
        # extra env for this node — e.g. PILOSA_FAULTS to arm boot-time
        # failpoints (chaos schedules that must fire during replay/join)
        self.extra_env = dict(extra_env or {})
        self.proc: subprocess.Popen | None = None
        self._log = None

    def start(self) -> "ProcessNode":
        env = dict(
            os.environ,
            PALLAS_AXON_POOL_IPS="",
            JAX_PLATFORMS="cpu",
            PILOSA_CLUSTER_ENABLED="1",
            PILOSA_REPLICAS=str(self.replicas),
            PILOSA_HEARTBEAT_INTERVAL=str(self.heartbeat),
            PILOSA_ANTI_ENTROPY_INTERVAL=str(self.anti_entropy),
            PILOSA_MESH="0",
            # persistent XLA compilation cache: restarted nodes (the
            # kill -9 chaos/backup scenarios re-boot the same data dir)
            # skip the first-query compile instead of re-paying it
            PILOSA_COMPILATION_CACHE_DIR=self.data_dir + "_jaxcache",
        )
        if self.seed_port is not None:
            env["PILOSA_SEEDS"] = f"127.0.0.1:{self.seed_port}"
        env.update(self.extra_env)
        self._log = open(self.data_dir + ".log", "ab")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "pilosa_tpu.cli", "server",
             "--bind", f"127.0.0.1:{self.port}",
             "--data-dir", self.data_dir, "--verbose"],
            env=env, stdout=self._log, stderr=self._log)
        return self

    def await_up(self, timeout: float = 60.0) -> "ProcessNode":
        client = Client("127.0.0.1", self.port, timeout=5.0)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"node :{self.port} exited rc={self.proc.returncode}")
            try:
                client._do("GET", "/status")
                return self
            except Exception:  # noqa: BLE001 — still booting
                time.sleep(0.25)
        raise TimeoutError(f"node :{self.port} never served /status")

    def kill9(self) -> None:
        self.proc.send_signal(signal.SIGKILL)
        self.proc.wait(timeout=10)

    def stop(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.terminate()
            try:
                self.proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                self.proc.kill()
                self.proc.wait()
        if self._log is not None:
            self._log.close()
            self._log = None


class ProcessCluster:
    __test__ = False

    def __init__(self, nodes: list[ProcessNode]):
        self.nodes = nodes
        self._clients: dict[int, Client] = {}

    def client(self, i: int = 0) -> Client:
        if i not in self._clients:
            self._clients[i] = Client("127.0.0.1", self.nodes[i].port,
                                      timeout=60.0)
        return self._clients[i]

    def await_membership(self, n: int, timeout: float = 60.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            dead = [node for node in self.nodes
                    if node.proc.poll() is not None]
            if dead:
                raise RuntimeError(
                    "node(s) died awaiting membership: " + ", ".join(
                        f":{d.port} rc={d.proc.returncode} "
                        f"(log {d.data_dir}.log)" for d in dead))
            try:
                states = [self.client(i)._do("GET", "/status")
                          for i in range(len(self.nodes))]
                if all(s["state"] == "NORMAL"
                       and len([nd for nd in s["nodes"]
                                if nd["state"] == "NORMAL"]) == n
                       for s in states):
                    return
            except Exception:  # noqa: BLE001 — node still joining
                pass
            time.sleep(0.3)
        raise TimeoutError(f"cluster never reached {n} NORMAL members")

    def close(self) -> None:
        for c in self._clients.values():
            c.close()
        for node in self.nodes:
            node.stop()


@contextmanager
def run_process_cluster(n: int, base_dir: str, replicas: int = 1,
                        heartbeat: float = 0.3,
                        anti_entropy: float = 0.0,
                        extra_env: dict[str, str] | None = None):
    """Boot an n-node cluster of separate OS processes; yields a
    :class:`ProcessCluster` once all members are NORMAL.  ``extra_env``
    applies to every node (e.g. ``PILOSA_FAULTS`` chaos schedules)."""
    nodes: list[ProcessNode] = []
    cluster = None
    try:
        for attempt in (0, 1):
            ports = free_ports(n)
            nodes = []
            try:
                for i, port in enumerate(ports):
                    node = ProcessNode(port, f"{base_dir}/node{i}",
                                       seed_port=ports[0] if i else None,
                                       replicas=replicas,
                                       heartbeat=heartbeat,
                                       anti_entropy=anti_entropy,
                                       extra_env=extra_env)
                    nodes.append(node.start())
                    node.await_up()
                break
            except RuntimeError:
                # free_ports probes then closes — another process can
                # steal a port before the node binds it.  One re-roll.
                for node in nodes:
                    node.stop()
                if attempt:
                    raise
        cluster = ProcessCluster(nodes)
        cluster.await_membership(n)
        yield cluster
    finally:
        if cluster is not None:
            try:
                cluster.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        else:
            for node in nodes:
                try:
                    node.stop()
                except Exception:  # noqa: BLE001
                    pass
