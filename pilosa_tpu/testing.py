"""In-process multi-node cluster harness for tests.

Reference: ``test/cluster.go#MustRunCluster`` (SURVEY.md §5) — the most
load-bearing fixture upstream: n real servers in one process, real
executors/holders, loopback HTTP between them.  Heartbeat intervals are
cranked down so liveness converges inside test timeouts.
"""

from __future__ import annotations

import time
from contextlib import contextmanager

from pilosa_tpu.api.client import Client
from pilosa_tpu.cli.config import Config
from pilosa_tpu.server import PilosaTPUServer


class TestCluster:
    __test__ = False  # not a pytest collectable

    def __init__(self, servers: list[PilosaTPUServer]):
        self.servers = servers
        self._ssl_by_server: dict[PilosaTPUServer, object] = {}

    @property
    def clients(self) -> list[Client]:
        return [Client("127.0.0.1", s.http.address[1],
                       ssl_context=self._client_ssl(s))
                for s in self.servers]

    def _client_ssl(self, s: PilosaTPUServer):
        # one context per server (tests poll .clients in loops; rebuilding
        # re-reads the PEM files every time)
        if s not in self._ssl_by_server:
            from pilosa_tpu.cli.config import client_ssl_of
            self._ssl_by_server[s] = client_ssl_of(s.cfg)
        return self._ssl_by_server[s]

    def client(self, i: int = 0) -> Client:
        return self.clients[i]

    def node_ids(self) -> list[str]:
        return [s.cluster.node_id for s in self.servers]

    def server_for(self, node_id: str) -> PilosaTPUServer:
        for s in self.servers:
            if s.cluster.node_id == node_id:
                return s
        raise KeyError(node_id)

    def await_membership(self, n: int, timeout: float = 10.0) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if all(len(s.cluster.alive_ids()) == n for s in self.servers):
                return
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster did not reach {n} members: "
            f"{[s.cluster.alive_ids() for s in self.servers]}")

    def await_state(self, state: str, timeout: float = 10.0,
                    stable_for: float = 0.3) -> None:
        """Wait until every node reports ``state`` AND it stays that way
        for ``stable_for`` seconds — a join-triggered resize may start a
        beat after the first NORMAL reading."""
        deadline = time.monotonic() + timeout
        stable_since = None
        while time.monotonic() < deadline:
            if all(s.cluster.state == state for s in self.servers):
                if stable_since is None:
                    stable_since = time.monotonic()
                elif time.monotonic() - stable_since >= stable_for:
                    return
            else:
                stable_since = None
            time.sleep(0.05)
        raise TimeoutError(
            f"cluster states {[s.cluster.state for s in self.servers]}")

    def close(self) -> None:
        for s in self.servers:
            s.close()


@contextmanager
def run_cluster(n: int, base_dir: str, replicas: int = 1,
                heartbeat: float = 0.2, anti_entropy: float = 0.0,
                mesh: bool = False, **cfg_kwargs):
    """Boot an n-node in-process cluster; yields a :class:`TestCluster`.
    Extra ``cfg_kwargs`` (e.g. a tls block) apply to every node."""
    servers: list[PilosaTPUServer] = []
    try:
        seed_bind = None
        for i in range(n):
            cfg = Config(
                bind="127.0.0.1:0",
                data_dir=f"{base_dir}/node{i}",
                seeds=[seed_bind] if seed_bind else [],
                replicas=replicas,
                cluster_enabled=True,
                heartbeat_interval=heartbeat,
                anti_entropy_interval=anti_entropy,
                mesh=mesh,
                **cfg_kwargs,
            )
            srv = PilosaTPUServer(cfg).open()
            servers.append(srv)
            if seed_bind is None:
                seed_bind = srv.cluster.node_id
        cluster = TestCluster(servers)
        cluster.await_membership(n)
        cluster.await_state("NORMAL")  # join-triggered resizes settled
        yield cluster
    finally:
        for s in servers:
            try:
                s.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
