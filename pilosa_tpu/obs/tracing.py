"""Tracing: spans over the query pipeline with cross-node propagation.

Reference: ``tracing/`` wrapping opentracing — spans per executor call
and per shard, HTTP header inject/extract for cross-node traces
(SURVEY.md §3.3, §6).  The rebuild is self-contained (no opentracing in
the image): explicit span tree, W3C-style ``traceparent`` header
propagation, and an in-memory ring of finished traces exposed at
``GET /internal/traces`` (``?trace_id=`` looks one trace up) and in
``profile=true`` query responses.

Cross-node fan-in (r9): the coordinator injects ``Traceparent`` on the
internal fan-out POSTs; the peer's ``/internal/query`` handler extracts
it, runs under a continuation span (node-tagged), and ships the
finished subtree back IN the response — the coordinator grafts it under
its ``cluster.*`` span, so one profile tree spans every node.  Grafted
subtrees arrive as plain JSON dicts; :meth:`Span.to_json` passes them
through, which is why ``Span.children`` may mix ``Span`` and ``dict``.

Slow queries land in :class:`SlowQueryLog` — a bounded ring of
(PQL, index, shards, duration, span tree) records behind
``GET /debug/slow``.
"""

from __future__ import annotations

import random
import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field as dc_field

TRACEPARENT = "Traceparent"  # traceparent: 00-<trace_id>-<span_id>-01


# span/trace ids need uniqueness, not cryptographic strength — the
# original secrets.token_hex path cost one urandom syscall per id
# (~7 ids/query measured ~126us/query, the single largest slice of the
# r05 product-path regression).  Per-thread PRNGs seeded from urandom
# once keep ids unique across threads without sharing generator state.
_id_tls = threading.local()


def _id_rng() -> random.Random:
    r = getattr(_id_tls, "rng", None)
    if r is None:
        r = _id_tls.rng = random.Random(secrets.token_bytes(16))
    return r


def fast_trace_id() -> str:
    return f"{_id_rng().getrandbits(64):016x}"


def fast_span_id() -> str:
    return f"{_id_rng().getrandbits(32):08x}"


# The ACTIVE trace id of the request this thread is serving (set by
# the API edge for every query — lite or traced — and by the internal
# fan-out handler for propagated legs).  The JSON log formatter reads
# it, so any log line emitted while serving a request carries the same
# id its latency exemplar and span tree do: one id joins all three.
_active = threading.local()


def set_current_trace_id(trace_id: str | None) -> None:
    _active.trace_id = trace_id


def current_trace_id() -> str | None:
    return getattr(_active, "trace_id", None)


_HEX = frozenset("0123456789abcdefABCDEF")


def parse_traceparent(value: str | None) \
        -> tuple[str, str, str] | None:
    """Validated ``(trace_id, parent_span_id, flags)`` from a
    traceparent header, or None for anything malformed: wrong segment
    count, empty ids, non-hex ids.  The caller falls back to a fresh
    root span — a garbage header from an arbitrary peer must never
    raise, and must never be accepted as a trace identity (it would
    alias unrelated traces in the finished ring).  Hex validation is
    by character set, NOT ``int(x, 16)`` — the int parser's literal
    quirks (underscores, signs, whitespace) are not hex ids.

    ``flags`` carries the coordinator's materialize + retain
    decisions: "01" = sampled/profiled — build the subtree, ship it
    back, AND keep a copy in the local ring; "02" = slow-hunt — build
    and ship the subtree but do NOT churn the local ring; "00" (or
    anything else) = the coordinator runs the lite path and will never
    materialize a tree — serve under NULL_TRACER, build nothing, ship
    nothing."""
    if not value:
        return None
    parts = value.split("-")
    if len(parts) != 4:
        return None
    _, trace_id, span_id, flags = parts
    if not trace_id or not span_id:
        return None
    if not (set(trace_id) <= _HEX and set(span_id) <= _HEX):
        return None
    return trace_id, span_id, flags


@dataclass(slots=True)
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float = 0.0
    duration: float = 0.0
    tags: dict = dc_field(default_factory=dict)
    # Span children for spans opened on this node; grafted remote
    # subtrees are appended as already-serialized dicts (to_json output
    # of the peer's continuation span)
    children: list = dc_field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name, "durationUs": round(self.duration * 1e6),
            "traceId": self.trace_id, "spanId": self.span_id,
            "parentId": self.parent_id,
            "tags": self.tags,
            "children": [c.to_json() if isinstance(c, Span) else c
                         for c in self.children],
        }


class Tracer:
    """Per-process tracer.  ``span()`` nests via a thread-local stack;
    ``extract``/``inject`` carry the active trace across nodes."""

    def __init__(self, keep: int = 128):
        self._local = threading.local()
        self._finished: deque[Span] = deque(maxlen=keep)
        self._lock = threading.Lock()

    def _stack(self) -> list[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    def current_span(self) -> Span | None:
        """The innermost open span on THIS thread (None outside any)."""
        stack = self._stack()
        return stack[-1] if stack else None

    @contextmanager
    def span(self, name: str, **tags):
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else fast_trace_id(),
            span_id=fast_span_id(),
            parent_id=parent.span_id if parent else None,
            start=time.perf_counter(),
            tags=tags,
        )
        stack.append(s)
        try:
            yield s
        finally:
            s.duration = time.perf_counter() - s.start
            stack.pop()
            if parent is not None:
                parent.children.append(s)
            else:
                with self._lock:
                    self._finished.append(s)

    def stage(self, name: str, duration: float, **tags) -> None:
        """Attach an already-timed child span (a StageTimer mark) to the
        innermost open span on this thread; no-op outside any span —
        per-stage attribution only makes sense inside a traced query."""
        parent = self.current_span()
        if parent is None:
            return
        parent.children.append(Span(
            name=name, trace_id=parent.trace_id,
            span_id=fast_span_id(), parent_id=parent.span_id,
            duration=duration, tags=tags))

    # -- cross-node propagation (reference: handler extract / client inject)

    def inject(self, headers: dict, span: Span | None = None,
               sampled: bool = True) -> None:
        """Write the active trace identity into ``headers``.  ``span``
        overrides the thread-local stack — fan-out legs run on pool
        threads where the coordinator's stack is not visible, so the
        dispatching thread captures its span first.

        The flags segment carries TWO decisions to the peer:
        ``sampled=True`` -> "01" (materialize the subtree, ship it
        back, AND keep a copy in your own finished ring);
        ``sampled=False`` -> "02" (materialize and ship — the
        coordinator may yet capture a SLOW trace that needs your
        subtree — but do NOT churn your ring for a trace that is
        99%-likely discarded).  Lite-path queries never reach this
        method; :class:`LiteTracer` injects flags "00" (build
        nothing)."""
        s = span if span is not None else self.current_span()
        if s is not None:
            flags = "01" if sampled else "02"
            headers[TRACEPARENT] = f"00-{s.trace_id}-{s.span_id}-{flags}"

    @contextmanager
    def extract(self, headers, name: str, **tags):
        """Open a span continuing the trace in ``headers`` (if any).
        Malformed or garbage traceparent values (wrong segment count,
        non-hex ids) fall back to a fresh root span — never an
        exception, never a fabricated trace identity."""
        tp = headers.get(TRACEPARENT) or headers.get(TRACEPARENT.lower())
        parsed = parse_traceparent(tp)
        if parsed is not None:
            trace_id, parent_id, _ = parsed
            remote = Span(name="remote-parent", trace_id=trace_id,
                          span_id=parent_id, parent_id=None)
            self._stack().append(remote)
            try:
                with self.span(name, **tags) as s:
                    yield s
            finally:
                self._stack().pop()
                # the synthetic parent is discarded; its real children
                # are this node's roots for the propagated trace
                with self._lock:
                    self._finished.extend(remote.children)
            return
        with self.span(name, **tags) as s:
            yield s

    def record(self, span: Span) -> None:
        """Publish a finished root span into this tracer's ring —
        per-request tracers hand their retained roots to the process
        GLOBAL_TRACER here, so ``/internal/traces?trace_id=`` resolves
        them after the request is gone."""
        with self._lock:
            self._finished.append(span)

    def finished(self) -> list[Span]:
        with self._lock:
            return list(self._finished)


class _NullSpanCtx:
    """Reusable no-op context manager: the span surface of the lite
    path.  ``__enter__`` yields None — every ``with tracer.span(...)``
    on the serving path uses the span positionally (no ``as``) or
    tolerates None."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullSpanCtx()


class NullTracer:
    """Tracer surface with zero per-call allocation — what a peer runs
    under when the coordinator's traceparent flags say the trace will
    never be materialized (``00``).  No spans, no ids, no ring."""

    __slots__ = ()
    sampled = False

    def span(self, name, **tags):
        return _NULL_CTX

    def stage(self, name, duration, **tags):
        pass

    def current_span(self):
        return None

    def inject(self, headers, span=None, sampled=False):
        pass

    def finished(self):
        return []

    def record(self, span):
        pass


NULL_TRACER = NullTracer()


class LiteTracer(NullTracer):
    """Trace IDENTITY without a span tree (r12 hot-path fix).

    The retention decision (sampling / profile / slow-hunt threshold)
    is made BEFORE any span materializes; queries that lose it run
    under this: a per-request trace id for the ``X-Pilosa-Trace-Id``
    header and cross-node propagation (flags ``00`` — peers run under
    :data:`NULL_TRACER`), plus a plain ``marks`` list the StageTimer
    appends (name, seconds) tuples into, so a query that turns out SLOW
    can still be captured with its per-stage breakdown.  Everything
    else — span objects, id generation per span, ring churn — is
    skipped entirely."""

    __slots__ = ("trace_id", "marks")

    def __init__(self, trace_id: str | None = None):
        self.trace_id = trace_id or fast_trace_id()
        self.marks: list[tuple[str, float]] = []

    def stage(self, name, duration, **tags):
        self.marks.append((name, duration))

    def inject(self, headers, span=None, sampled=False):
        # propagate identity so peers neither invent a fresh root nor
        # churn their rings; flags "00" = the tree is never built
        headers[TRACEPARENT] = f"00-{self.trace_id}-00000000-00"

    def slow_root(self, name: str, duration: float, **tags) -> Span:
        """Materialize a minimal root for slow-query capture AFTER the
        fact: the request's stage marks become ``stage.*`` children.
        This is the degraded (but still attributable) tree an
        unsampled slow query gets; full executor-span trees need the
        query to be sampled, profiled, or the slow threshold set at or
        under the API's ``SLOW_TRACE_FLOOR``."""
        root = Span(name=name, trace_id=self.trace_id,
                    span_id=fast_span_id(), parent_id=None,
                    duration=duration, tags=tags)
        for mark, dur in self.marks:
            root.children.append(Span(
                name=mark, trace_id=self.trace_id,
                span_id=fast_span_id(), parent_id=root.span_id,
                duration=dur))
        return root


class SlowQueryLog:
    """Bounded ring of slow-query records: PQL, index, shards,
    duration, trace id and the full span tree.  ``total`` keeps
    counting past the ring bound (the ring is a sample, the counter is
    the truth — same split as prometheus counter vs. exemplars)."""

    def __init__(self, keep: int = 64):
        self._entries: deque[dict] = deque(maxlen=keep)
        self._lock = threading.Lock()
        self._total = 0

    def record(self, entry: dict) -> None:
        with self._lock:
            self._total += 1
            self._entries.append(entry)

    def entries(self) -> list[dict]:
        """Newest first (the one an operator is chasing is recent)."""
        with self._lock:
            return list(reversed(self._entries))

    def summary(self) -> dict:
        with self._lock:
            slowest = max((e.get("durationMs", 0.0)
                           for e in self._entries), default=0.0)
            return {"total": self._total, "kept": len(self._entries),
                    "slowestMs": slowest}


GLOBAL_TRACER = Tracer()
