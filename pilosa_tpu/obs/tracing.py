"""Tracing: spans over the query pipeline with cross-node propagation.

Reference: ``tracing/`` wrapping opentracing — spans per executor call
and per shard, HTTP header inject/extract for cross-node traces
(SURVEY.md §3.3, §6).  The rebuild is self-contained (no opentracing in
the image): explicit span tree, W3C-style ``traceparent`` header
propagation, and an in-memory ring of finished traces exposed for
``profile=true`` query responses and debugging.
"""

from __future__ import annotations

import secrets
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field as dc_field

TRACEPARENT = "Traceparent"  # traceparent: 00-<trace_id>-<span_id>-01


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_id: str | None
    start: float = 0.0
    duration: float = 0.0
    tags: dict = dc_field(default_factory=dict)
    children: list["Span"] = dc_field(default_factory=list)

    def to_json(self) -> dict:
        return {
            "name": self.name, "durationUs": round(self.duration * 1e6),
            "tags": self.tags,
            "children": [c.to_json() for c in self.children],
        }


class Tracer:
    """Per-process tracer.  ``span()`` nests via a thread-local stack;
    ``extract``/``inject`` carry the active trace across nodes."""

    def __init__(self, keep: int = 128):
        self._local = threading.local()
        self._finished: deque[Span] = deque(maxlen=keep)
        self._lock = threading.Lock()

    def _stack(self) -> list[Span]:
        if not hasattr(self._local, "stack"):
            self._local.stack = []
        return self._local.stack

    @contextmanager
    def span(self, name: str, **tags):
        stack = self._stack()
        parent = stack[-1] if stack else None
        s = Span(
            name=name,
            trace_id=parent.trace_id if parent else secrets.token_hex(8),
            span_id=secrets.token_hex(4),
            parent_id=parent.span_id if parent else None,
            start=time.perf_counter(),
            tags=tags,
        )
        stack.append(s)
        try:
            yield s
        finally:
            s.duration = time.perf_counter() - s.start
            stack.pop()
            if parent is not None:
                parent.children.append(s)
            else:
                with self._lock:
                    self._finished.append(s)

    # -- cross-node propagation (reference: handler extract / client inject)

    def inject(self, headers: dict) -> None:
        stack = self._stack()
        if stack:
            s = stack[-1]
            headers[TRACEPARENT] = f"00-{s.trace_id}-{s.span_id}-01"

    @contextmanager
    def extract(self, headers, name: str):
        """Open a span continuing the trace in ``headers`` (if any)."""
        tp = headers.get(TRACEPARENT) or headers.get(TRACEPARENT.lower())
        if tp:
            try:
                _, trace_id, parent_id, _ = tp.split("-")
            except ValueError:
                trace_id = None
            if trace_id is not None:
                remote = Span(name="remote-parent", trace_id=trace_id,
                              span_id=parent_id, parent_id=None)
                self._stack().append(remote)
                try:
                    with self.span(name) as s:
                        yield s
                finally:
                    self._stack().pop()
                    # the synthetic parent is discarded; its real children
                    # are this node's roots for the propagated trace
                    with self._lock:
                        self._finished.extend(remote.children)
                return
        with self.span(name) as s:
            yield s

    def finished(self) -> list[Span]:
        with self._lock:
            return list(self._finished)


GLOBAL_TRACER = Tracer()
