"""Pipeline flight recorder: always-on incident capture (r19).

The dispatch pipeline, health governor, watchdog, and eviction
machinery make load-bearing decisions that until now left no
reconstructable timeline: a watchdog trip told you *that* a window
stalled, not what the pipeline was doing in the seconds before.  The
flight recorder is a lock-light fixed-size ring of structured
lifecycle events — enqueue/dispatch/readback/deliver per window,
governor state transitions, watchdog trips, window quarantines, plane
evictions (with reason), page-ins, program compiles — each stamped
with a monotonic timestamp and a global sequence number.

Hot-path contract (same bar as the lite tracer, PR 7): recording an
event allocates nothing but the float boxes Python itself makes — the
ring slots are preallocated lists written in place, the sequence
counter is an ``itertools.count`` (atomic under the GIL), and there is
no lock on the record path.  A racing wrap-around can tear one slot's
fields; :meth:`snapshot` drops torn slots instead of crashing, which
is the right trade for a recorder that must never slow the pipeline
it is recording.

Incident capture: :meth:`incident` records the triggering event and
immediately dumps the whole ring to a JSON artifact (the postmortem
for "why did availability dip at 03:14").  Dumps are rate-limited and
bounded in count; the live ring stays retrievable via
``GET /debug/flight`` and is fanned in cluster-wide next to the
metrics snapshots.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

# ring capacity: at the observed healthy event rate (4 events per
# window, windows every few ms worst-case) 4096 slots hold the last
# several seconds of pipeline history — enough to see the run-up to a
# watchdog trip, small enough to dump in one write
DEFAULT_CAPACITY = 4096

# incident dumps kept on disk; older artifacts are unlinked so a
# flapping governor cannot fill the data dir
MAX_DUMPS = 8

# floor between dumps: a quarantine storm produces one artifact per
# interval, not one per window
DUMP_INTERVAL_SECONDS = 5.0

# slot layout (preallocated list, written in place on the hot path)
_SEQ, _TS, _KIND, _ENTITY, _DETAIL, _VALUE = range(6)


class FlightRecorder:
    """Fixed-size ring of pipeline lifecycle events + incident dumps.

    ``record`` is the hot-path entry: positional scalars only, no
    kwargs, no per-event allocation beyond float boxing.  ``incident``
    is the cold path: it records the trigger and dumps the ring.
    """

    def __init__(self, capacity: int = DEFAULT_CAPACITY,
                 dump_dir: str | None = None, stats=None):
        self.capacity = max(64, int(capacity))
        # slots are preallocated and reused; seq 0 marks "never
        # written" (the global counter starts at 1)
        self._ring = [[0, 0.0, "", "", "", 0.0]
                      for _ in range(self.capacity)]
        self._seq = itertools.count(1)
        self.dump_dir = dump_dir
        self._stats = stats
        self._dump_lock = threading.Lock()
        self._last_dump_t = 0.0
        self._dumps: list = []        # newest-last artifact paths
        self.enabled = True

    # -- hot path -----------------------------------------------------------

    def record(self, kind: str, entity: str = "", detail: str = "",
               value: float = 0.0) -> None:
        """Append one event.  Lock-free: a torn slot under wrap-around
        races is dropped at read time, never an error here."""
        if not self.enabled:
            return
        seq = next(self._seq)
        slot = self._ring[seq % self.capacity]
        # write seq last-ish is pointless without a memory barrier;
        # snapshot() instead validates monotonic seq per slot index
        slot[_SEQ] = seq
        slot[_TS] = time.monotonic()
        slot[_KIND] = kind
        slot[_ENTITY] = entity
        slot[_DETAIL] = detail
        slot[_VALUE] = value
        if self._stats is not None:
            self._stats.count("flight_events_total", 1)

    # -- incidents ----------------------------------------------------------

    def incident(self, reason: str, entity: str = "",
                 detail: str = "") -> str | None:
        """Record the triggering event and dump the ring to a JSON
        artifact.  Returns the artifact path (None when dumping is
        disabled or rate-limited away)."""
        self.record("incident", entity, reason if not detail
                    else f"{reason}: {detail}")
        if self._stats is not None:
            self._stats.count("flight_incidents_total", 1, reason=reason)
        if self.dump_dir is None:
            return None
        with self._dump_lock:
            now = time.monotonic()
            if now - self._last_dump_t < DUMP_INTERVAL_SECONDS:
                return self._dumps[-1] if self._dumps else None
            self._last_dump_t = now
            return self._dump(reason)

    def _dump(self, reason: str) -> str | None:
        """Write the current ring to ``flight-<seq>-<reason>.json``.
        Caller holds the dump lock."""
        snap = self.snapshot()
        snap["reason"] = reason
        snap["wallTime"] = time.time()
        tag = "".join(c if c.isalnum() or c in "-_" else "-"
                      for c in reason)[:48]
        path = os.path.join(self.dump_dir,
                            f"flight-{snap['lastSeq']}-{tag}.json")
        try:
            os.makedirs(self.dump_dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(snap, f)
            os.replace(tmp, path)
        except OSError:
            # a full disk must not take the pipeline down with it
            return None
        self._dumps.append(path)
        while len(self._dumps) > MAX_DUMPS:
            old = self._dumps.pop(0)
            try:
                os.unlink(old)
            except OSError:
                pass
        if self._stats is not None:
            self._stats.count("flight_dumps_total", 1)
        return path

    @property
    def last_dump(self) -> str | None:
        return self._dumps[-1] if self._dumps else None

    # -- read side ----------------------------------------------------------

    def snapshot(self, limit: int | None = None) -> dict:
        """The ring as JSON-ready dicts, oldest first.  Torn slots
        (seq 0, or a seq that does not map back to its slot index —
        the signature of a mid-write wrap race) are dropped."""
        events = []
        last_seq = 0
        for idx, slot in enumerate(self._ring):
            seq = slot[_SEQ]
            if seq <= 0 or seq % self.capacity != idx:
                continue
            events.append({"seq": seq, "ts": slot[_TS],
                           "kind": slot[_KIND], "entity": slot[_ENTITY],
                           "detail": slot[_DETAIL],
                           "value": slot[_VALUE]})
            if seq > last_seq:
                last_seq = seq
        events.sort(key=lambda e: e["seq"])
        if limit is not None and len(events) > limit:
            events = events[-limit:]
        return {"events": events, "lastSeq": last_seq,
                "capacity": self.capacity,
                "dumps": list(self._dumps)}


class NullFlightRecorder:
    """Recorder-shaped nothing for contexts (benches with
    instrumentation off, tools) that want the seam without the ring."""

    enabled = False
    dump_dir = None
    last_dump = None

    def record(self, kind: str, entity: str = "", detail: str = "",
               value: float = 0.0) -> None:
        pass

    def incident(self, reason: str, entity: str = "",
                 detail: str = "") -> None:
        return None

    def snapshot(self, limit: int | None = None) -> dict:
        return {"events": [], "lastSeq": 0, "capacity": 0, "dumps": []}


NULL_FLIGHT = NullFlightRecorder()
