"""Logging: the injected logger interface.

Reference: ``logger.go`` — a tiny ``Logger`` interface with std/verbose
implementations, injected through every constructor (SURVEY.md §3.3).
The rebuild rides Python's stdlib logging with the same shape: one
``get_logger`` used by server/executor/cluster, verbosity switch, and a
structured (key=value) formatter for operational greppability.

r14 adds a structured **JSON formatter** (``log_format = "json"``):
one JSON object per line, with the ACTIVE trace id (the id of the
request the emitting thread is serving — see
:func:`pilosa_tpu.obs.tracing.current_trace_id`) injected as
``traceId``.  A slow query's p99 bucket exemplar, its retained trace
at ``/internal/traces?trace_id=``, and its log lines then join on one
id — the correlated-logs leg of the single-pane contract.
"""

from __future__ import annotations

import json
import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"


class JsonFormatter(logging.Formatter):
    """One JSON object per log line: timestamp, level, logger, message,
    and the emitting thread's active trace id (omitted when no request
    is being served).  A record-level ``traceId`` (passed via
    ``extra=``) wins over the thread-local — emitters that outlive the
    request window (the slow-query capture logs after the serving
    ``finally`` reset) attach the id explicitly."""

    def format(self, record: logging.LogRecord) -> str:
        from pilosa_tpu.obs.tracing import current_trace_id
        out = {
            "ts": self.formatTime(record),
            "level": record.levelname,
            "logger": record.name,
            "message": record.getMessage(),
        }
        trace_id = getattr(record, "traceId", None) or current_trace_id()
        if trace_id:
            out["traceId"] = trace_id
        if record.exc_info:
            out["exc"] = self.formatException(record.exc_info)
        return json.dumps(out)


def get_logger(name: str = "pilosa_tpu", verbose: bool = False,
               stream=None, fmt: str | None = None) -> logging.Logger:
    """``fmt``: ``"json"`` installs the structured formatter,
    ``"text"`` the key=value default; ``None`` keeps whatever an
    earlier call configured (text on first creation)."""
    logger = logging.getLogger(name)
    created = False
    if not logger.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        logger.addHandler(h)
        logger.propagate = False
        created = True
    if fmt not in (None, "", "text", "json"):
        raise ValueError(f"unknown log_format {fmt!r} "
                         "(expected 'text' or 'json')")
    if fmt == "json":
        formatter: logging.Formatter = JsonFormatter()
    elif fmt == "text" or created:
        formatter = logging.Formatter(_FORMAT)
    else:
        formatter = None
    if formatter is not None:
        for h in logger.handlers:
            h.setFormatter(formatter)
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    return logger
