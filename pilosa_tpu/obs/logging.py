"""Logging: the injected logger interface.

Reference: ``logger.go`` — a tiny ``Logger`` interface with std/verbose
implementations, injected through every constructor (SURVEY.md §3.3).
The rebuild rides Python's stdlib logging with the same shape: one
``get_logger`` used by server/executor/cluster, verbosity switch, and a
structured (key=value) formatter for operational greppability.
"""

from __future__ import annotations

import logging
import sys

_FORMAT = "%(asctime)s %(levelname).1s %(name)s %(message)s"


def get_logger(name: str = "pilosa_tpu", verbose: bool = False,
               stream=None) -> logging.Logger:
    logger = logging.getLogger(name)
    if not logger.handlers:
        h = logging.StreamHandler(stream or sys.stderr)
        h.setFormatter(logging.Formatter(_FORMAT))
        logger.addHandler(h)
        logger.propagate = False
    logger.setLevel(logging.DEBUG if verbose else logging.INFO)
    return logger
