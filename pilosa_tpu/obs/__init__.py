"""Cross-cutting observability (LX of SURVEY.md §2): metrics, tracing,
logging."""

from pilosa_tpu.obs.logging import get_logger
from pilosa_tpu.obs.metrics import (NopStats, StageTimer, Stats,
                                    StatsdStats)
from pilosa_tpu.obs.tracing import (GLOBAL_TRACER, SlowQueryLog, Tracer,
                                    parse_traceparent)

__all__ = ["Stats", "NopStats", "StageTimer", "StatsdStats",
           "get_logger", "Tracer", "GLOBAL_TRACER", "SlowQueryLog",
           "parse_traceparent"]
