"""Cross-cutting observability (LX of SURVEY.md §2): metrics, tracing,
logging."""

from pilosa_tpu.obs.flight import (NULL_FLIGHT, FlightRecorder,
                                   NullFlightRecorder)
from pilosa_tpu.obs.ledger import NULL_LEDGER, CostLedger, NullLedger
from pilosa_tpu.obs.logging import get_logger
from pilosa_tpu.obs.metrics import (NopStats, StageTimer, Stats,
                                    StatsdStats)
from pilosa_tpu.obs.tracing import (GLOBAL_TRACER, NULL_TRACER,
                                    LiteTracer, NullTracer, SlowQueryLog,
                                    Tracer, fast_span_id, fast_trace_id,
                                    parse_traceparent)

__all__ = ["Stats", "NopStats", "StageTimer", "StatsdStats",
           "get_logger", "Tracer", "GLOBAL_TRACER", "SlowQueryLog",
           "LiteTracer", "NullTracer", "NULL_TRACER",
           "fast_trace_id", "fast_span_id", "parse_traceparent",
           "FlightRecorder", "NullFlightRecorder", "NULL_FLIGHT",
           "CostLedger", "NullLedger", "NULL_LEDGER"]
