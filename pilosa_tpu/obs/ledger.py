"""Device-cost ledger: who spent the device's milliseconds (r19).

The dispatch pipeline co-batches many tenants' queries into one
collection window: one fused program, one packed read, one measured
wall-clock — and until now no answer to "which tenant/shape/plane is
actually consuming the device".  ROADMAP items 2 (roofline) and 3 (HBM
economy) both need that attribution: you cannot chase a roofline or
price a tenant without knowing where the window's milliseconds went.

The ledger apportions every dispatch window's measured cost to the
items it served:

- **seconds** — the window's dispatch + readback wall-clock split by
  each item's bytes-scanned share (:func:`apportion`; equal split when
  the window scanned nothing).  Shares sum EXACTLY to the measured
  wall — pinned by ``tests/test_obs.py`` — so per-tenant rollups can
  be trusted to re-add to the device totals.
- **bytes** — each item's own measured scan bytes (its group's scan
  split across the group's deduplicated riders).
- **solo fast-lane** dispatches are charged whole to their one caller.
- **compile seconds** ride inside the dispatch wall they stalled (the
  jit happens at call time), so the apportionment already attributes
  them; :meth:`note_compile` additionally books per-family compile
  totals + first-compile exemplars for the program-ladder analysis.

Attribution context rides a thread-local set by the executor at
admission (tenant = index name, the query's trace id when it has one)
and refined at plane-resolution points (the ``index/field`` plane
label): the batcher's submit paths run on the caller's thread, so
``_Pending`` items stamp the context at construction and carry it
into the window — no signature changes on the dispatch spine.

Rollups are bounded maps (coldest half pruned on overflow) and the
Prometheus families ride the registry's label-cardinality caps
(``obs.metrics.BOUNDED_LABELS``): top-K tenants/planes keep their own
series, the long tail folds into ``other`` — and the counters join
the PR 9 cluster fan-in, so ``/metrics/cluster`` shows fleet-wide
cost.  Per-trace shares feed the profiled query's span tree
(``deviceSeconds`` on the root) and the decayed per-tenant rate feeds
tenancy QoS's optional ``tenant_device_seconds_quota``.
"""

from __future__ import annotations

import threading

# rollup map bound (tenant/shape/plane keys are user-controlled):
# on overflow the cheapest half is dropped — the totals stay exact,
# only per-key detail for cold keys is forgotten
_MAX_KEYS = 512

# per-trace share retention (joins a profiled query's span tree):
# bounded FIFO — only traced queries land here, so the common case
# writes nothing
_MAX_TRACES = 1024

# decayed per-tenant device-seconds half-life: the QoS quota keys off
# the last minute or so of actual device use, not all-time totals
DECAY_SECONDS = 60.0

# -- attribution context (thread-local) ---------------------------------------

_ctx = threading.local()


def set_query_context(tenant: str = "", trace_id: str | None = None):
    """Executor admission hook: stamp the calling thread with the
    query's tenant (index name) and trace identity.  Cleared by
    :func:`clear_query_context` when the query leaves the executor."""
    _ctx.tenant = tenant
    _ctx.trace_id = trace_id
    _ctx.plane = ""


def set_plane_context(plane: str) -> None:
    """Refine the thread's context with the plane (``index/field``)
    the next dispatch will scan."""
    _ctx.plane = plane


def query_context() -> tuple:
    """(tenant, plane, trace_id) for the calling thread."""
    return (getattr(_ctx, "tenant", ""), getattr(_ctx, "plane", ""),
            getattr(_ctx, "trace_id", None))


def clear_query_context() -> None:
    _ctx.tenant = ""
    _ctx.trace_id = None
    _ctx.plane = ""


# -- exact apportionment ------------------------------------------------------


def apportion(total: float, weights) -> list[float]:
    """Split ``total`` proportionally to ``weights`` such that the
    shares sum EXACTLY (bit-for-bit, left-to-right float sum) to
    ``total``.  Zero/empty weights split equally.  The last share
    absorbs the floating-point remainder, with a fix-up loop for the
    last-bit rounding of the final addition."""
    n = len(weights)
    if n == 0:
        return []
    if n == 1:
        return [total]
    wsum = 0.0
    for w in weights:
        wsum += float(w)
    shares = []
    acc = 0.0
    for w in weights[:-1]:
        s = (total * (float(w) / wsum)) if wsum > 0.0 else total / n
        shares.append(s)
        acc += s
    shares.append(total - acc)
    # float addition is not associative at the last bit; nudge the
    # remainder share until a left-to-right re-sum reproduces total
    for _ in range(4):
        s = 0.0
        for x in shares:
            s += x
        if s == total:
            break
        shares[-1] += total - s
    return shares


class CostLedger:
    """Per-tenant / per-shape / per-plane device-cost attribution.

    Charging runs once per dispatch window on the readback worker (or
    once per solo fast-lane dispatch on the caller thread after the
    answer is already host-resident) — off the latency-critical path.
    One small lock guards the rollup maps."""

    def __init__(self, stats=None, decay_seconds: float = DECAY_SECONDS):
        from pilosa_tpu.obs import NopStats
        self._stats = stats or NopStats()
        self.decay_seconds = max(1.0, float(decay_seconds))
        self._lock = threading.Lock()
        # key -> [seconds, bytes, items]
        self._tenants: dict[str, list] = {}
        self._shapes: dict[str, list] = {}
        self._planes: dict[str, list] = {}
        # tenant -> [decayed seconds, last decay stamp]
        self._recent: dict[str, list] = {}
        # trace id -> apportioned seconds (bounded FIFO)
        self._trace_s: dict[str, float] = {}
        self._trace_order: list[str] = []
        self.windows = 0
        self.solo_dispatches = 0
        self.total_seconds = 0.0
        self.total_bytes = 0
        self.compile_seconds = 0.0
        self.compile_count = 0

    # -- charging ------------------------------------------------------------

    def charge_window(self, wall_seconds: float, entries) -> None:
        """Apportion one window's measured wall-clock to the items it
        served.  ``entries``: sequence of
        ``(tenant, shape, plane, nbytes, trace_id)`` — one per
        delivered item; seconds split by bytes share, bytes charged
        as measured."""
        entries = list(entries)
        if not entries:
            return
        shares = apportion(float(wall_seconds),
                           [e[3] for e in entries])
        with self._lock:
            self.windows += 1
            for (tenant, shape, plane, nbytes, trace_id), sec in zip(
                    entries, shares):
                self._charge(tenant, shape, plane, sec, nbytes,
                             trace_id)

    def charge_solo(self, tenant: str, shape: str, plane: str,
                    wall_seconds: float, nbytes: int,
                    trace_id: str | None = None) -> None:
        """A solo fast-lane dispatch: one caller, charged whole."""
        with self._lock:
            self.solo_dispatches += 1
            self._charge(tenant, shape, plane, float(wall_seconds),
                         nbytes, trace_id)

    def _charge(self, tenant: str, shape: str, plane: str,
                seconds: float, nbytes: int,
                trace_id: str | None) -> None:
        """Caller holds the lock."""
        tenant = tenant or "unattributed"
        plane = plane or tenant
        self.total_seconds += seconds
        self.total_bytes += int(nbytes)
        for table, key in ((self._tenants, tenant),
                           (self._shapes, shape),
                           (self._planes, plane)):
            row = table.get(key)
            if row is None:
                if len(table) >= _MAX_KEYS:
                    self._prune(table)
                row = table[key] = [0.0, 0, 0]
            row[0] += seconds
            row[1] += int(nbytes)
            row[2] += 1
        # decayed per-tenant rate (the QoS device-seconds feed)
        rec = self._recent.get(tenant)
        now = _mono()
        if rec is None:
            if len(self._recent) >= _MAX_KEYS:
                self._recent.clear()
            self._recent[tenant] = [seconds, now]
        else:
            rec[0] = rec[0] * self._decay(now - rec[1]) + seconds
            rec[1] = now
        if trace_id is not None:
            if trace_id not in self._trace_s:
                self._trace_order.append(trace_id)
                if len(self._trace_order) > _MAX_TRACES:
                    self._trace_s.pop(self._trace_order.pop(0), None)
            self._trace_s[trace_id] = (
                self._trace_s.get(trace_id, 0.0) + seconds)
        # scrape families (label cardinality capped at registry level;
        # the counters join the cluster fan-in)
        st = self._stats
        st.count("tenant_device_seconds_total", seconds, tenant=tenant)
        st.count("tenant_device_bytes_total", nbytes, tenant=tenant)
        st.count("shape_device_seconds_total", seconds, shape=shape)
        st.count("plane_device_seconds_total", seconds, plane=plane)
        # the hottest shape's latency bucket carries a resolvable
        # trace id as its exemplar
        st.observe("query_device_seconds", seconds, trace_id=trace_id,
                   shape=shape)

    @staticmethod
    def _prune(table: dict) -> None:
        keep = sorted(table.items(), key=lambda kv: -kv[1][0])
        cut = dict(keep[:_MAX_KEYS // 2])
        table.clear()
        table.update(cut)

    def _decay(self, dt: float) -> float:
        if dt <= 0.0:
            return 1.0
        return 0.5 ** (dt / self.decay_seconds)

    # -- compile observability (tentpole layer 3) ----------------------------

    def note_compile(self, family: str, seconds: float,
                     first: bool) -> None:
        """One fused-program compile: per-family seconds histogram,
        with the compiling query's trace id as the bucket exemplar on
        FIRST compiles (the program-ladder warm-up signal)."""
        from pilosa_tpu.obs.tracing import current_trace_id
        with self._lock:
            self.compile_seconds += float(seconds)
            self.compile_count += 1
        tid = current_trace_id() if first else None
        self._stats.observe("fused_compile_seconds", float(seconds),
                            trace_id=tid, family=family)
        self._stats.count("fused_compile_seconds_total", float(seconds),
                          family=family)

    # -- read side -----------------------------------------------------------

    def recent_seconds(self, tenant: str) -> float:
        """Decayed device-seconds for one tenant — what the QoS
        ``tenant_device_seconds_quota`` admits against."""
        with self._lock:
            rec = self._recent.get(tenant)
            if rec is None:
                return 0.0
            return rec[0] * self._decay(_mono() - rec[1])

    def trace_seconds(self, trace_id: str | None) -> float | None:
        """Apportioned device-seconds charged to one trace (None when
        the trace never reached the device or was never charged)."""
        if trace_id is None:
            return None
        with self._lock:
            return self._trace_s.get(trace_id)

    def payload(self, top_k: int = 5) -> dict:
        """The ``/status`` costs block: totals plus top-K rollups by
        device seconds with the long tail folded into ``other``."""
        with self._lock:
            return {
                "windows": self.windows,
                "soloDispatches": self.solo_dispatches,
                "deviceSecondsTotal": round(self.total_seconds, 6),
                "bytesScannedTotal": int(self.total_bytes),
                "compileSecondsTotal": round(self.compile_seconds, 6),
                "compileCount": self.compile_count,
                "tenants": self._top(self._tenants, top_k),
                "shapes": self._top(self._shapes, top_k),
                "planes": self._top(self._planes, top_k),
                "trackedTenants": len(self._tenants),
                "trackedShapes": len(self._shapes),
                "trackedPlanes": len(self._planes),
            }

    @staticmethod
    def _top(table: dict, top_k: int) -> dict:
        rows = sorted(table.items(), key=lambda kv: -kv[1][0])
        out = {}
        other = [0.0, 0, 0]
        for i, (key, (sec, nbytes, items)) in enumerate(rows):
            if i < top_k:
                out[key] = {"deviceSeconds": round(sec, 6),
                            "bytes": int(nbytes), "items": items}
            else:
                other[0] += sec
                other[1] += nbytes
                other[2] += items
        if other[2]:
            out["other"] = {"deviceSeconds": round(other[0], 6),
                            "bytes": int(other[1]), "items": other[2]}
        return out


def _mono() -> float:
    import time
    return time.monotonic()


class NullLedger:
    """Ledger-shaped nothing (instrumentation-off benches)."""

    windows = 0
    solo_dispatches = 0

    def charge_window(self, wall_seconds, entries) -> None:
        pass

    def charge_solo(self, *a, **k) -> None:
        pass

    def note_compile(self, *a, **k) -> None:
        pass

    def recent_seconds(self, tenant: str) -> float:
        return 0.0

    def trace_seconds(self, trace_id):
        return None

    def payload(self, top_k: int = 5) -> dict:
        return {"windows": 0, "soloDispatches": 0,
                "deviceSecondsTotal": 0.0, "bytesScannedTotal": 0,
                "compileSecondsTotal": 0.0, "compileCount": 0,
                "tenants": {}, "shapes": {}, "planes": {},
                "trackedTenants": 0, "trackedShapes": 0,
                "trackedPlanes": 0}


NULL_LEDGER = NullLedger()
