"""Metrics: counters, gauges, histograms with Prometheus text export.

Reference: ``stats.go#StatsClient`` (Count/Gauge/Timing/Histogram/
WithTags; SURVEY.md §3.3) with statsd/expvar/prometheus backends.  The
rebuild keeps one in-process registry exporting the Prometheus text
format at ``/metrics`` (the v2-era surface); a ``NopStats`` mirrors the
reference's nop client for tests.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _labels_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Stats:
    """In-process metrics registry.  Thread-safe; cheap enough for the
    query path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._gauges: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._hists: dict[str, dict[tuple, list]] = defaultdict(dict)

    # -- StatsClient surface (reference parity) -----------------------------

    def count(self, name: str, value: float = 1, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            m = self._counters[name]
            m[key] = m.get(key, 0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[name][_labels_key(labels)] = value

    def observe(self, name: str, value: float, **labels) -> None:
        """Histogram observation (reference: Timing/Histogram)."""
        key = _labels_key(labels)
        with self._lock:
            h = self._hists[name].get(key)
            if h is None:
                # [bucket counts..., +inf count, sum, total]
                h = self._hists[name][key] = [0] * (len(_BUCKETS) + 1) + [0.0, 0]
            for i, ub in enumerate(_BUCKETS):
                if value <= ub:
                    h[i] += 1
                    break
            else:
                h[len(_BUCKETS)] += 1
            h[-2] += value
            h[-1] += 1

    def timing(self, name: str, seconds: float, **labels) -> None:
        self.observe(name, seconds, **labels)

    # -- export -------------------------------------------------------------

    def histogram_summary(self, name: str) -> dict:
        """Compact per-label view of one histogram family:
        ``{label: {count, sum, mean}}`` — the ``diagnostics`` dump of
        the per-stage query timers (``query_stage_seconds``), cheap
        enough for ``/status`` consumers that don't want the full
        Prometheus bucket text."""
        with self._lock:
            fam = self._hists.get(name)
            if not fam:
                return {}
            out = {}
            for key, h in sorted(fam.items()):
                label = ",".join(f"{k}={v}" for k, v in key) or "total"
                n = h[-1]
                out[label] = {"count": n, "sum": round(h[-2], 6),
                              "mean": round(h[-2] / n, 6) if n else 0.0}
            return out

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {n: dict(m) for n, m in self._counters.items()},
                "gauges": {n: dict(m) for n, m in self._gauges.items()},
            }

    def prometheus_text(self) -> str:
        out = []
        with self._lock:
            for name, m in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._hists.items()):
                out.append(f"# TYPE {name} histogram")
                for key, h in sorted(m.items()):
                    cum = 0
                    for i, ub in enumerate(_BUCKETS):
                        cum += h[i]
                        lk = key + (("le", repr(ub)),)
                        out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}")
                    cum += h[len(_BUCKETS)]
                    lk = key + (("le", "+Inf"),)
                    out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}")
                    out.append(f"{name}_sum{_fmt_labels(key)} {h[-2]}")
                    out.append(f"{name}_count{_fmt_labels(key)} {h[-1]}")
        return "\n".join(out) + "\n"


class StatsdStats(Stats):
    """Stats registry that ALSO emits every observation as a statsd
    UDP packet (reference: ``statsd.go#statsdClient`` behind the
    StatsClient interface).  DogStatsD wire format with tag support::

        pilosa.query_seconds:12.3|ms|#call:Count

    Subclassing keeps the in-process registry authoritative —
    ``/metrics`` Prometheus text and ``/status`` summaries are
    unchanged; statsd is an additional sink.  Emission is fire-and-
    forget UDP: a missing/slow collector can never stall the serving
    path (send errors are counted, not raised)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa"):
        super().__init__()
        import socket
        self._addr = (host, port)
        self._prefix = (prefix + ".") if prefix else ""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self.send_errors = 0

    @staticmethod
    def _tags(labels: dict) -> str:
        if not labels:
            return ""
        inner = ",".join(f"{k}:{v}" for k, v in sorted(labels.items()))
        return "|#" + inner

    def _emit(self, name: str, value, kind: str, labels: dict) -> None:
        pkt = (f"{self._prefix}{name}:{value}|{kind}"
               f"{self._tags(labels)}").encode()
        try:
            self._sock.sendto(pkt, self._addr)
        except OSError:
            self.send_errors += 1

    def count(self, name: str, value: float = 1, **labels) -> None:
        super().count(name, value, **labels)
        self._emit(name, value, "c", labels)

    def gauge(self, name: str, value: float, **labels) -> None:
        super().gauge(name, value, **labels)
        self._emit(name, value, "g", labels)

    def observe(self, name: str, value: float, **labels) -> None:
        super().observe(name, value, **labels)
        # statsd timers are milliseconds by convention
        self._emit(name, round(value * 1000.0, 6), "ms", labels)

    def close(self) -> None:
        self._sock.close()


class NopStats:
    """No-op client (reference: ``nopStatsClient``)."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def histogram_summary(self, name):
        return {}

    def snapshot(self):
        return {"counters": {}, "gauges": {}}

    def prometheus_text(self):
        return ""


class StageTimer:
    """Per-request overhead attribution: ``mark(stage)`` charges the
    monotonic time since the previous mark to that stage as one
    ``query_stage_seconds{stage=...}`` histogram observation.

    Stages on the serving path: ``admit`` (execution-slot acquisition +
    recovery gate), ``parse`` (PQL text → AST), ``plan`` (AST → leaf
    arrays/program structure, incl. plan-cache validation), ``dispatch``
    (program enqueue), ``read`` (device → host; on batcher-coalesced
    requests the whole coalesced wait — window + dispatch + read — is
    charged here, there is no per-request dispatch to time), and
    ``assemble`` (host result construction).  The per-stage sums are
    the attribution bench/config18 prints — the residual product/raw
    concurrency gap is measured per stage, not guessed.

    With a ``tracer`` attached, every mark ALSO lands as a completed
    ``stage.<name>`` child span under the innermost open span of the
    traced query — the per-stage children a distributed profile tree
    carries on every node (no-op outside any span)."""

    __slots__ = ("_stats", "_metric", "_last", "tracer")

    def __init__(self, stats, metric: str = "query_stage_seconds",
                 tracer=None):
        self._stats = stats
        self._metric = metric
        self.tracer = tracer
        self._last = time.perf_counter()

    def mark(self, stage: str) -> None:
        now = time.perf_counter()
        self._stats.observe(self._metric, now - self._last, stage=stage)
        if self.tracer is not None:
            self.tracer.stage("stage." + stage, now - self._last)
        self._last = now

    def reset(self) -> None:
        """Restart the clock without charging anything (skip a gap that
        belongs to no stage)."""
        self._last = time.perf_counter()
