"""Metrics: counters, gauges, histograms with Prometheus text export.

Reference: ``stats.go#StatsClient`` (Count/Gauge/Timing/Histogram/
WithTags; SURVEY.md §3.3) with statsd/expvar/prometheus backends.  The
rebuild keeps one in-process registry exporting the Prometheus text
format at ``/metrics`` (the v2-era surface); a ``NopStats`` mirrors the
reference's nop client for tests.
"""

from __future__ import annotations

import threading
from collections import defaultdict

_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _labels_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


class Stats:
    """In-process metrics registry.  Thread-safe; cheap enough for the
    query path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._gauges: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._hists: dict[str, dict[tuple, list]] = defaultdict(dict)

    # -- StatsClient surface (reference parity) -----------------------------

    def count(self, name: str, value: float = 1, **labels) -> None:
        key = _labels_key(labels)
        with self._lock:
            m = self._counters[name]
            m[key] = m.get(key, 0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[name][_labels_key(labels)] = value

    def observe(self, name: str, value: float, **labels) -> None:
        """Histogram observation (reference: Timing/Histogram)."""
        key = _labels_key(labels)
        with self._lock:
            h = self._hists[name].get(key)
            if h is None:
                # [bucket counts..., +inf count, sum, total]
                h = self._hists[name][key] = [0] * (len(_BUCKETS) + 1) + [0.0, 0]
            for i, ub in enumerate(_BUCKETS):
                if value <= ub:
                    h[i] += 1
                    break
            else:
                h[len(_BUCKETS)] += 1
            h[-2] += value
            h[-1] += 1

    def timing(self, name: str, seconds: float, **labels) -> None:
        self.observe(name, seconds, **labels)

    # -- export -------------------------------------------------------------

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {n: dict(m) for n, m in self._counters.items()},
                "gauges": {n: dict(m) for n, m in self._gauges.items()},
            }

    def prometheus_text(self) -> str:
        out = []
        with self._lock:
            for name, m in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._hists.items()):
                out.append(f"# TYPE {name} histogram")
                for key, h in sorted(m.items()):
                    cum = 0
                    for i, ub in enumerate(_BUCKETS):
                        cum += h[i]
                        lk = key + (("le", repr(ub)),)
                        out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}")
                    cum += h[len(_BUCKETS)]
                    lk = key + (("le", "+Inf"),)
                    out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}")
                    out.append(f"{name}_sum{_fmt_labels(key)} {h[-2]}")
                    out.append(f"{name}_count{_fmt_labels(key)} {h[-1]}")
        return "\n".join(out) + "\n"


class NopStats:
    """No-op client (reference: ``nopStatsClient``)."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def snapshot(self):
        return {"counters": {}, "gauges": {}}

    def prometheus_text(self):
        return ""
