"""Metrics: counters, gauges, histograms with Prometheus text export.

Reference: ``stats.go#StatsClient`` (Count/Gauge/Timing/Histogram/
WithTags; SURVEY.md §3.3) with statsd/expvar/prometheus backends.  The
rebuild keeps one in-process registry exporting the Prometheus text
format at ``/metrics`` (the v2-era surface); a ``NopStats`` mirrors the
reference's nop client for tests.

r14 (the cluster-observability pane, ISSUE 9) adds:

- **per-family bucket sets** (:meth:`Stats.set_buckets`): byte- and
  count-scale histogram families stop reusing the latency buckets
  (``BYTE_BUCKETS``/``COUNT_BUCKETS``/``RATIO_BUCKETS`` presets);
- **label-value escaping** per the Prometheus exposition rules
  (``\\``, ``"``, newline) — a PQL-derived label can no longer corrupt
  the scrape document;
- **trace exemplars**: ``observe(..., trace_id=...)`` remembers the
  latest (trace id, value, timestamp) per bucket and renders it as an
  OpenMetrics exemplar after the bucket line, so a p99 bucket names a
  trace id — resolvable at ``/internal/traces?trace_id=`` whenever
  that query's trace was RETAINED (sampled, profiled, or
  slow-captured; a fast unsampled query's exemplar is best-effort:
  its id is real but its trace was never ring-buffered);
- **cluster fan-in merge** (:func:`render_cluster_metrics`): per-node
  registry snapshots (:meth:`Stats.full_snapshot`) merge into ONE
  Prometheus document — counters/gauges keep per-node series under a
  ``node`` label, histograms merge bucket-wise (exact: counts are
  per-bucket sums) when every node agrees on the family's buckets and
  fall back to node-labeled series when they don't (version skew).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict

_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
            0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# per-family bucket presets (set_buckets): device-plane telemetry spans
# bytes (KB..64GB scans), item counts (coalescing-window occupancy) and
# ratios (window fill) — none of which the latency default resolves
BYTE_BUCKETS = (1 << 10, 1 << 14, 1 << 17, 1 << 20, 1 << 23, 1 << 26,
                1 << 28, 1 << 30, 1 << 32, 1 << 34, 1 << 36)
COUNT_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0)
RATIO_BUCKETS = (0.01, 0.05, 0.1, 0.25, 0.5, 0.75, 0.9, 1.0)
# small structural depths (compound-tree nesting, r16): the interesting
# range is 1..8 with single-level resolution at the shallow end
DEPTH_BUCKETS = (1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0)

# synthetic families emitted only in the CLUSTER document (rendered by
# render_cluster_metrics, not observed through a registry).  Module
# constants so the metrics-inventory drift check can enumerate them.
CLUSTER_NODE_UP = "cluster_metrics_node_up"
CLUSTER_STALE_NODES = "cluster_metrics_stale_nodes"
# StageTimer's default histogram family (referenced via this constant,
# not a literal call site)
STAGE_METRIC = "query_stage_seconds"

# -- label-cardinality bounds (r19 satellite) ---------------------------------
#
# A label whose values the USER controls (tenant = index name, peer =
# node id) grows one series per distinct value forever — a churny
# multi-tenant deployment turns `tenant_shed_total{tenant}` into an
# unbounded scrape.  Families listed here are capped at registry level:
# the first K distinct values of the bounded label keep their own
# series, every later value folds into the ``other`` series.  The
# capped rollup stays a faithful TOTAL (folding moves a count between
# series, it never drops one); per-entity detail for the long tail
# lives in the /status blocks, which are maps, not scrape series.
#
# Module constant (family -> (label, K)) so the metrics-inventory
# cardinality lint can enforce that every family with a user-controlled
# label declares its bound here.
DEFAULT_LABEL_BOUND = 32
OTHER_LABEL = "other"
BOUNDED_LABELS: dict[str, tuple[str, int]] = {
    # per-tenant families (tenant = index name: user-controlled)
    "tenant_shed_total": ("tenant", DEFAULT_LABEL_BOUND),
    "tenant_device_seconds_total": ("tenant", DEFAULT_LABEL_BOUND),
    "tenant_device_bytes_total": ("tenant", DEFAULT_LABEL_BOUND),
    # per-plane ledger rollup (plane key derives from index/field names)
    "plane_device_seconds_total": ("plane", DEFAULT_LABEL_BOUND),
    # per-peer families (node ids churn across replaces/restarts)
    "hint_handoff_total": ("peer", 64),
    "hint_appended_total": ("peer", 64),
    "hint_replay_dropped_total": ("peer", 64),
    "hint_backlog_ops": ("peer", 64),
    "read_failover_total": ("peer", 64),
    "read_hedged_total": ("peer", 64),
    "peer_breaker_state": ("peer", 64),
    "breaker_transitions_total": ("peer", 64),
}


def escape_label_value(v) -> str:
    """Prometheus exposition escaping for label VALUES: backslash,
    double quote, and newline must be escaped or a hostile value (PQL
    text, a key) corrupts the whole scrape document."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _labels_key(labels: dict[str, str]) -> tuple:
    return tuple(sorted(labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in key)
    return "{" + inner + "}"


class Stats:
    """In-process metrics registry.  Thread-safe; cheap enough for the
    query path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._gauges: dict[str, dict[tuple, float]] = defaultdict(dict)
        self._hists: dict[str, dict[tuple, list]] = defaultdict(dict)
        # family -> bucket upper bounds (default _BUCKETS); latched at
        # set_buckets or first observation
        self._hist_buckets: dict[str, tuple] = {}
        # (family, labels-key) -> {bucket index: (trace_id, value, ts)}
        # — the LATEST exemplar per bucket, bounded per series by the
        # bucket count
        self._exemplars: dict[tuple, dict[int, tuple]] = {}
        # label-cardinality caps: (family, label) -> K, plus the set of
        # label values already holding their own series
        self._label_bounds: dict[tuple, int] = {
            (fam, lab): k for fam, (lab, k) in BOUNDED_LABELS.items()}
        self._label_seen: dict[tuple, set] = {}

    def bound_label(self, name: str, label: str,
                    top_k: int = DEFAULT_LABEL_BOUND) -> None:
        """Cap one family's label cardinality: the first ``top_k``
        distinct values of ``label`` keep their own series; later
        values fold into the ``other`` series.  Families in
        :data:`BOUNDED_LABELS` are capped automatically."""
        with self._lock:
            self._label_bounds[(name, label)] = int(top_k)

    def _cap(self, name: str, labels: dict) -> dict:
        """Fold over-cardinality label values into ``other``.  Caller
        holds the lock; ``labels`` is the call's own kwargs dict, so
        in-place mutation is safe."""
        for lab in labels:
            k = self._label_bounds.get((name, lab))
            if k is None:
                continue
            v = str(labels[lab])
            if v == OTHER_LABEL:
                continue
            seen = self._label_seen.setdefault((name, lab), set())
            if v in seen:
                continue
            if len(seen) < k:
                seen.add(v)
            else:
                labels[lab] = OTHER_LABEL
        return labels

    # -- StatsClient surface (reference parity) -----------------------------

    def count(self, name: str, value: float = 1, **labels) -> None:
        with self._lock:
            key = _labels_key(self._cap(name, labels) if labels
                              else labels)
            m = self._counters[name]
            m[key] = m.get(key, 0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            key = _labels_key(self._cap(name, labels) if labels
                              else labels)
            self._gauges[name][key] = value

    def set_buckets(self, name: str, buckets: tuple) -> None:
        """Declare one family's histogram buckets (upper bounds,
        ascending).  Idempotent for an identical bucket set; changing
        the buckets of a family that already holds observations raises
        — re-bucketing recorded counts would fabricate history."""
        b = tuple(float(x) for x in buckets)
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"buckets for {name!r} must be ascending "
                             f"and non-empty: {buckets!r}")
        with self._lock:
            cur = self._hist_buckets.get(name)
            if cur == b:
                return
            if cur is not None or self._hists.get(name):
                raise ValueError(
                    f"histogram family {name!r} already has "
                    f"{'buckets' if cur else 'observations'}; cannot "
                    f"re-bucket")
            self._hist_buckets[name] = b

    def observe(self, name: str, value: float, trace_id: str | None = None,
                **labels) -> None:
        """Histogram observation (reference: Timing/Histogram).  With
        ``trace_id``, the observation is remembered as the bucket's
        OpenMetrics exemplar — the join point between a latency bucket
        and ``/internal/traces?trace_id=`` (the lite serving path
        passes its cheap trace id here; cost is one tuple write)."""
        with self._lock:
            key = _labels_key(self._cap(name, labels) if labels
                              else labels)
            buckets = self._hist_buckets.setdefault(name, _BUCKETS)
            h = self._hists[name].get(key)
            if h is None:
                # [bucket counts..., +inf count, sum, total]
                h = self._hists[name][key] = \
                    [0] * (len(buckets) + 1) + [0.0, 0]
            for i, ub in enumerate(buckets):
                if value <= ub:
                    h[i] += 1
                    break
            else:
                i = len(buckets)
                h[i] += 1
            h[-2] += value
            h[-1] += 1
            if trace_id is not None:
                ex = self._exemplars.get((name, key))
                if ex is None:
                    ex = self._exemplars[(name, key)] = {}
                ex[i] = (trace_id, value, time.time())

    def timing(self, name: str, seconds: float,
               trace_id: str | None = None, **labels) -> None:
        self.observe(name, seconds, trace_id=trace_id, **labels)

    # -- export -------------------------------------------------------------

    def histogram_summary(self, name: str) -> dict:
        """Compact per-label view of one histogram family:
        ``{label: {count, sum, mean}}`` — the ``diagnostics`` dump of
        the per-stage query timers (``query_stage_seconds``), cheap
        enough for ``/status`` consumers that don't want the full
        Prometheus bucket text.  Distinct label SETS that stringify to
        the same display label (a collision) merge their counts and
        sums rather than silently dropping one."""
        with self._lock:
            fam = self._hists.get(name)
            if not fam:
                return {}
            merged: dict[str, list] = {}
            for key, h in sorted(fam.items()):
                label = ",".join(f"{k}={v}" for k, v in key) or "total"
                agg = merged.setdefault(label, [0, 0.0])
                agg[0] += h[-1]
                agg[1] += h[-2]
            return {label: {"count": n, "sum": round(s, 6),
                            "mean": round(s / n, 6) if n else 0.0}
                    for label, (n, s) in merged.items()}

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": {n: dict(m) for n, m in self._counters.items()},
                "gauges": {n: dict(m) for n, m in self._gauges.items()},
            }

    def full_snapshot(self) -> dict:
        """JSON-ready dump of the WHOLE registry — counters, gauges and
        histograms with their bucket boundaries and raw (non-cumulative)
        bucket counts.  This is the ``/internal/metrics/snapshot`` wire
        payload the cluster fan-in merges; bucket counts ride raw so
        the merge is an element-wise sum (bucket-exact)."""
        with self._lock:
            hists = {}
            for name, fam in self._hists.items():
                b = self._hist_buckets.get(name, _BUCKETS)
                hists[name] = {
                    "buckets": [float(x) for x in b],
                    "series": [{"labels": dict(key),
                                "counts": [int(c) for c in h[:len(b) + 1]],
                                "sum": float(h[-2]), "count": int(h[-1])}
                               for key, h in sorted(fam.items())]}
            return {
                "counters": {n: [{"labels": dict(k), "value": v}
                                 for k, v in sorted(m.items())]
                             for n, m in self._counters.items()},
                "gauges": {n: [{"labels": dict(k), "value": v}
                               for k, v in sorted(m.items())]
                           for n, m in self._gauges.items()},
                "histograms": hists,
            }

    def prometheus_text(self, openmetrics: bool = False) -> str:
        """Registry as exposition text.  The default is the classic
        Prometheus 0.0.4 format, which allows ONLY ``metric value
        [timestamp]`` per sample line — an exemplar suffix there is a
        parse error that fails the whole scrape — so exemplars render
        ONLY when ``openmetrics`` is set (the ``/metrics`` handler sets
        it when the scraper's Accept header negotiates
        ``application/openmetrics-text``); OpenMetrics output also
        terminates with the mandatory ``# EOF``."""
        out = []
        with self._lock:
            for name, m in sorted(self._counters.items()):
                out.append(f"# TYPE {name} counter")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._gauges.items()):
                out.append(f"# TYPE {name} gauge")
                for key, v in sorted(m.items()):
                    out.append(f"{name}{_fmt_labels(key)} {v}")
            for name, m in sorted(self._hists.items()):
                buckets = self._hist_buckets.get(name, _BUCKETS)
                out.append(f"# TYPE {name} histogram")
                for key, h in sorted(m.items()):
                    ex = (self._exemplars.get((name, key), {})
                          if openmetrics else {})
                    _render_hist_series(out, name, key, buckets,
                                        h, h[-2], h[-1], ex)
        if openmetrics:
            out.append("# EOF")
        return "\n".join(out) + "\n"


def _render_hist_series(out: list, name: str, key: tuple, buckets,
                        counts, total: float, count: int,
                        exemplars: dict | None = None) -> None:
    """Append one histogram series' cumulative ``_bucket`` /``_sum``/
    ``_count`` exposition lines — the ONE place the cumulative-bucket
    encoding lives, shared by the single-node document and both
    branches (merged / bucket-skew) of the cluster document.
    ``counts`` holds raw per-bucket counts with +Inf at index
    ``len(buckets)`` (trailing entries beyond that are ignored, so a
    registry's ``[counts..., sum, total]`` row can be passed as-is)."""
    ex = exemplars or {}
    cum = 0
    for i, ub in enumerate(buckets):
        cum += counts[i]
        lk = key + (("le", repr(ub)),)
        out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}"
                   + _fmt_exemplar(ex.get(i)))
    cum += counts[len(buckets)]
    lk = key + (("le", "+Inf"),)
    out.append(f"{name}_bucket{_fmt_labels(lk)} {cum}"
               + _fmt_exemplar(ex.get(len(buckets))))
    out.append(f"{name}_sum{_fmt_labels(key)} {total}")
    out.append(f"{name}_count{_fmt_labels(key)} {count}")


def _fmt_exemplar(ex: tuple | None) -> str:
    """OpenMetrics exemplar suffix for a bucket line:
    ``# {trace_id="..."} value timestamp`` (empty when the bucket has
    never seen a traced observation)."""
    if ex is None:
        return ""
    trace_id, value, ts = ex
    return (f' # {{trace_id="{escape_label_value(trace_id)}"}} '
            f"{value} {round(ts, 3)}")


# -- cluster fan-in merge -----------------------------------------------------


def render_cluster_metrics(snaps: dict[str, dict],
                           stale: list[str] | tuple = ()) -> str:
    """ONE Prometheus document for the whole fleet from per-node
    :meth:`Stats.full_snapshot` payloads.

    Merge rules (the single-pane contract):

    - counters and gauges keep ONE series per node, the node id added
      as a ``node`` label (summing gauges across nodes is usually
      wrong, and per-node counters are what an operator diffs);
    - histograms merge BUCKET-WISE across nodes per label set — counts
      are element-wise sums, so the merged distribution is exact, not
      an approximation — whenever every reporting node agrees on the
      family's bucket boundaries; disagreeing families (version skew
      mid-rollout) degrade to per-node series under a ``node`` label
      instead of fabricating a merge;
    - ``cluster_metrics_node_up{node=...}`` gauges (1 fetched / 0
      stale) and a ``cluster_metrics_stale_nodes`` count make partial
      documents self-describing: a scrape through a dead peer is
      degraded, never an error.
    """
    out = [f"# pilosa-tpu cluster metrics: {len(snaps)} node(s), "
           f"{len(stale)} stale"]
    out.append(f"# TYPE {CLUSTER_NODE_UP} gauge")
    for nid in sorted(snaps):
        out.append(f'{CLUSTER_NODE_UP}{{node="{escape_label_value(nid)}"}} 1')
    for nid in sorted(stale):
        out.append(f'{CLUSTER_NODE_UP}{{node="{escape_label_value(nid)}"}} 0')
    out.append(f"# TYPE {CLUSTER_STALE_NODES} gauge")
    out.append(f"{CLUSTER_STALE_NODES} {len(stale)}")

    for kind in ("counters", "gauges"):
        names = sorted({n for s in snaps.values() for n in s.get(kind, {})})
        ptype = "counter" if kind == "counters" else "gauge"
        for name in names:
            out.append(f"# TYPE {name} {ptype}")
            for nid in sorted(snaps):
                for series in snaps[nid].get(kind, {}).get(name, []):
                    key = _node_key(series["labels"], nid)
                    out.append(f"{name}{_fmt_labels(key)} "
                               f"{series['value']}")

    names = sorted({n for s in snaps.values()
                    for n in s.get("histograms", {})})
    for name in names:
        per_node = {nid: s["histograms"][name]
                    for nid, s in snaps.items()
                    if name in s.get("histograms", {})}
        out.append(f"# TYPE {name} histogram")
        bucket_sets = {tuple(f["buckets"]) for f in per_node.values()}
        if len(bucket_sets) == 1:
            buckets = bucket_sets.pop()
            merged: dict[tuple, list] = {}
            for fam in per_node.values():
                for series in fam["series"]:
                    key = _labels_key(series["labels"])
                    agg = merged.setdefault(
                        key, [[0] * (len(buckets) + 1), 0.0, 0])
                    for i, c in enumerate(series["counts"]):
                        agg[0][i] += c
                    agg[1] += series["sum"]
                    agg[2] += series["count"]
            for key, (counts, total, n) in sorted(merged.items()):
                _render_hist_series(out, name, key, buckets,
                                    counts, total, n)
        else:
            # bucket disagreement (mid-rollout skew): keep per-node
            # series — a wrong merge would be worse than no merge
            for nid in sorted(per_node):
                fam = per_node[nid]
                for series in fam["series"]:
                    _render_hist_series(out, name,
                                        _node_key(series["labels"], nid),
                                        fam["buckets"], series["counts"],
                                        series["sum"], series["count"])
    return "\n".join(out) + "\n"


def _node_key(labels: dict, nid: str) -> tuple:
    """Labels-key with the node id merged in (the fan-in's ``node``
    label wins over any same-named label a series already carried)."""
    return _labels_key({**labels, "node": nid})


class StatsdStats(Stats):
    """Stats registry that ALSO emits every observation as a statsd
    UDP packet (reference: ``statsd.go#statsdClient`` behind the
    StatsClient interface).  DogStatsD wire format with tag support::

        pilosa.query_seconds:12.3|ms|#call:Count

    Subclassing keeps the in-process registry authoritative —
    ``/metrics`` Prometheus text and ``/status`` summaries are
    unchanged; statsd is an additional sink.  Emission is fire-and-
    forget UDP: a missing/slow collector can never stall the serving
    path (send errors are counted, not raised)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 8125,
                 prefix: str = "pilosa"):
        super().__init__()
        import socket
        self._addr = (host, port)
        self._prefix = (prefix + ".") if prefix else ""
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.setblocking(False)
        self.send_errors = 0

    @staticmethod
    def _tags(labels: dict) -> str:
        if not labels:
            return ""
        inner = ",".join(f"{k}:{v}" for k, v in sorted(labels.items()))
        return "|#" + inner

    def _emit(self, name: str, value, kind: str, labels: dict) -> None:
        pkt = (f"{self._prefix}{name}:{value}|{kind}"
               f"{self._tags(labels)}").encode()
        try:
            self._sock.sendto(pkt, self._addr)
        except OSError:
            self.send_errors += 1

    def count(self, name: str, value: float = 1, **labels) -> None:
        super().count(name, value, **labels)
        self._emit(name, value, "c", labels)

    def gauge(self, name: str, value: float, **labels) -> None:
        super().gauge(name, value, **labels)
        self._emit(name, value, "g", labels)

    def observe(self, name: str, value: float, trace_id: str | None = None,
                **labels) -> None:
        super().observe(name, value, trace_id=trace_id, **labels)
        # statsd timers are milliseconds by convention (exemplars have
        # no statsd encoding; they live in the in-process registry) —
        # but only ``*_seconds`` families carry seconds; item-count,
        # ratio and byte histograms ship as DogStatsD histograms with
        # the raw value (a 1 GiB window is not a 1e12 ms timer)
        if name.endswith("_seconds"):
            self._emit(name, round(value * 1000.0, 6), "ms", labels)
        else:
            self._emit(name, value, "h", labels)

    def close(self) -> None:
        self._sock.close()


class NopStats:
    """No-op client (reference: ``nopStatsClient``)."""

    def count(self, *a, **k):
        pass

    def gauge(self, *a, **k):
        pass

    def observe(self, *a, **k):
        pass

    def timing(self, *a, **k):
        pass

    def set_buckets(self, *a, **k):
        pass

    def bound_label(self, *a, **k):
        pass

    def histogram_summary(self, name):
        return {}

    def snapshot(self):
        return {"counters": {}, "gauges": {}}

    def full_snapshot(self):
        return {"counters": {}, "gauges": {}, "histograms": {}}

    def prometheus_text(self, openmetrics: bool = False):
        return ""


class StageTimer:
    """Per-request overhead attribution: ``mark(stage)`` charges the
    monotonic time since the previous mark to that stage as one
    ``query_stage_seconds{stage=...}`` histogram observation.

    Stages on the serving path: ``admit`` (execution-slot acquisition +
    recovery gate), ``parse`` (PQL text → AST), ``plan`` (AST → leaf
    arrays/program structure, incl. plan-cache validation), ``dispatch``
    (program enqueue), ``read`` (device → host; on batcher-coalesced
    requests the whole coalesced wait — window + dispatch + read — is
    charged here, there is no per-request dispatch to time), and
    ``assemble`` (host result construction).  The per-stage sums are
    the attribution bench/config18 prints — the residual product/raw
    concurrency gap is measured per stage, not guessed.

    With a ``tracer`` attached, every mark ALSO lands as a completed
    ``stage.<name>`` child span under the innermost open span of the
    traced query — the per-stage children a distributed profile tree
    carries on every node (no-op outside any span) — and the query's
    trace id (a LiteTracer's cheap id, or the open root span's) rides
    every observation as the bucket's exemplar, so a slow bucket on
    ``/metrics`` names a trace an operator can resolve whenever the
    retention policy kept it (sampled/profiled/slow-captured — a fast
    unsampled query's exemplar id was never ring-buffered)."""

    __slots__ = ("_stats", "_metric", "_last", "tracer", "trace_id")

    def __init__(self, stats, metric: str = STAGE_METRIC,
                 tracer=None):
        self._stats = stats
        self._metric = metric
        self.tracer = tracer
        tid = getattr(tracer, "trace_id", None)
        if tid is None and tracer is not None:
            cur = tracer.current_span()
            tid = cur.trace_id if cur is not None else None
        self.trace_id = tid
        self._last = time.perf_counter()

    def mark(self, stage: str) -> None:
        now = time.perf_counter()
        self._stats.observe(self._metric, now - self._last,
                            trace_id=self.trace_id, stage=stage)
        if self.tracer is not None:
            self.tracer.stage("stage." + stage, now - self._last)
        self._last = now

    def reset(self) -> None:
        """Restart the clock without charging anything (skip a gap that
        belongs to no stage)."""
        self._last = time.perf_counter()
