"""Diagnostics: periodic anonymized usage snapshot + version check.

Reference: ``diagnostics.go`` (SURVEY.md §3.3) — an opt-out phone-home
in upstream.  This rebuild inverts the default (opt-IN, and this image
has no egress anyway): the reporter builds the same shaped payload and
hands it to a pluggable ``send`` callable; the default sink writes to
the logger at debug level.  The payload builder is exercised by tests
and by ``/status`` consumers.
"""

from __future__ import annotations

import threading
import time

from pilosa_tpu import __version__


def build_payload(holder, cluster=None, stats=None, slow_log=None,
                  executor=None) -> dict:
    """Anonymized usage snapshot (counts only, no names/keys).  With
    ``stats``, includes the per-stage query-overhead summary
    (``query_stage_seconds``) so a payload doubles as the serving-path
    attribution dump; with ``slow_log`` (a
    :class:`pilosa_tpu.obs.SlowQueryLog`), the slow-query counters
    (totals and slowest only — never PQL text, which may carry keys);
    with ``executor`` (a meshed :class:`pilosa_tpu.exec.Executor`),
    the ``mesh`` serving block (device count, shard axis, per-device
    resident plane bytes, padded-shard count — byte counts only,
    never data)."""
    n_fields = 0
    n_shards = 0
    field_types: dict[str, int] = {}
    for idx in holder.indexes.values():
        for fname, f in idx.fields.items():
            if fname.startswith("_"):
                continue
            n_fields += 1
            field_types[f.options.type] = \
                field_types.get(f.options.type, 0) + 1
        n_shards += len(idx.available_shards())
    payload = {
        "version": __version__,
        "numIndexes": len(holder.indexes),
        "numFields": n_fields,
        "numShards": n_shards,
        "fieldTypes": field_types,
        "numNodes": len(cluster.member_ids()) if cluster else 1,
    }
    if cluster is not None:
        # counts-only summaries of the PR 6/8 subsystems (never peer
        # ids/addresses — the payload stays anonymized): how many peers
        # look sick, how much hinted-write backlog is pending
        try:
            peers = cluster.health_payload().get("peers", [])
            payload["clusterHealth"] = {
                "peers": len(peers),
                "suspect": sum(1 for p in peers if p.get("suspect")),
                "breakersOpen": sum(1 for p in peers
                                    if p.get("breaker") == "open")}
        except Exception:  # noqa: BLE001
            pass
        try:
            wh = cluster.write_health_payload()
            payload["writeHealth"] = {
                "hintedHandoff": bool(wh.get("hintedHandoff")),
                "backlogOps": int(wh.get("hintBacklogOps", 0)),
                # r15 ingest: hinted BULK ops (import batches) pending
                # replay — counts only, never payloads
                "bulkOps": int(wh.get("hintBulkOps", 0)),
                "hintedPeers": len(wh.get("hintedPeers", ())),
                "oldestSeconds": float(wh.get("hintOldestSeconds", 0.0))}
        except Exception:  # noqa: BLE001
            pass
    if stats is not None:
        try:
            payload["queryStages"] = stats.histogram_summary(
                "query_stage_seconds")
        except Exception:  # noqa: BLE001
            pass
    if slow_log is not None:
        try:
            payload["slowQueries"] = slow_log.summary()
        except Exception:  # noqa: BLE001
            pass
    try:
        import jax
        payload["deviceKind"] = jax.devices()[0].device_kind
        payload["numDevices"] = jax.device_count()
    except Exception:  # noqa: BLE001 — diagnostics must never break serving
        pass
    if executor is not None:
        try:
            mesh = executor.mesh_status()
            if mesh is not None:
                payload["mesh"] = mesh
        except Exception:  # noqa: BLE001 — diagnostics never break serving
            pass
        try:
            ten = executor.tenancy_status()
            tenants = ten.get("tenants", {})
            # counts only — tenant (index) names never leave the node
            payload["tenancy"] = {
                "paging": bool(ten.get("paging")),
                "tenants": len(tenants),
                "residentPages": sum(
                    int(t.get("residentPages", 0))
                    for t in tenants.values()),
                "pageIns": int(ten.get("pageIns", 0)),
                "evictions": int(ten.get("evictions", 0)),
                "sheds": int(ten.get("qos", {}).get("shedTotal", 0)),
            }
        except Exception:  # noqa: BLE001 — diagnostics never break serving
            pass
        try:
            costs = executor.cost_status()
            # counts-only here too: the ledger's per-tenant/shape/plane
            # breakdowns carry index and field names — only aggregate
            # totals and cardinalities leave the node
            payload["costs"] = {
                "windows": int(costs.get("windows", 0)),
                "soloDispatches": int(costs.get("soloDispatches", 0)),
                "deviceSecondsTotal": float(
                    costs.get("deviceSecondsTotal", 0.0)),
                "bytesScannedTotal": int(
                    costs.get("bytesScannedTotal", 0)),
                "compileSecondsTotal": float(
                    costs.get("compileSecondsTotal", 0.0)),
                "compileCount": int(costs.get("compileCount", 0)),
                "tenants": int(costs.get("trackedTenants", 0)),
                "shapes": int(costs.get("trackedShapes", 0)),
                "planes": int(costs.get("trackedPlanes", 0)),
            }
        except Exception:  # noqa: BLE001 — diagnostics never break serving
            pass
    return payload


class Diagnostics:
    """Periodic reporter; disabled unless an interval > 0 is given
    (upstream default-on behavior deliberately inverted)."""

    def __init__(self, holder, cluster=None, interval: float = 0.0,
                 send=None, logger=None, stats=None, slow_log=None,
                 executor=None):
        self.holder = holder
        self.cluster = cluster
        self.stats = stats
        self.slow_log = slow_log
        self.executor = executor
        self.interval = interval
        self.send = send or self._log_sink
        self.logger = logger
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _log_sink(self, payload: dict) -> None:
        if self.logger is not None:
            self.logger.debug("diagnostics: %s", payload)

    def start(self) -> "Diagnostics":
        if self.interval > 0:
            self._thread = threading.Thread(target=self._loop,
                                            name="pilosa-diagnostics",
                                            daemon=True)
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                self.send(build_payload(self.holder, self.cluster,
                                        stats=self.stats,
                                        slow_log=self.slow_log,
                                        executor=self.executor))
            except Exception:  # noqa: BLE001
                pass

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
