"""Command line: ``python -m pilosa_tpu.cli <command>``.

Reference: ``cmd/`` cobra commands → ``ctl/`` implementations
(SURVEY.md §3.3): server, import, export, backup, restore, check,
config, generate-config, version.  argparse subcommands; client-side
commands talk HTTP to a running server.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys

from pilosa_tpu import __version__
from pilosa_tpu.cli import config as cfgmod


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--config", help="TOML config file")
    p.add_argument("--bind", help="host:port to serve on / connect to")
    p.add_argument("--data-dir", dest="data_dir", help="storage directory")
    p.add_argument("--grpc-bind", dest="grpc_bind",
                   help="host:port for the gRPC surface (default off)")
    p.add_argument("--verbose", action="store_true", default=None)
    p.add_argument("--tls-certificate", dest="tls_certificate",
                   help="PEM certificate; enables TLS on every surface")
    p.add_argument("--tls-key", dest="tls_key", help="PEM private key")
    p.add_argument("--tls-ca-certificate", dest="tls_ca_certificate",
                   help="CA bundle for verifying peers")
    p.add_argument("--tls-skip-verify", dest="tls_skip_verify",
                   action="store_true", default=None,
                   help="outbound: accept any server certificate")
    p.add_argument("--tls-enable-client-auth",
                   dest="tls_enable_client_auth", action="store_true",
                   default=None, help="inbound: require client certs")


_CLI_KEYS = ("bind", "data_dir", "verbose", "grpc_bind",
             "tls_certificate", "tls_key", "tls_ca_certificate",
             "tls_skip_verify", "tls_enable_client_auth")


def _load_cfg(args) -> cfgmod.Config:
    overrides = {k: getattr(args, k, None) for k in _CLI_KEYS}
    return cfgmod.load(args.config, overrides=overrides)


def _client(cfg: cfgmod.Config):
    from pilosa_tpu.api.client import Client
    return Client(cfg.host, cfg.port,
                  ssl_context=cfgmod.client_ssl_of(cfg))


# -- commands ---------------------------------------------------------------


def cmd_server(args) -> int:
    cfg = _load_cfg(args)
    from pilosa_tpu.obs import get_logger
    log = get_logger(verbose=cfg.verbose)
    log.info("effective config: %s", json.dumps(cfg.effective()))

    from pilosa_tpu.server import PilosaTPUServer
    srv = PilosaTPUServer(cfg)
    srv.open()
    scheme = "https" if cfg.tls_certificate else "http"
    log.info("listening on %s://%s:%d data=%s", scheme, cfg.host,
             cfg.port, cfg.data_dir)
    stop = []
    signal.signal(signal.SIGINT, lambda *a: stop.append(1))
    signal.signal(signal.SIGTERM, lambda *a: stop.append(1))
    try:
        while not stop:
            signal.pause()
    finally:
        log.info("shutting down")
        srv.close()
    return 0


def cmd_import(args) -> int:
    """CSV import: ``row,col`` lines (or ``col,value`` with --value-field,
    keys auto-detected by the target field/index schema).  Reference:
    ``ctl/import.go`` batching."""
    cfg = _load_cfg(args)
    client = _client(cfg)
    if args.create:
        try:
            client.create_index(args.index, {"keys": args.keys})
        except Exception:
            pass
        try:
            opts = ({"type": "int"} if args.value else
                    {"keys": args.keys and not args.id_rows})
            client.create_field(args.index, args.field, opts)
        except Exception:
            pass

    schema = {i["name"]: i for i in client.schema()}
    if args.index not in schema:
        print(f"index {args.index!r} not found (use --create)",
              file=sys.stderr)
        return 1
    idx_keyed = schema[args.index]["options"]["keys"]
    fld = next((f for f in schema[args.index]["fields"]
                if f["name"] == args.field), None)
    if fld is None:
        print(f"field {args.field!r} not found (use --create)",
              file=sys.stderr)
        return 1
    fld_keyed = fld["options"]["keys"]

    src = open(args.file) if args.file != "-" else sys.stdin
    batch_rows, batch_cols, batch_vals = [], [], []
    totals = []
    # parallel batch submission (reference ctl/import.go streams batches
    # concurrently); server-side locks keep application correct
    from concurrent.futures import ThreadPoolExecutor
    pool = ThreadPoolExecutor(max_workers=max(1, args.workers))
    futures = []

    def submit(rows, cols, vals):
        ckey = "columnKeys" if idx_keyed else "columnIDs"
        if args.value:
            return client.import_values(
                args.index, args.field, **{ckey: cols, "values": vals})
        rkey = "rowKeys" if fld_keyed else "rowIDs"
        return client.import_bits(
            args.index, args.field, **{rkey: rows, ckey: cols})

    def flush():
        if not batch_cols:
            return
        futures.append(pool.submit(submit, list(batch_rows),
                                   list(batch_cols), list(batch_vals)))
        batch_rows.clear(), batch_cols.clear(), batch_vals.clear()

    for line in src:
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        a, b = line.split(",", 1)
        if args.value:
            batch_cols.append(a if idx_keyed else int(a))
            batch_vals.append(int(b))
        else:
            batch_rows.append(a if fld_keyed else int(a))
            batch_cols.append(b if idx_keyed else int(b))
        if len(batch_cols) >= args.batch_size:
            flush()
    flush()
    totals = [f.result() for f in futures]
    pool.shutdown()
    print(f"imported (changed {sum(totals)} bits/values)", file=sys.stderr)
    return 0


def cmd_export(args) -> int:
    cfg = _load_cfg(args)
    out = _client(cfg).export_csv(args.index, args.field)
    (open(args.output, "w") if args.output else sys.stdout).write(out)
    return 0


def cmd_backup(args) -> int:
    """Directory backup (manifest + per-fragment archives, incremental
    capable, works against a live cluster) — or, when ``--output`` ends
    in ``.tar``, the legacy single-node tar."""
    cfg = _load_cfg(args)
    if args.output.endswith(".tar"):
        client = _client(cfg)
        blob = client._do("GET", "/internal/backup")
        with open(args.output, "wb") as f:
            f.write(blob)
        print(f"wrote {len(blob)} bytes to {args.output}",
              file=sys.stderr)
        return 0
    from pilosa_tpu.api.client import ClientError
    from pilosa_tpu.backup import BackupDriver, BackupError, ManifestError
    drv = BackupDriver(cfg.host, cfg.port, args.output,
                       workers=args.workers,
                       incremental=args.incremental,
                       ssl_context=cfgmod.client_ssl_of(cfg))
    try:
        res = drv.run()
    except (BackupError, ManifestError, ClientError, OSError) as e:
        print(f"backup failed: {e}", file=sys.stderr)
        return 1
    print(f"backup: {res['fragments']} fragments "
          f"({len(res['transferred'])} transferred, "
          f"{len(res['skipped'])} skipped, "
          f"{res['fallbacks']} replica fallbacks), "
          f"{res['bytes']} bytes in {res['seconds']}s -> {args.output}",
          file=sys.stderr)
    return 0


def cmd_restore(args) -> int:
    """Restore a directory archive into a FRESH (possibly different-
    sized) cluster; a ``.tar``/file input takes the legacy tar path."""
    cfg = _load_cfg(args)
    if not os.path.exists(args.input):
        print(f"restore failed: no archive at {args.input!r}",
              file=sys.stderr)
        return 1
    if not os.path.isdir(args.input):
        client = _client(cfg)
        with open(args.input, "rb") as f:
            blob = f.read()
        client._do("POST", "/internal/restore", blob,
                   content_type="application/x-tar")
        print("restored", file=sys.stderr)
        return 0
    from pilosa_tpu.api.client import ClientError
    from pilosa_tpu.backup import (BackupError, DigestError,
                                   ManifestError, RestoreDriver)
    drv = RestoreDriver(cfg.host, cfg.port, args.input,
                        workers=args.workers,
                        ssl_context=cfgmod.client_ssl_of(cfg))
    try:
        res = drv.run()
    except (BackupError, DigestError, ManifestError, ClientError,
            OSError) as e:
        print(f"restore failed: {e}", file=sys.stderr)
        return 1
    print(f"restore: {res['fragments']} fragments "
          f"({res['pushes']} pushes) onto {res['nodes']} node(s), "
          f"{res['bytes']} bytes in {res['seconds']}s "
          f"(aae repaired {res['aaeRepaired']})", file=sys.stderr)
    return 0


def cmd_check(args) -> int:
    """Offline integrity check of a data dir (reference: ``pilosa
    check``/``inspect``): every fragment file parses, op-logs replay,
    BSI invariants hold."""
    cfg = _load_cfg(args)
    from pilosa_tpu.store import Holder
    problems = 0
    h = Holder(cfg.data_dir)
    try:
        h.open()
    except Exception as e:  # noqa: BLE001 — report, not crash
        print(f"FATAL: holder open failed: {e}")
        return 1
    for iname, idx in h.indexes.items():
        for fname, f in idx.fields.items():
            for vname, v in f.views.items():
                for shard, frag in v.fragments.items():
                    try:
                        # positions() forces FULL expansion — container
                        # bodies validate too, not just the directory
                        # the lazy mmap open parses
                        n = len(frag.positions())
                        print(f"ok {iname}/{fname}/{vname}/{shard}: "
                              f"{n} bits, {len(frag.row_ids())} rows, "
                              f"op_n={frag.op_n}")
                    except Exception as e:  # noqa: BLE001
                        problems += 1
                        print(f"BAD {iname}/{fname}/{vname}/{shard}: {e}")
    # r19: a corrupt snapshot no longer raises at open — it
    # quarantines (the node serves the fragment from replicas) — so
    # the offline check must read the registry too
    for entry in h.storage_health.quarantined_entries():
        problems += 1
        print(f"BAD {entry['path']}: quarantined "
              f"({entry['kind']}) {entry['detail']}")
    h.close()
    print(f"{problems} problems" if problems else "all fragments ok")
    return 1 if problems else 0


def cmd_config(args) -> int:
    print(json.dumps(_load_cfg(args).effective(), indent=2))
    return 0


def cmd_generate_config(args) -> int:
    cfg = cfgmod.Config()
    for f, v in cfg.effective().items():
        key = f.replace("_", "-")
        if isinstance(v, str):
            print(f'{key} = "{v}"')
        elif isinstance(v, bool):
            print(f"{key} = {str(v).lower()}")
        elif isinstance(v, list):
            print(f"{key} = {v!r}")
        else:
            print(f"{key} = {v}")
    return 0


def cmd_version(args) -> int:
    print(__version__)
    return 0


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="pilosa-tpu",
                                description="TPU-native bitmap index")
    sub = p.add_subparsers(dest="command", required=True)

    sp = sub.add_parser("server", help="run a node")
    _add_common(sp)
    sp.set_defaults(fn=cmd_server)

    sp = sub.add_parser("import", help="bulk import CSV")
    _add_common(sp)
    sp.add_argument("-i", "--index", required=True)
    sp.add_argument("-f", "--field", required=True)
    sp.add_argument("file", help="CSV path or - for stdin")
    sp.add_argument("--create", action="store_true",
                    help="create index/field if missing")
    sp.add_argument("--keys", action="store_true",
                    help="with --create: keyed index/field")
    sp.add_argument("--id-rows", action="store_true",
                    help="with --create --keys: rows stay integer ids")
    sp.add_argument("--value", action="store_true",
                    help="CSV is col,value for an int field")
    sp.add_argument("--batch-size", type=int, default=100_000)
    sp.add_argument("--workers", type=int, default=4,
                    help="concurrent import batches in flight")
    sp.set_defaults(fn=cmd_import)

    sp = sub.add_parser("export", help="export field as CSV")
    _add_common(sp)
    sp.add_argument("-i", "--index", required=True)
    sp.add_argument("-f", "--field", required=True)
    sp.add_argument("-o", "--output")
    sp.set_defaults(fn=cmd_export)

    sp = sub.add_parser(
        "backup", help="consistent online backup to a directory "
        "(or legacy tar when -o ends in .tar)")
    _add_common(sp)
    sp.add_argument("-o", "--output", required=True,
                    help="archive directory (manifest.json + fragment "
                         "files); a .tar path takes the legacy path")
    sp.add_argument("--workers", type=int, default=4,
                    help="parallel fragment transfers")
    sp.add_argument("--incremental", action="store_true",
                    help="diff against the output dir's prior manifest "
                         "and transfer only changed fragments")
    sp.set_defaults(fn=cmd_backup)

    sp = sub.add_parser(
        "restore", help="restore a backup directory into a fresh "
        "cluster (elastic: node count may differ), or a legacy tar")
    _add_common(sp)
    sp.add_argument("input", help="archive directory or legacy .tar")
    sp.add_argument("--workers", type=int, default=4,
                    help="parallel fragment pushes")
    sp.set_defaults(fn=cmd_restore)

    sp = sub.add_parser("check", help="offline data-dir integrity check")
    _add_common(sp)
    sp.set_defaults(fn=cmd_check)

    sp = sub.add_parser("config", help="print effective config")
    _add_common(sp)
    sp.set_defaults(fn=cmd_config)

    sp = sub.add_parser("generate-config", help="print default TOML")
    sp.set_defaults(fn=cmd_generate_config)

    sp = sub.add_parser("version", help="print version")
    sp.set_defaults(fn=cmd_version)
    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
