"""Command line + config (L6 of SURVEY.md §2)."""

from pilosa_tpu.cli.config import Config, load

__all__ = ["Config", "load"]
