"""Layered configuration: TOML file ⊕ ``PILOSA_*`` env vars ⊕ CLI flags.

Reference: ``server/config.go`` with cobra+viper layering (SURVEY.md
§3.3, §6): flags override env, env overrides file, file overrides
defaults.  One typed dataclass; ``effective()`` dumps the resolved
config the way the reference's startup log does.
"""

from __future__ import annotations

import dataclasses
import os
from dataclasses import dataclass, field as dc_field

ENV_PREFIX = "PILOSA_"


@dataclass
class Config:
    bind: str = "127.0.0.1:10101"
    data_dir: str = "~/.pilosa_tpu"
    verbose: bool = False
    # "text" (key=value lines) or "json": one JSON object per line
    # with the active trace id injected as ``traceId`` — the
    # correlated-logs leg of the observability pane (a latency
    # exemplar, its /internal/traces tree, and its log lines join
    # on one id)
    log_format: str = "text"
    fsync: bool = False
    # cluster
    name: str = ""                      # node id; default derived from bind
    seeds: list[str] = dc_field(default_factory=list)  # host:port of peers
    replicas: int = 1
    cluster_enabled: bool = False       # force cluster mode without seeds
                                        # (single seed node of a new cluster)
    anti_entropy_interval: float = 600.0  # seconds; 0 disables
    heartbeat_interval: float = 2.0
    # read availability (serving through failure):
    # replica-failover hops a fan-out read leg may take after a
    # transport-class failure before the query fails (reads are
    # idempotent by the internode contract; writes never fail over)
    failover_max_depth: int = 2
    # hedge a straggling fan-out leg onto a live replica after this
    # many seconds — first answer wins, the loser is abandoned.
    # 0 disables (default); 0.15 is the documented starting point for
    # sub-second read SLOs (≈ a few p99s of a healthy internode leg)
    hedge_after: float = 0.0
    # consecutive transport failures that OPEN a peer's circuit
    # breaker (open peers are skipped at read-routing time; half-open
    # probes ride the heartbeat loop)
    breaker_threshold: int = 3
    # write availability (durable hinted handoff): a write that finds
    # a replica down is applied on the live replicas and durably
    # hinted for the dead one, then replayed in order on rejoin.
    # hint_max_age bounds the handoff window (seconds): once a peer's
    # oldest pending hint outlives it, strict writes (Clear/ClearRow/
    # Store) flip back to loud 503 refusal and Set falls back to
    # AAE-only repair — the hint log cannot grow without bound.
    # <= 0 disables handoff entirely (the pre-r13 fail-fast contract).
    hint_max_age: float = 300.0
    # ops per replay POST when draining a peer's hint log
    hint_replay_batch: int = 256
    diagnostics_interval: float = 0.0   # opt-in usage snapshot; 0 = off
    # observability backends
    stats_backend: str = ""             # "" = in-process /metrics only;
                                        # "statsd" also emits UDP statsd
    statsd_address: str = "127.0.0.1:8125"
    # always-on tracing: every query runs under a per-request span tree
    # (X-Pilosa-Trace-Id on each response); this fraction of ordinary
    # queries is RETAINED in the /internal/traces ring without the
    # caller asking (profile=true and slow queries always retain)
    trace_sample_rate: float = 0.01
    # queries slower than this (seconds) are captured — PQL, shards,
    # duration, full span tree — behind GET /debug/slow; 0 disables
    slow_query_threshold: float = 1.0
    # fault injection (chaos testing): JSON list of failpoint specs,
    # armed at boot — see pilosa_tpu.fault.configure.  Usually set via
    # PILOSA_FAULTS; live arming uses POST /internal/fault instead.
    faults: str = ""
    # device
    # Cross-request coalescing window for concurrent dense reads
    # (Count, BSI aggregates, dense TopN, Distinct): "adaptive"
    # (default) grows the window under queue pressure and shrinks it to
    # 0 when traffic is solo; a number fixes the window in seconds;
    # 0/"off" disables coalescing entirely.
    count_batch_window: str = "adaptive"
    query_timeout: float = 0.0         # seconds per query; 0 = unlimited
                                       # (?timeout= overrides per request)
    plane_budget_bytes: int = 4 << 30
    # Ingest delta planes (r15): writes to a resident whole-view plane
    # absorb into a bounded device-side overlay the query kernels
    # merge at dispatch time (base⊕delta) — reads keep serving at the
    # ceiling with zero generation-stale rebuild stalls.
    # delta_buffer_cells bounds the overlay (changed 32-bit plane
    # words per plane; 0 disables = pre-r15 incremental scatter);
    # past delta_compact_fraction of that, a background compactor
    # folds the overlay into the base and swaps generations.
    delta_buffer_cells: int = 65536
    delta_compact_fraction: float = 0.5
    # Whole-tree query compilation (r16): compound boolean PQL
    # (Intersect/Union/Difference/Xor/Not/UnionRows trees, BSI range
    # leaf filters) compiles to ONE fused XLA program — rows gathered
    # from the resident plane as traced operands, ops folded as a
    # postfix program — with concurrent requests sharing one memory
    # pass per plane through the batcher window.  False restores the
    # pre-r16 op-at-a-time/generic path (the bench baseline).
    tree_fusion: bool = True
    # Persistent dispatch pipeline (r17): how many dispatched-but-
    # unread collection windows the batcher may run ahead — window N's
    # device compute overlaps window N-1's packed device→host read.
    # <=1 restores the serial dispatch→read loop.
    dispatch_pipeline_depth: int = 2
    # Solo fast lane (r17): width-1 requests with no queue pressure
    # skip window formation and dispatch inline on the caller thread
    # over donated ping-pong chains (pre-bound slot operands, standing
    # output slots) — the attack on the one-RPC-per-query solo floor.
    # False restores the always-windowed pre-r17 path.
    solo_fastlane: bool = True
    # Pipeline watchdog (r18): per-stage age bound (seconds) on every
    # in-flight batcher window.  A window stalled past it — hung XLA
    # compile, stalled dispatch, wedged device→host read — is
    # QUARANTINED: its items fail with a structured error naming the
    # stage, its pipeline slot is reclaimed, and the wedged stage
    # worker is superseded so unrelated queries keep serving.  Keep it
    # well above worst-case legitimate compiles (seconds at full
    # scale).  0 disables the monitor entirely (the pre-r18 contract:
    # no watchdog thread, unbounded dispatch waits).
    dispatch_watchdog_seconds: float = 30.0
    # Device health governor (r18): after consecutive dispatch faults
    # or a watchdog trip flip serving to DEGRADED (fast lane off,
    # pipelining off, windows executed inline per item on the proven
    # op-at-a-time fallback path), then — every this-many seconds —
    # admit ONE window back onto the fused pipeline as a probe;
    # success restores healthy serving.
    device_health_probe_seconds: float = 5.0
    # Serving kernel tier (r24): "xla" (default) compiles every fused
    # family through the XLA oracle tier; "pallas" routes the hottest
    # families (selected-row gather scans, whole-plane count chains,
    # filtered row-count reduces — delta-overlay variants included)
    # through hand-written Pallas TPU kernels.  Per-family fail-safe:
    # a family whose Pallas lowering fails falls back to XLA silently
    # (pallas_fallback_total counts it), and degraded serving always
    # runs the per-item XLA fallback whatever the tier.  On non-TPU
    # backends "pallas" resolves to "xla" unless the test-only
    # PILOSA_PALLAS_INTERPRET escape hatch forces interpret mode.
    kernel_tier: str = "xla"
    # On-device dispatch loops (r24): the batcher collapses a
    # collection window's same-shape selected-count groups into ONE
    # jitted fori_loop/scan dispatch over stacked operands instead of
    # one program launch per group (dispatch_loop_iters histogram
    # proves the collapse; per-item fallback covers failures).
    dispatch_loop_fusion: bool = False
    # Compile-ladder warm-up (r24): when a plane becomes resident, a
    # background single-flight warmer pre-compiles the delta-aware
    # fused program ladder (one program per pow2 overlay bucket per
    # family) OFF the serving path, so the first post-ingest query
    # hits a warm cache.  Compile seconds book into the cost ledger
    # under "warmup".  Single-device only (mesh placement disables).
    fused_warmup: bool = False
    # Storage integrity (r19).  Background scrubber: re-verify every
    # on-disk checksum (snapshot frames, op-log records, dense
    # sidecars, hint logs) each scrub_interval_seconds, reading at
    # most scrub_bytes_per_second (a strictly-lower-priority I/O
    # budget).  scrub_bytes_per_second=0 disables the scrubber
    # entirely (the pre-r19 contract: no thread, no re-verification).
    # A corrupt fragment is quarantined — reads serve from replicas,
    # local strict writes refuse with a structured 503 storageFault —
    # and auto-repaired from a healthy replica in cluster mode.
    scrub_interval_seconds: float = 600.0
    scrub_bytes_per_second: int = 32 << 20
    # Disk-health governor: write-path ENOSPC flips the node to
    # READ-ONLY degraded serving (strict writes refuse with a
    # structured writeUnavailable{disk_full}; peers hint the missed
    # copies); every disk_probe_seconds a probe (statvfs headroom >=
    # disk_min_free_bytes + a real probe write) checks whether space
    # freed and restores healthy serving.
    disk_min_free_bytes: int = 64 << 20
    disk_probe_seconds: float = 5.0
    # Multi-tenant HBM economy (r17 — tenant = index name).
    # plane_paging: a plane past the HBM budget (or its tenant's byte
    # quota) serves PAGED — fixed-byte shard pages resident on device,
    # the host oracle covering the rest, bit-exact; single-device only
    # (a mesh placement disables it).  plane_page_bytes sizes one page
    # (smaller = finer residency control, more page-ins).
    plane_paging: bool = True
    plane_page_bytes: int = 64 << 20
    # Per-tenant quotas, all 0 = off.  tenant_byte_quota caps one
    # tenant's resident plane/page bytes (page-ins evict the tenant's
    # OWN coldest entries first, then fall back to the oracle).
    # tenant_qps_quota / tenant_slot_quota shed an over-quota tenant's
    # queries with a structured tenantThrottled 503 + Retry-After
    # BEFORE they take an executor slot — other tenants keep their
    # admission floors.
    tenant_byte_quota: int = 0
    tenant_qps_quota: float = 0.0
    tenant_slot_quota: int = 0
    # Warm dense-plane cache: cold plane builds persist generation-
    # keyed dense sidecar images (<fragment>.dense) so a restarted
    # node re-expands at near raw-copy speed instead of re-decoding
    # roaring containers; any write/compaction/restore invalidates.
    plane_sidecars: bool = True
    # JAX persistent compilation cache directory ("" = off): warm
    # restarts skip the ~1 s first-query XLA compile by reloading
    # compiled programs from disk (jax_compilation_cache_dir).
    compilation_cache_dir: str = ""
    # Queries EXECUTING at once; extras queue at the executor (bounds
    # concurrent device scratch; 0 = off).  Size against HBM headroom:
    # resident planes (plane_budget_bytes) + slots × ~0.5 GB scratch
    # must fit the chip — at an 8 GB budget on a 16 GB chip, 16 slots
    # measurably OOM'd and 6 served cleanly (bench/config14 r5).
    max_concurrent_queries: int = 8
    max_map_count: int = 32768          # live snapshot mmaps before LRU
                                        # heap demotion (syswrap parity)
    grpc_bind: str = ""                 # host:port; "" disables gRPC
    mesh: bool = True                   # shard planes over all local devices
    # tls (reference: server/config.go [tls] section) — one block turns
    # on HTTPS, TLS internode fan-out, and gRPC TLS together; the
    # node's certificate doubles as its client cert for mTLS when
    # enable_client_auth requires peers to authenticate
    tls_certificate: str = ""           # PEM cert path; "" = plaintext
    tls_key: str = ""                   # PEM private key path
    tls_ca_certificate: str = ""        # CA bundle for verifying peers
    tls_skip_verify: bool = False       # outbound: skip server-cert check
    tls_enable_client_auth: bool = False  # inbound: require client certs
    # multi-host jax (one process per host of a pod slice; the host-level
    # cluster layer above is independent of this)
    jax_coordinator: str = ""           # host:port of process 0; "" = single
    jax_num_processes: int = 0
    jax_process_id: int = -1

    @property
    def host(self) -> str:
        return self.bind.rsplit(":", 1)[0]

    @property
    def port(self) -> int:
        return int(self.bind.rsplit(":", 1)[1])

    def effective(self) -> dict:
        return dataclasses.asdict(self)


_BOOL_TRUE = {"1", "true", "yes", "on"}


def _coerce(value: str, typ):
    if typ is bool:
        return value.lower() in _BOOL_TRUE
    if typ is int:
        return int(value)
    if typ is float:
        return float(value)
    if typ == list[str]:
        return [s.strip() for s in value.split(",") if s.strip()]
    return value


def load(path: str | None = None, env: dict | None = None,
         overrides: dict | None = None) -> Config:
    """defaults ← TOML file ← PILOSA_* env ← explicit overrides."""
    cfg = Config()
    fields = {f.name: f.type for f in dataclasses.fields(Config)}

    if path:
        import tomllib
        with open(path, "rb") as f:
            data = tomllib.load(f)
        for k, v in data.items():
            k = k.replace("-", "_")
            if k == "tls" and isinstance(v, dict):
                # [tls] table, upstream-style: certificate = "...", ...
                for tk, tv in v.items():
                    tk = "tls_" + tk.replace("-", "_")
                    if tk not in fields:
                        raise ValueError(
                            f"unknown [tls] key {tk[4:]!r} in {path}")
                    setattr(cfg, tk, tv)
                continue
            if k not in fields:
                raise ValueError(f"unknown config key {k!r} in {path}")
            setattr(cfg, k, v)

    env = env if env is not None else os.environ
    for k in fields:
        ev = env.get(ENV_PREFIX + k.upper())
        if ev is not None:
            setattr(cfg, k, _coerce(ev, _resolve_type(fields[k])))

    for k, v in (overrides or {}).items():
        if v is not None:
            setattr(cfg, k, v)

    cfg.data_dir = os.path.expanduser(cfg.data_dir)
    for k in ("tls_certificate", "tls_key", "tls_ca_certificate"):
        v = getattr(cfg, k)
        if v:
            setattr(cfg, k, os.path.expanduser(v))
    if not cfg.name:
        cfg.name = cfg.bind
    return cfg


def tls_of(cfg: Config):
    """The resolved tls block as an :class:`pilosa_tpu.api.tls.TLSConfig`."""
    from pilosa_tpu.api.tls import TLSConfig
    return TLSConfig(
        certificate=cfg.tls_certificate, key=cfg.tls_key,
        ca_certificate=cfg.tls_ca_certificate,
        skip_verify=cfg.tls_skip_verify,
        enable_client_auth=cfg.tls_enable_client_auth)


def client_ssl_of(cfg: Config):
    """Outbound TLS context for this config (internode fan-out, CLI
    client), or None when the tls block is off — the single recipe
    every surface shares."""
    from pilosa_tpu.api.tls import client_context
    return client_context(tls_of(cfg))


def _resolve_type(t):
    # dataclass field types may be strings under future annotations
    if t in ("bool", bool):
        return bool
    if t in ("int", int):
        return int
    if t in ("float", float):
        return float
    if t in ("list[str]",) or t == list[str]:
        return list[str]
    return str
