"""Ingest subsystem (r15): bulk import pipeline + device-side delta
planes — serve reads at the ceiling while writes stream in.

Two halves (ROADMAP item 4, SURVEY.md §4.5 "host delta queues → device
scatter"):

- :mod:`pilosa_tpu.ingest.bulk` — the replicated bulk-import
  coordinator: batched (row, col) and roaring imports apply straight
  into fragments in one oplog-batched, fsync-coalesced append per
  batch, routed through the breaker-aware write path so hinted handoff
  and idempotent op-id replay cover bulk ops exactly like PQL writes.

- :mod:`pilosa_tpu.ingest.delta` — device-side delta overlays: recent
  writes accumulate as bounded (cell → word value) buffers beside the
  resident base plane; query kernels merge base⊕delta at dispatch time
  (Count / selected-counts / TopN row counts) so a write never marks
  the plane generation-stale on the serving path, while a background
  compactor folds full overlays into the base and atomically swaps
  generations (:class:`pilosa_tpu.exec.planes.PlaneCache` hosts the
  state and drives both).
"""

from pilosa_tpu.ingest.bulk import BulkImporter, apply_import_hint
from pilosa_tpu.ingest.delta import (DeltaMirror, DeltaOverlay,
                                     adjusted_row_counts,
                                     adjusted_selected_counts)

__all__ = [
    "BulkImporter", "apply_import_hint", "DeltaMirror", "DeltaOverlay",
    "adjusted_row_counts", "adjusted_selected_counts",
]
