"""Replicated bulk import through the breaker-aware write path.

The pre-r15 import routing (``API._route_to_owners``) forwarded every
shard batch blindly to its owners: a dead replica failed the batch, a
saturated one hung it, and nothing was durably queued for rejoin.  This
coordinator gives bulk ops the SAME failure contract PQL writes earned
in PR 6/8:

- owners are split by the breaker-aware reachable set
  (``dist._write_reachable``): known-dead owners are durably HINTED
  up front (hint-before-apply), targets that die mid-apply hand off
  after the surviving legs, and a peer with pending hints receives new
  batches only BEHIND its backlog (one ordered stream per peer);
- every shard batch carries a unique 128-bit **op id**; receivers dedup
  against the durable ``IdWindow`` (duplicate delivery — internode
  retries, replayed hints — is a no-op);
- additive imports (``clear=False``) are best-effort like ``Set``
  (a missed replica converges via hints/AAE); clearing imports are
  strict like ``Clear`` (a replica that missed the clear would
  resurrect bits through union-merge AAE) and refuse with the
  structured 503 ``writeUnavailable`` body when handoff can't cover;
- hinted batches replay through ``/internal/hints/replay`` as
  ``kind: "import"`` records (:func:`apply_import_hint`), in append
  order with the PQL hints around them — the AAE-defers-to-hints
  ordering rule covers bulk ops for free (records carry field+shards).

Local applies use the oplog batched-append API: one fsync-coalesced
``SyncBatch`` per import batch (see ``store/oplog.py``).
"""

from __future__ import annotations

import base64
import os

import numpy as np

from pilosa_tpu.engine.words import SHARD_WIDTH
from pilosa_tpu.store.view import VIEW_STANDARD


def apply_import_hint(api, op: dict) -> int:
    """Apply one replayed ``kind: "import"`` hint record locally (the
    receive half of bulk handoff).  Dedup by op id happens in the
    replay endpoint before this is called."""
    imp = op["import"]
    if imp.get("mode") == "roaring":
        return api.import_roaring(
            op["index"], op["field"], int(imp["shard"]),
            base64.b64decode(imp["blob"]),
            view=imp.get("view", VIEW_STANDARD),
            clear=bool(imp.get("clear", False)), direct=True)
    return api.import_bits(
        op["index"], op["field"], row_ids=imp["rows"],
        col_ids=imp["cols"], timestamps=imp.get("timestamps"),
        clear=bool(imp.get("clear", False)), direct=True)


class BulkImporter:
    """Shard-batch coordinator for replicated bulk imports (cluster
    mode only; single-node applies stay inside :class:`API`)."""

    def __init__(self, api, cluster):
        self.api = api
        self.cluster = cluster

    # -- public -------------------------------------------------------------

    def import_bits(self, index: str, field: str, rows: np.ndarray,
                    cols: np.ndarray, timestamps, clear: bool) -> int:
        """Pre-translated (row, col[, ts]) pairs → one replicated op per
        touched shard; returns the primary's changed count, like the
        reference import orchestration."""
        from pilosa_tpu.api import proto
        shards = cols // np.uint64(SHARD_WIDTH)
        changed = 0
        for shard in np.unique(shards):
            m = shards == shard
            sub_rows = [int(r) for r in rows[m]]
            sub_cols = [int(c) for c in cols[m]]
            sub_ts = ([timestamps[i] for i in np.nonzero(m)[0]]
                      if timestamps is not None else None)
            op_id = os.urandom(16).hex()
            path = f"/index/{index}/field/{field}/import"

            def encode():
                return proto.encode_import_request(
                    row_ids=sub_rows, col_ids=sub_cols,
                    timestamps=sub_ts, clear=clear)

            def json_body():
                return {"rowIDs": sub_rows, "columnIDs": sub_cols,
                        "timestamps": sub_ts, "clear": clear}

            changed += self._shard_op(
                index, field, int(shard),
                op_name="ImportClear" if clear else "Import",
                op_id=op_id, additive=not clear,
                apply_local=lambda: self.api.import_bits(
                    index, field, row_ids=sub_rows, col_ids=sub_cols,
                    timestamps=sub_ts, clear=clear, direct=True,
                    op_id=op_id),
                forward=self._forwarder(path, op_id, encode, json_body),
                hint_payload={"mode": "bits", "rows": sub_rows,
                              "cols": sub_cols, "timestamps": sub_ts,
                              "clear": clear})
        return changed

    def import_roaring(self, index: str, field: str, shard: int,
                       blob: bytes, view: str, clear: bool) -> int:
        """One serialized roaring image → one replicated shard op."""
        op_id = os.urandom(16).hex()
        qs = f"?view={view}" + ("&clear=1" if clear else "")
        path = (f"/index/{index}/field/{field}/import-roaring/"
                f"{shard}{qs}")

        def forward(client):
            return client._do(
                "POST", path, blob,
                content_type="application/octet-stream",
                headers={"X-Pilosa-Direct": "1",
                         "X-Pilosa-Op-Id": op_id})["changed"]

        return self._shard_op(
            index, field, shard,
            op_name="ImportClear" if clear else "Import",
            op_id=op_id, additive=not clear,
            apply_local=lambda: self.api.import_roaring(
                index, field, shard, blob, view=view, clear=clear,
                direct=True, op_id=op_id),
            forward=forward,
            hint_payload={"mode": "roaring", "shard": shard,
                          "view": view, "clear": clear,
                          "blob": base64.b64encode(blob).decode()})

    # -- internals ----------------------------------------------------------

    @staticmethod
    def _forwarder(path: str, op_id: str, encode, json_body):
        """Remote leg with the direct + op-id headers; protobuf wire
        encoded lazily on the first remote owner, JSON fallback for
        inputs the codec refuses (mirrors the query-path forwarding)."""
        cache: list = []

        def forward(client):
            from pilosa_tpu.api import proto
            if not cache:
                try:
                    cache.append((encode(), True))
                except ValueError:
                    cache.append((None, False))
            body, is_proto = cache[0]
            headers = {"X-Pilosa-Direct": "1", "X-Pilosa-Op-Id": op_id}
            if is_proto:
                return client._do("POST", path, body,
                                  content_type=proto.CONTENT_TYPE,
                                  headers=headers)["changed"]
            return client._json("POST", path, json_body(),
                                headers=headers)["changed"]
        return forward

    def _hint_record(self, index: str, field: str, shard: int,
                     op_name: str, op_id: str, payload: dict) -> dict:
        """A replayable bulk hint: same routing facts the AAE gating
        and drain machinery key on as PQL hints, plus the import
        payload."""
        return {"id": op_id, "index": index, "op": op_name,
                "field": field, "shards": [int(shard)],
                "kind": "import", "import": payload}

    def _hint(self, peer: str, record: dict) -> None:
        hints = self.cluster.hints
        hints.add(peer, record)
        self.cluster.stats.count("hint_handoff_total", 1, peer=peer)
        self.cluster.logger.info(
            "%s batch hinted for %s (replica down)", record["op"], peer)

    def _shard_op(self, index: str, field: str, shard: int, *,
                  op_name: str, op_id: str, additive: bool,
                  apply_local, forward, hint_payload: dict) -> int:
        """Apply one shard batch on every replica owner through the
        breaker-aware split; returns the first successful owner's
        changed count."""
        from pilosa_tpu.api.client import ClientError
        cluster = self.cluster
        dist = cluster.dist
        owners = cluster.shard_owners(index, shard)
        hints = cluster.hints
        record = self._hint_record(index, field, shard, op_name, op_id,
                                   hint_payload)
        if hints is None:
            # handoff disabled: the legacy contract — additive imports
            # are best-effort over reachable owners (AAE repairs on
            # rejoin), clearing imports fail-fast BEFORE any replica
            # applies
            reachable = dist._write_reachable()
            dead = sorted(set(owners) - reachable)
            if dead and not additive:
                raise dist._unavailable(op_name, dead[0], "replica_down")
            targets, handed = [o for o in owners if o in reachable], []
            if not targets:
                raise dist._unavailable(op_name, dead[0] if dead
                                        else None, "no_live_replica")
            if dead:
                cluster.stats.count("write_replicas_missed", len(dead))
        else:
            targets, handed = dist._split_write_targets(
                op_name, owners, additive=additive)
            for peer in handed:
                # hint FIRST (durable intent), then apply live — a
                # coordinator crash in between re-delivers, never loses
                self._hint(peer, record)

        def one(node_id):
            if node_id == cluster.node_id:
                return apply_local()
            return forward(cluster._client(node_id))

        def guarded(node_id):
            try:
                return ("ok", one(node_id))
            except ClientError as e:
                # the ONE shared classification with the PQL write
                # path: down / busy / propagate ("state unknown")
                tag = dist.write_failure_class(e)
                if tag is None:
                    raise
                return (tag, (node_id, e))

        if len(targets) == 1:
            outs = [guarded(targets[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(targets)) as pool:
                outs = list(pool.map(guarded, targets))
        oks = [r for tag, r in outs if tag == "ok"]
        downs = [r for tag, r in outs if tag == "down"]
        busys = [r for tag, r in outs if tag == "busy"]
        if downs and hints is not None:
            for nid, _err in downs:
                self._hint(nid, record)
            downs = []
        if busys and not additive:
            nid, _err = busys[0]
            raise dist._unavailable(op_name, nid, "replica_busy")
        downs += busys
        if downs and (not additive or not oks):
            from pilosa_tpu.exec.executor import ExecutionError
            nid, err = downs[0]
            raise ExecutionError(
                f"replica {nid} unreachable for {op_name}: {err}")
        if not oks:
            # every live target died mid-apply (each hinted): nothing
            # applied NOW — acking would claim otherwise; the hints
            # stay queued and replay un-acked (at-least-once)
            raise dist._unavailable(op_name, targets[0],
                                    "no_live_replica")
        if downs:
            cluster.stats.count("write_replicas_missed", len(downs))
            cluster.logger.warning(
                "%s batch applied on %d/%d owners; missed %s",
                op_name, len(oks), len(targets),
                [nid for nid, _ in downs])
        return int(oks[0])
