"""Device-side delta planes: bounded write overlays merged at dispatch.

The device half of SURVEY.md §4.5 ingest (host delta queues → device
scatter), rebuilt so the QUERY path never rewrites the base plane:

- the **mirror** (:class:`DeltaMirror`) is the host-side truth of the
  overlay: an insertion-ordered ``(flat_row, word) → current word
  value`` map, absorbed from fragment mutation journals
  (``Fragment.changed_cells_since``) when a resident plane's
  generations fall behind.  A cell's value is the word's CURRENT
  contents, so sets AND clears are both "overwrite this word" — no
  separate set/clear masks, no ordering hazard.

- the **overlay** (:class:`DeltaOverlay`) is the mirror's device form:
  three pow2-padded arrays (flat row index, word index, value) the
  merge kernels consume.  Padding uses an out-of-range row index so
  scatter-adds drop pad lanes and gathers mask them.

- the **merge kernels** (:func:`adjusted_row_counts`,
  :func:`adjusted_selected_counts`) answer base⊕delta in one program:
  scan the UNCHANGED base plane exactly as the clean path does, gather
  the overlay's base words, and adjust each touched row's count by
  ``popcount(new) − popcount(old)``.  The base plane is read-only —
  no donation, no 4 GB re-scatter — so the marginal cost per query is
  one small gather + scatter-add over the overlay, and concurrent
  readers share the same immutable arrays.

Capacity is bounded (``PlaneCache.delta_cells``); past the compaction
threshold a background compactor folds the overlay into the base plane
via the existing ``dynamic_update_slice``/scatter machinery and swaps
the cache entry's generation atomically (exec/planes.py owns that).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np


def _pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


@dataclass
class DeltaOverlay:
    """Device form of one plane's pending write cells.

    ``rows`` are FLAT row indices (``shard_axis * R_pad + row_slot``)
    into ``plane.reshape(S * R_pad, W)``; pad lanes carry
    ``rows == S * R_pad`` (out of range → dropped/masked by the merge
    kernels).  ``vals`` are the cells' current word values — base⊕delta
    is "replace these words"."""

    rows: jax.Array   # int32[C_pad]
    words: jax.Array  # int32[C_pad]
    vals: jax.Array   # uint32[C_pad]
    n: int            # live cells (<= C_pad)
    bits: int         # set bits carried by live cells (gauge fodder)

    @property
    def nbytes(self) -> int:
        return int(self.rows.size) * 12


@dataclass
class BsiOverlay:
    """Device form of a BIT-SLICED plane's pending write cells,
    grouped by touched word-COLUMN (r20 BSI ingest).

    A BSI write changes several rows of ONE word column at once (the
    exists row, maybe the sign row, and the changed magnitude bits of
    the same 32-column word), and the aggregate kernels
    (``bit_counts``/``min_max_bits``/``range_cmp``) read whole columns
    — so the overlay's unit is the (shard, word) column, not the
    single cell.  The delta-aware aggregates split base⊕delta into

      base side   the untouched columns: the clean kernel over the
                  immutable base plane with touched word columns
                  masked OUT of the filter (:func:`bsi_excl_filter`);
      mini side   the touched columns as a tiny standalone plane
                  ``uint32[K, rows, 1]`` holding the MERGED words
                  (:func:`bsi_mini_plane`), run through the SAME
                  kernel with a per-column filter word.

    Exact by construction: every column is counted on exactly one
    side.  Pad lanes carry ``col_shard == n_shards`` (dropped by the
    exclusion scatter) and an all-zero mini filter (no contribution).
    """

    col_shard: jax.Array  # int32[K_pad] (pad lanes = n_shards)
    col_word: jax.Array   # int32[K_pad]
    col_vals: jax.Array   # uint32[K_pad, rows] new word values
    col_mask: jax.Array   # uint32[K_pad, rows] 0xFFFFFFFF = row touched
    n: int                # live touched columns (<= K_pad)
    bits: int             # set bits carried by live cell values

    @property
    def nbytes(self) -> int:
        # per lane: vals + mask words (col_vals.size covers both at
        # 4 B each) plus the shard + word indices (4 B each)
        return int(self.col_vals.size) * 8 + int(self.col_shard.size) * 8


class DeltaMirror:
    """Host mirror of one resident plane's overlay cells.

    Mutated only under the owning ``PlaneCache``'s lock; the built
    :class:`DeltaOverlay` is immutable, so serving threads read a
    fully-formed object or none.  ``cap`` bounds cells — absorb refuses
    past it and the caller compacts/rebuilds instead.

    Backing store is three parallel numpy arrays plus a cell→slot
    index: absorbing a batch costs one append/overwrite per BATCH cell
    (not a rebuild of the whole mirror), and :meth:`build_overlay` is
    a vectorized pad-copy — the work done under the cache lock scales
    with the write batch, not the overlay's fill."""

    _GROW = 1024
    # build_bsi_overlay's minimum pow2 column bucket (see there)
    BSI_COL_PAD_MIN = 64

    def __init__(self, cap: int):
        self.cap = int(cap)
        self._index: dict[tuple[int, int], int] = {}
        size = min(self._GROW, max(1, self.cap))
        self._rows = np.empty(size, np.int64)
        self._words = np.empty(size, np.int64)
        self._vals = np.empty(size, np.uint32)
        self.bits = 0  # sum of bit_count over live cell values

    def __len__(self) -> int:
        return len(self._index)

    def would_fit(self, new_cells) -> bool:
        """Whether absorbing ``new_cells`` keeps the mirror at/under
        cap (overwrites of existing cells don't grow it)."""
        grow = sum(1 for k in new_cells if k not in self._index)
        return len(self._index) + grow <= self.cap

    def absorb(self, new_cells: dict[tuple[int, int], int]) -> None:
        """Overwrite-merge journal cells (values are current word
        truth, so later absorbs supersede earlier ones per word)."""
        for key, val in new_cells.items():
            slot = self._index.get(key)
            if slot is None:
                slot = len(self._index)
                if slot >= len(self._rows):
                    grow = min(max(len(self._rows) * 2, self._GROW),
                               max(self.cap, slot + 1))
                    for name in ("_rows", "_words", "_vals"):
                        arr = getattr(self, name)
                        new = np.empty(grow, arr.dtype)
                        new[:len(arr)] = arr
                        setattr(self, name, new)
                self._index[key] = slot
                self._rows[slot], self._words[slot] = key
            else:
                self.bits -= int(self._vals[slot]).bit_count()
            self._vals[slot] = val
            self.bits += val.bit_count()

    def snapshot(self) -> dict:
        """{(flat_row, word): value} copy (the fold path's input)."""
        n = len(self._index)
        return dict(zip(zip(self._rows[:n].tolist(),
                            self._words[:n].tolist()),
                        self._vals[:n].tolist()))

    def build_overlay(self, place, flat_total: int) -> DeltaOverlay:
        """Materialize the device overlay (pow2-padded; pad rows =
        ``flat_total`` → masked/dropped by the kernels).  ``place`` is
        the device placement callable."""
        n = len(self._index)
        c_pad = _pow2(max(1, n))
        rows = np.full(c_pad, flat_total, np.int32)
        words = np.zeros(c_pad, np.int32)
        vals = np.zeros(c_pad, np.uint32)
        rows[:n] = self._rows[:n]
        words[:n] = self._words[:n]
        vals[:n] = self._vals[:n]
        return DeltaOverlay(place(rows), place(words), place(vals),
                            n=n, bits=self.bits)

    def build_bsi_overlay(self, place, n_rows: int,
                          n_shards: int) -> BsiOverlay:
        """Materialize the BSI (word-column-grouped) device overlay:
        live cells regroup by (shard, word) so each touched column
        carries its new row words + touched-row mask in one lane.
        ``n_rows`` is the plane's row count (depth + 2); pad columns
        carry ``col_shard == n_shards`` (dropped/masked).

        Vectorized: this runs under the cache lock on every absorb
        (once per write gap a read observes), so the work must stay
        one ``np.unique`` + two fancy scatters — a python loop over
        the mirror measured O(cells) per READ under sustained ingest
        and collapsed the config30 mixed phase."""
        n = len(self._index)
        flat = self._rows[:n]
        word = self._words[:n]
        # one sortable key per (shard, word) column; words are < 2^32
        key = (flat // n_rows).astype(np.int64) * (1 << 32) + word
        uniq, inv = np.unique(key, return_inverse=True)
        k = len(uniq)
        # floor the pow2 column bucket: every bucket size is a fresh
        # XLA compile of each delta-aware aggregate family, so the
        # low rungs of the ladder (1, 2, 4, ... columns) are pure
        # compile churn during ingest warm-up — pad lanes are masked,
        # so a 64-column floor costs only trivial device scratch
        k_pad = _pow2(max(self.BSI_COL_PAD_MIN, k))
        col_shard = np.full(k_pad, n_shards, np.int32)
        col_word = np.zeros(k_pad, np.int32)
        col_vals = np.zeros((k_pad, n_rows), np.uint32)
        col_mask = np.zeros((k_pad, n_rows), np.uint32)
        col_shard[:k] = (uniq >> 32).astype(np.int32)
        col_word[:k] = (uniq & 0xFFFFFFFF).astype(np.int32)
        rows_in_col = (flat % n_rows).astype(np.int64)
        col_vals[inv, rows_in_col] = self._vals[:n]
        col_mask[inv, rows_in_col] = 0xFFFFFFFF
        return BsiOverlay(place(col_shard), place(col_word),
                          place(col_vals), place(col_mask),
                          n=k, bits=self.bits)


# ---------------------------------------------------------------------------
# Merge kernels (pure jnp; jitted through FusedCache — one program per
# (plane shape, overlay bucket[, filter]) like every other fused family)
# ---------------------------------------------------------------------------


def _cell_diffs(plane: jax.Array, d_rows: jax.Array, d_words: jax.Array,
                d_vals: jax.Array, filter_words: jax.Array | None):
    """Per-cell popcount deltas vs the base plane: int32[C_pad] (pad
    lanes 0) plus each cell's plane row slot (pad lanes out of range)."""
    s, r, w = plane.shape
    total = s * r
    flat = plane.reshape(total, w)
    rc = jnp.clip(d_rows, 0, total - 1)
    base = flat[rc, d_words]
    val = d_vals
    if filter_words is not None:
        fflat = filter_words.reshape(s * w)
        f = fflat[jnp.clip((rc // r) * w + d_words, 0, s * w - 1)]
        base = jnp.bitwise_and(base, f)
        val = jnp.bitwise_and(val, f)
    diff = (jax.lax.population_count(val).astype(jnp.int32)
            - jax.lax.population_count(base).astype(jnp.int32))
    valid = d_rows < total
    diff = jnp.where(valid, diff, 0)
    slot = jnp.where(valid, rc % r, r)  # pad → R (dropped)
    return diff, slot


def adjusted_row_counts(plane: jax.Array, d_rows: jax.Array,
                        d_words: jax.Array, d_vals: jax.Array,
                        filter_words: jax.Array | None = None,
                        reduce_shards: bool = True,
                        row_counts_fn=None) -> jax.Array:
    """Whole-plane per-row popcounts of base⊕delta.

    plane uint32[S, R, W]; overlay arrays int32/uint32[C_pad] →
    int32[R] (``reduce_shards``) or int32[S, R].  The base scan is
    byte-identical to the clean ``row_counts`` path; delta cells only
    adjust the touched (shard, row) entries, so N concurrent queries
    over the same (plane, overlay) pair still dedupe to one scan.
    ``row_counts_fn`` swaps the base scan kernel (the pallas serving
    tier routes here) — base⊕delta stays ONE program either way: the
    adjustment traces into the same jit as the scan."""
    from pilosa_tpu.engine import kernels
    s, r, _ = plane.shape
    rc = row_counts_fn if row_counts_fn is not None else kernels.row_counts
    counts = rc(plane, filter_words)  # int32[S, R]
    diff, _slot = _cell_diffs(plane, d_rows, d_words, d_vals,
                              filter_words)
    flat = counts.reshape(s * r)
    flat = flat.at[jnp.where(d_rows < s * r, d_rows, s * r)].add(
        diff, mode="drop")
    counts = flat.reshape(s, r)
    if reduce_shards:
        return jnp.sum(counts, axis=0, dtype=jnp.int32)
    return counts


def overlay_gathered_rows(sel: jax.Array, row_idx: jax.Array,
                          d_rows: jax.Array, d_words: jax.Array,
                          d_vals: jax.Array, r_pad: int) -> jax.Array:
    """Apply the overlay's word overwrites to a row GATHER: ``sel``
    uint32[S, G, W] is ``jnp.take(plane, row_idx, axis=-2)``, and each
    overlay cell whose (shard, row slot) lands in the gathered set
    overwrites its word with the cell's current value — the base⊕delta
    form the whole-tree kernels consume (the tree folds over gathered
    WORDS, so counts-only adjustment doesn't apply; the words
    themselves must be fresh).  ``row_idx`` lanes past the live width
    may repeat slot 0 (pow2 padding); a cell matches its FIRST lane
    only, and programs never address pad lanes, so stale pad words are
    unobservable.  Pad cells (``d_rows >= S * r_pad``) drop."""
    s, g, _ = sel.shape
    total = s * r_pad
    valid = d_rows < total
    cell_s = jnp.where(valid, d_rows // r_pad, s)  # pad → out of range
    cell_slot = d_rows % r_pad
    match = (cell_slot[:, None] == row_idx[None, :]) & valid[:, None]
    lane = jnp.where(jnp.any(match, axis=1),
                     jnp.argmax(match, axis=1), g)  # no lane → drop
    return sel.at[cell_s, lane, d_words].set(d_vals, mode="drop")


def overlay_row(val: jax.Array, slot, d_rows: jax.Array,
                d_words: jax.Array, d_vals: jax.Array,
                r_pad: int) -> jax.Array:
    """Apply the overlay's word overwrites to ONE plane row: ``val``
    uint32[S, W] is ``plane[:, slot, :]`` (``slot`` traced); every
    overlay cell whose row slot matches overwrites its word.  The
    per-push form of :func:`overlay_gathered_rows` — the solo tree
    program reads rows straight off the plane, so the merge happens
    row-wise inside the same fused chain."""
    s = val.shape[0]
    total = s * r_pad
    match = (d_rows % r_pad == slot) & (d_rows < total)
    cell_s = jnp.where(match, d_rows // r_pad, s)  # non-match → drop
    return val.at[cell_s, d_words].set(d_vals, mode="drop")


def adjusted_selected_counts(plane: jax.Array, row_idx: jax.Array,
                             d_rows: jax.Array, d_words: jax.Array,
                             d_vals: jax.Array,
                             sorted_idx: bool = False,
                             selected_fn=None) -> jax.Array:
    """Selected-row popcounts of base⊕delta, shard axis reduced on
    device: int32[N] for ``row_idx`` int32[N] (plane row slots, the
    multi-query fused gather).  Each overlay cell contributes its diff
    to EVERY matching output lane (duplicate slots answer
    independently, like the clean gather).  ``sorted_idx``: the static
    ascending-stride gather promise (see
    ``kernels.selected_row_counts``).  ``selected_fn`` swaps the base
    gather kernel ``(plane, row_idx) → int32[S, N]`` (the pallas
    serving tier) — the overlay adjustment traces into the same jit,
    so base⊕delta stays one program."""
    from pilosa_tpu.engine import kernels
    sel_fn = (selected_fn if selected_fn is not None else
              lambda p, ix: kernels.selected_row_counts(
                  p, ix, sorted_idx=sorted_idx))
    sel = jnp.sum(sel_fn(plane, row_idx),
                  axis=-2, dtype=jnp.int32)              # int32[N]
    diff, slot = _cell_diffs(plane, d_rows, d_words, d_vals, None)
    match = slot[:, None] == row_idx[None, :]            # [C_pad, N]
    add = jnp.sum(jnp.where(match, diff[:, None], 0), axis=0,
                  dtype=jnp.int32)
    return sel + add


# ---------------------------------------------------------------------------
# BSI split kernels (r20): base-with-exclusion ⊕ merged mini plane.
# Pure jnp — jitted through FusedCache's run_*_plane_batch family; the
# overlay arrays are traced operands, so one program serves any
# overlay of the same pow2 column bucket.
# ---------------------------------------------------------------------------


def bsi_sides(plane: jax.Array, filter_words, overlay):
    """The base⊕delta split as ``[(plane, filter), ...]`` sides for
    EAGER consumers (the batcher's per-item fallbacks): the clean
    plane alone when there is no overlay, else the base with touched
    columns excluded plus the merged mini plane.  Fused programs use
    ``FusedCache._bsi_split`` (same math, traced operands)."""
    if overlay is None:
        return [(plane, filter_words)]
    return [
        (plane, bsi_excl_filter(plane, overlay.col_shard,
                                overlay.col_word, filter_words)),
        (bsi_mini_plane(plane, overlay.col_shard, overlay.col_word,
                        overlay.col_vals, overlay.col_mask),
         bsi_mini_filter(plane, overlay.col_shard, overlay.col_word,
                         filter_words))]


def bsi_excl_filter(plane: jax.Array, col_shard: jax.Array,
                    col_word: jax.Array,
                    filter_words: jax.Array | None) -> jax.Array:
    """The base side's filter: the caller's ``filter_words`` (all-ones
    when absent) with every overlay-touched word column zeroed — those
    32-column words are answered by the mini plane instead.  Pad lanes
    (``col_shard == S``) drop."""
    s, _, w = plane.shape
    base = (jnp.full((s, w), 0xFFFFFFFF, jnp.uint32)
            if filter_words is None else filter_words)
    return base.at[col_shard, col_word].set(0, mode="drop")


def bsi_mini_plane(plane: jax.Array, col_shard: jax.Array,
                   col_word: jax.Array, col_vals: jax.Array,
                   col_mask: jax.Array) -> jax.Array:
    """The mini side: each touched word column as one single-word
    shard of a tiny standalone BSI plane ``uint32[K, rows, 1]`` —
    overlay words where touched, base words elsewhere.  Pad lanes
    gather shard 0 garbage; the mini FILTER zeroes them."""
    s = plane.shape[0]
    cs = jnp.clip(col_shard, 0, s - 1)
    base_cols = plane[cs, :, col_word]            # [K, rows]
    merged = jnp.where(col_mask.astype(bool), col_vals, base_cols)
    return merged[..., None]                      # [K, rows, 1]


def bsi_mini_filter(plane: jax.Array, col_shard: jax.Array,
                    col_word: jax.Array,
                    filter_words: jax.Array | None) -> jax.Array:
    """The mini side's per-column filter word ``uint32[K, 1]``: the
    caller's filter at each touched column (all-ones when absent),
    zero on pad lanes so they contribute nothing anywhere."""
    s = plane.shape[0]
    valid = (col_shard < s).astype(jnp.uint32) * jnp.uint32(0xFFFFFFFF)
    if filter_words is not None:
        cs = jnp.clip(col_shard, 0, s - 1)
        valid = valid & filter_words[cs, col_word]
    return valid[:, None]                         # [K, 1]
