"""Cluster: membership, placement, translation replication, AAE, resize.

Reference: ``cluster.go`` + ``gossip/`` + ``broadcast.go`` (SURVEY.md
§3.3, §3.6).  The reference uses memberlist gossip for liveness and a
coordinator-driven state machine; this rebuild keeps the shape with a
boring HTTP control plane (no gossip lib in the image, and TPU-pod
deployments want deterministic membership anyway):

- membership: explicit join to seed nodes + periodic heartbeats; a node
  is suspect after 3 missed heartbeat intervals;
- coordinator: lowest node id (reference: v1 coordinator) — drives
  resize jobs and owns key-translation assignment, replicating the
  append-only key logs to every node (v1 translate-log streaming);
- placement: jump-hash shard→partition→node with ``replicas`` copies
  (:mod:`pilosa_tpu.parallel.placement`);
- anti-entropy: periodic block-checksum diff + bidirectional union
  merge between replicas (reference: holder syncer, SURVEY.md §4.6);
- resize: on membership change the coordinator computes fragment
  transfers from a cluster-wide inventory and instructs holders to push
  roaring blobs to the new owners (reference: ``ResizeJob``/
  ``ResizeInstruction``).

Queries fan out via :class:`pilosa_tpu.cluster.dist.DistributedExecutor`.
"""

from __future__ import annotations

import os
import threading
import time

from pilosa_tpu import fault
from pilosa_tpu.cluster.breaker import BreakerBoard
from pilosa_tpu.cluster.dist import DistributedExecutor
from pilosa_tpu.obs import NopStats, get_logger
from pilosa_tpu.parallel.placement import shard_nodes

STATE_STARTING = "STARTING"
STATE_NORMAL = "NORMAL"
STATE_RESIZING = "RESIZING"
STATE_DEGRADED = "DEGRADED"

SUSPECT_AFTER = 3  # missed heartbeat intervals

_SHARD_CACHE_TTL = 2.0
# cache lifetime for a shard universe built while a peer fetch failed:
# long enough to stop per-query hammering of a sick peer, short enough
# that the complete view returns quickly once the peer answers
_SHARD_NEG_TTL = 0.25


class Cluster:
    def __init__(self, cfg, api, stats=None, logger=None, port: int | None = None):
        self.cfg = cfg
        self.api = api
        self.stats = stats or NopStats()
        self.logger = logger or get_logger("pilosa_tpu.cluster")
        host = cfg.host
        self.port = port if port is not None else cfg.port
        self.node_id = f"{host}:{self.port}"
        self.nodes: dict[str, dict] = {
            self.node_id: {"id": self.node_id, "uri": self.node_id,
                           "state": STATE_STARTING}}
        self._last_seen: dict[str, float] = {}
        # internode TLS (upstream: internode client certs,
        # server/config.go); one context for every peer client
        from pilosa_tpu.cli.config import client_ssl_of
        self._client_ssl_ctx = client_ssl_of(cfg)
        self.state = STATE_STARTING
        # ACTIVE placement topology: the node set shard_owners routes
        # by.  Joins/removals change MEMBERSHIP immediately but the
        # placement only advances when a resize job has finished
        # streaming fragments for the new set — otherwise a joining
        # node instantly "owns" shards whose data hasn't arrived and
        # queries silently undercount (config17 r5).
        self.placement_ids: list[str] = [self.node_id]
        # monotonic (wall-clock) version of the ACTIVE placement: rides
        # every heartbeat both ways, so a node that missed the one
        # best-effort resize-completion broadcast detects the mismatch
        # within a heartbeat interval and PULLS the newer topology
        # instead of routing by the stale one forever (ADVICE r5)
        self.placement_version: float = 0.0
        self._load_placement()
        self._placement_pull = threading.Lock()  # one pull at a time
        # per-peer circuit breakers: consecutive transport failures
        # open a peer (reads route straight to replicas); half-open
        # probes ride the heartbeat loop
        self.breakers = BreakerBoard(
            threshold=getattr(cfg, "breaker_threshold", 3),
            stats=self.stats, logger=self.logger)
        # durable hinted handoff (r13): per-peer crash-safe hint logs —
        # writes keep serving through a dead replica, the missed copy
        # replays in order on rejoin.  hint_max_age <= 0 disables
        # (the pre-r13 strict fail-fast contract).
        self.hints = None
        if float(getattr(cfg, "hint_max_age", 0.0) or 0.0) > 0:
            from pilosa_tpu.cluster.hints import HintBoard
            self.hints = HintBoard(
                os.path.join(api.holder.path, "_hints"),
                max_age=cfg.hint_max_age, fsync=cfg.fsync,
                stats=self.stats, logger=self.logger)
        # receiver-side durable dedup window for /internal/hints/replay
        # — always on (cheap), so this node dedups a peer's replays
        # even when its own handoff is disabled
        from pilosa_tpu.store.oplog import IdWindow
        self.applied_ops = IdWindow(
            os.path.join(api.holder.path, "_hints_applied.log"))
        # peers with pending INBOUND hints anywhere in the cluster:
        # holder id -> (hinted peer set, monotonic update ts).  Learned
        # from every heartbeat (both directions carry ``hintsFor``) and
        # seeded by the join response, so a rejoined stale peer and
        # every up-to-date replica both know to defer AAE union-merge
        # with each other BEFORE the first anti-entropy tick can run —
        # the ordering rule that makes a replayed Clear irresurrectable.
        self._hints_inbound: dict[str, tuple[set, float]] = {}
        self.dist = DistributedExecutor(self)
        self._clients: dict[str, object] = {}
        # index -> (fetched_at, shards, incomplete): `incomplete` rides
        # the cache so strict callers reject degraded hits too
        self._shard_cache: dict[
            str, tuple[float, tuple[int, ...], bool]] = {}
        self._lock = threading.RLock()
        self._status_ts = 0.0
        self._removed: dict[str, float] = {}  # tombstones: explicit removals
        # schema tombstones: (index, field|None) -> deletion ts; a full
        # schema push from a stale peer must not resurrect deletions
        self._schema_tombstones: dict[tuple, float] = {}
        self._resize_lock = threading.Lock()
        self._resize_abort = threading.Event()
        # set once open()'s join (and its schema pull) has completed:
        # until then a missing index cannot be judged "deleted" — the
        # HTTP server answers /internal/hints/replay before open()
        # finishes, so a drain kicked by our own join request can race
        # the join response's apply_schema
        self._schema_ready = threading.Event()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    # -- placement persistence ----------------------------------------------

    def _placement_path(self) -> str:
        import os
        return os.path.join(self.api.holder.path, "_cluster.json")

    def _load_placement(self) -> None:
        """Last activated topology survives restarts: a coordinator
        that cold-restarts alone must NOT serve with placement=[self]
        (it would silently route every shard to itself and undercount)
        — it keeps routing by the persisted topology, failing loudly
        for shards whose owners haven't rejoined yet."""
        import json as _json
        import os
        try:
            with open(self._placement_path()) as f:
                data = _json.load(f)
                saved = data.get("placement") or []
        except (OSError, ValueError):
            return
        if saved and self.node_id in saved:
            self.placement_ids = sorted(saved)
            self.placement_version = float(data.get("version", 0.0))

    def _save_placement(self) -> None:
        import json as _json
        try:
            tmp = self._placement_path() + ".tmp"
            with open(tmp, "w") as f:
                _json.dump({"placement": self.placement_ids,
                            "version": self.placement_version}, f)
            import os
            os.replace(tmp, self._placement_path())
        except OSError as e:
            self.logger.warning("placement persist failed: %s", e)

    # -- lifecycle ----------------------------------------------------------

    def open(self) -> "Cluster":
        joined = False
        for seed in self.cfg.seeds:
            if seed == self.node_id:
                continue
            try:
                resp = self._client(seed)._json(
                    "POST", "/internal/join",
                    {"id": self.node_id, "uri": self.node_id})
                now = time.monotonic()
                with self._lock:
                    self.nodes = {n["id"]: n for n in resp["nodes"]}
                    for nid in self.nodes:
                        self._last_seen.setdefault(nid, now)
                    self.state = resp.get("state", STATE_NORMAL)
                    self.placement_ids = sorted(
                        resp.get("placement") or self.nodes)
                    self.placement_version = float(
                        resp.get("placementVersion", 0.0))
                    self._save_placement()
                for t in resp.get("schemaTombstones", []):
                    self.record_schema_tombstone(t["index"], t.get("field"),
                                                 t.get("ts", 0.0))
                # seed the inbound-hints view from the join response:
                # a REJOINING node may itself be the hinted peer, and
                # it must defer its own AAE participation before its
                # first anti-entropy tick (heartbeats refresh the view
                # within one interval; this closes the boot window)
                self._note_hints_inbound(
                    "<join>", set(resp.get("hintedPeers", [])))
                self.api.apply_schema(
                    self.filter_schema(resp.get("schema", [])))
                self._pull_translate_tails(seed)
                joined = True
                self.logger.info("joined cluster via %s (%d nodes)", seed,
                                 len(self.nodes))
                # ask the coordinator to rebalance onto the new membership
                # (the seed we joined through may not be the coordinator —
                # and WE might be it, if our id sorts lowest)
                coord = self.coordinator_id()
                if coord == self.node_id:
                    self.trigger_resize()
                else:
                    try:
                        self._client(coord)._json(
                            "POST", "/internal/resize/trigger", {})
                    except Exception as e:  # noqa: BLE001
                        self.logger.warning("resize trigger failed: %s", e)
                break
            except Exception as e:  # noqa: BLE001 — try next seed
                self.logger.warning("join via %s failed: %s", seed, e)
        if not joined:
            self.logger.info("no seeds joinable; starting as single node")
        with self._lock:
            self.nodes.setdefault(
                self.node_id, {"id": self.node_id, "uri": self.node_id})
            self.nodes[self.node_id]["state"] = STATE_NORMAL
            if self.state == STATE_STARTING:
                self.state = STATE_NORMAL
        self._schema_ready.set()
        self._spawn(self._heartbeat_loop, "heartbeat")
        if self.cfg.anti_entropy_interval > 0:
            self._spawn(self._aae_loop, "anti-entropy")
        return self

    def close(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)
        if self.hints is not None:
            self.hints.close()
        self.applied_ops.close()

    def _spawn(self, fn, name: str) -> None:
        t = threading.Thread(target=fn, name=f"pilosa-{name}", daemon=True)
        t.start()
        self._threads.append(t)

    # -- membership ---------------------------------------------------------

    def _client(self, node_id: str):
        from pilosa_tpu.api.client import Client
        with self._lock:
            c = self._clients.get(node_id)
            if c is None:
                host, port = node_id.rsplit(":", 1)
                # idempotent_posts: every /internal/* POST is idempotent
                # by contract (cluster/internal.py module docstring), so
                # the lost-response retry is safe for internode calls
                c = self._clients[node_id] = Client(
                    host, int(port), ssl_context=self._client_ssl_ctx,
                    idempotent_posts=True)
            return c

    def member_ids(self) -> list[str]:
        with self._lock:
            return sorted(self.nodes)

    def alive_ids(self) -> list[str]:
        now = time.monotonic()
        horizon = SUSPECT_AFTER * self.cfg.heartbeat_interval
        with self._lock:
            return sorted(
                nid for nid in self.nodes
                if nid == self.node_id
                or now - self._last_seen.get(nid, now) < horizon)

    def coordinator_id(self) -> str:
        """Lowest alive node id (reference: v1 coordinator election by
        ordering)."""
        return self.alive_ids()[0]

    def is_coordinator(self) -> bool:
        return self.coordinator_id() == self.node_id

    def handle_join(self, node: dict) -> dict:
        with self._lock:
            self._removed.pop(node["id"], None)  # explicit rejoin clears
            is_new = node["id"] not in self.nodes
            self.nodes[node["id"]] = {**node, "state": STATE_NORMAL}
            self._last_seen[node["id"]] = time.monotonic()
        # a node that rejoined through the membership path is routable
        # again NOW — stale breaker history must not make its shards
        # pay failover detours until a probe happens by
        self.breakers.reset(node["id"])
        # rejoin hook: start draining any hints queued for it while it
        # was down (writes keep hinting until the drain empties — the
        # per-peer stream stays ordered)
        self._drain_hints_async(node["id"])
        if is_new:
            # propagate the tombstone clear: every peer must re-admit the
            # rejoining node or its heartbeats keep getting bounced
            self._broadcast_status(cleared=[node["id"]])
            if self.is_coordinator():
                self.trigger_resize()
        with self._lock:
            tombs = [{"index": i, "field": f, "ts": ts}
                     for (i, f), ts in self._schema_tombstones.items()]
        return {"nodes": list(self.nodes.values()), "state": self.state,
                "placement": list(self.placement_ids),
                "placementVersion": self.placement_version,
                "schema": self.api.schema(), "schemaTombstones": tombs,
                "hintedPeers": sorted(self.hinted_peers())}

    def handle_heartbeat(self, node_id: str, state: str,
                         placement_version: float = 0.0,
                         hints_for: list[str] | None = None) -> dict:
        self._note_hints_inbound(node_id, set(hints_for or ()))
        with self._lock:
            if node_id in self._removed:
                # tombstoned: tell the sender it was removed; it must
                # rejoin explicitly to come back
                return {"id": self.node_id, "state": self.state,
                        "removed": True}
            self._last_seen[node_id] = time.monotonic()
            unknown = node_id not in self.nodes
            if unknown:
                # node knows us but we lost it (e.g. restarted): re-add
                self.nodes[node_id] = {"id": node_id, "uri": node_id,
                                       "state": state}
            else:
                # keep the sender's state FRESH: a restarted seed node
                # re-learns its peers from their heartbeats, and the
                # first one may arrive while the sender is still
                # DEGRADED from the outage — pinning that snapshot
                # forever left the rejoined cluster reporting DEGRADED
                # members after everyone had recovered (r11)
                self.nodes[node_id]["state"] = state
            ours = self.placement_version
        if unknown or placement_version > ours:
            # pull the sender's full cluster state off-thread (this
            # runs in an HTTP handler; the pull is its own round trip).
            # Newer placementVersion: the sender activated a topology
            # we missed.  UNKNOWN sender: our membership view is stale
            # (we restarted and lost it) — the version check alone
            # cannot heal that, because placement_version is persisted
            # across restarts while membership is not: two nodes that
            # both cold-restarted (e.g. the seed and a peer killed
            # together) each re-learn only nodes that heartbeat THEM
            # and never each other, wedging membership in an
            # asymmetric split (surfaced by chaos
            # coordinator_crash_hint_log, r13)
            threading.Thread(target=self._pull_cluster_state,
                             args=(node_id,),
                             name="pilosa-placement-pull",
                             daemon=True).start()
        return {"id": self.node_id, "state": self.state,
                "placementVersion": ours,
                "hintsFor": (sorted(self.hints.pending_peers())
                             if self.hints is not None else [])}

    def status_payload(self) -> dict:
        """The full cluster-state snapshot served at
        ``/internal/cluster/state`` and broadcast after membership /
        placement changes."""
        with self._lock:
            return {"nodes": list(self.nodes.values()),
                    "state": self.state,
                    "placement": list(self.placement_ids),
                    "placementVersion": self.placement_version,
                    # replica factor rides along so external placement
                    # walkers (the backup/restore drivers) can compute
                    # shard_nodes without a config side channel
                    "replicas": self.cfg.replicas,
                    "ts": time.time()}

    def _pull_cluster_state(self, node_id: str) -> None:
        """Fetch a peer's cluster state and apply it (pull-on-mismatch
        convergence for missed broadcasts).  Single-flight: heartbeats
        from several newer peers must not stack redundant pulls."""
        if not self._placement_pull.acquire(blocking=False):
            return
        try:
            payload = self._client(node_id)._json(
                "GET", "/internal/cluster/state")
            self.handle_status(payload)
        except Exception as e:  # noqa: BLE001 — retried next heartbeat
            self.logger.warning("placement pull from %s failed: %s",
                                node_id, e)
        finally:
            self._placement_pull.release()

    def handle_status(self, payload: dict) -> None:
        now = time.monotonic()
        with self._lock:
            # out-of-order guard: RESIZING->NORMAL broadcasts may race
            if payload.get("ts", float("inf")) < self._status_ts:
                return
            self._status_ts = payload.get("ts", self._status_ts)
            for cleared_id in payload.get("cleared", []):
                self._removed.pop(cleared_id, None)
            # MERGE membership: a broadcast snapshotted before a
            # concurrent join must not evict the newer node (nodes are
            # only removed explicitly, never by omission); tombstoned
            # nodes stay out even if a stale snapshot carries them
            for n in payload["nodes"]:
                if n["id"] in self._removed:
                    continue
                if n["id"] == self.node_id:
                    # our OWN state is authoritative: a peer's snapshot
                    # may predate our recovery, and (now that heartbeat
                    # states stay fresh, r11) a DEGRADED-era echo would
                    # latch in our self entry forever — nothing else
                    # ever rewrites it
                    continue
                self.nodes[n["id"]] = n
                self._last_seen.setdefault(n["id"], now)
            self.state = payload["state"]
            pv = float(payload.get("placementVersion",
                                   payload.get("ts", 0.0)))
            if payload.get("placement") and pv >= self.placement_version:
                # version-gated: a stale peer's snapshot (e.g. a pull
                # answered from an even older node) must not regress an
                # already-activated topology
                self.placement_ids = sorted(payload["placement"])
                self.placement_version = pv
                self._save_placement()

    def _broadcast_status(self, cleared: list[str] | None = None) -> None:
        payload = self.status_payload()
        if cleared:
            payload["cleared"] = cleared
        for nid in self.member_ids():
            if nid == self.node_id:
                continue
            if fault.ACTIVE:
                spec = fault.fire("cluster.broadcast", peer=nid,
                                  path="/internal/cluster/status")
                # only `drop` skips the send (a triggered `delay`
                # already slept and the broadcast must still go out):
                # the peer must then converge via the placement
                # version riding heartbeats (pull-on-mismatch)
                if spec is not None and spec["action"] == "drop":
                    self.logger.warning("fault: status broadcast to %s "
                                        "dropped", nid)
                    continue
            try:
                self._client(nid)._json("POST", "/internal/cluster/status",
                                        payload)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("status broadcast to %s failed: %s",
                                    nid, e)

    def _heartbeat_loop(self) -> None:
        while not self._stop.wait(self.cfg.heartbeat_interval):
            self._heartbeat_once()

    def _heartbeat_once(self) -> None:
        """One heartbeat round (split out so tests can drive rounds
        deterministically)."""
        for nid in self.member_ids():
            if nid == self.node_id:
                continue
            # this round's heartbeat doubles as the breaker's half-open
            # probe: an OPEN peer steps to HALF_OPEN, then the result
            # below either closes it or re-opens it
            self.breakers.begin_probe(nid)
            try:
                resp = self._client(nid)._json(
                    "POST", "/internal/heartbeat",
                    {"id": self.node_id, "state": self.state,
                     "placementVersion": self.placement_version,
                     # pending-hint advertising rides every heartbeat
                     # both ways: the whole cluster learns which peers
                     # must not be AAE-synced within one interval
                     "hintsFor": (sorted(self.hints.pending_peers())
                                  if self.hints is not None else [])})
                self.breakers.record_success(nid)
                if resp.get("removed"):
                    # we were explicitly removed: drop to single-node
                    # membership (an operator rejoin brings us back)
                    self.logger.warning(
                        "this node was removed from the cluster by %s",
                        nid)
                    with self._lock:
                        self.nodes = {self.node_id:
                                      self.nodes.get(self.node_id,
                                                     {"id": self.node_id,
                                                      "uri": self.node_id,
                                                      "state": self.state})}
                    break
                with self._lock:
                    self._last_seen[nid] = time.monotonic()
                self._note_hints_inbound(nid,
                                         set(resp.get("hintsFor", ())))
                if (self.hints is not None
                        and self.hints.has_pending(nid)):
                    # the peer answered: it is reachable again — drain
                    # its hint backlog off-thread (single-flight)
                    self._drain_hints_async(nid)
                if (resp.get("placementVersion", 0.0)
                        > self.placement_version):
                    # the PEER activated a placement we missed (its
                    # broadcast is best-effort): pull it now — inline,
                    # this loop is already a background thread
                    self._pull_cluster_state(nid)
            except Exception as e:  # noqa: BLE001 — peer down
                from pilosa_tpu.api.client import ClientError
                if isinstance(e, ClientError) and e.status != 0:
                    # the peer ANSWERED (an HTTP error): alive for
                    # breaker purposes — only never-answered requests
                    # count toward opening, same rule as internal_query
                    # (an erroring-but-alive peer must not have its
                    # strict writes refused via _write_reachable)
                    self.breakers.record_success(nid)
                else:
                    self.breakers.record_failure(nid)
        alive = set(self.alive_ids())
        with self._lock:
            dead = set(self.nodes) - alive
            new_state = (STATE_DEGRADED if dead and
                         self.state == STATE_NORMAL else self.state)
            if new_state != self.state:
                self.logger.warning("nodes suspect: %s", sorted(dead))
                self.state = new_state
            if not dead and self.state == STATE_DEGRADED:
                self.state = STATE_NORMAL

    # -- hinted handoff (r13) ------------------------------------------------

    def _note_hints_inbound(self, holder: str, peers: set) -> None:
        """Record one holder's advertised pending-hint peer set (an
        empty set clears its entry — the holder's drain finished)."""
        with self._lock:
            if peers:
                self._hints_inbound[holder] = (set(peers),
                                               time.monotonic())
            else:
                self._hints_inbound.pop(holder, None)

    def hinted_peers(self) -> set[str]:
        """Every peer with pending hinted writes anywhere in the
        cluster — this node's own board plus what peers advertised on
        their heartbeats.  AAE defers all union-merge with these peers
        (and a node finding ITSELF here defers its own participation):
        its copies are stale until the replay lands, and a sync now
        could resurrect a cleared bit.

        Advertised entries expire after the suspect horizon: a holder
        that stopped refreshing is down, and gating forever on its
        word would leave the hinted peer unrepairable.  (Caveat: if a
        hint HOLDER stays dead past the horizon while its hinted peer
        rejoins, AAE may converge the stale copy before the holder
        returns to drain — a double failure the ``hint_max_age`` bound
        keeps narrow; see the README runbook.)"""
        horizon = SUSPECT_AFTER * self.cfg.heartbeat_interval
        now = time.monotonic()
        out: set[str] = set()
        with self._lock:
            stale = [h for h, (_, ts) in self._hints_inbound.items()
                     if now - ts > horizon]
            for h in stale:
                del self._hints_inbound[h]
            for peers, _ts in self._hints_inbound.values():
                out |= peers
        if self.hints is not None:
            out |= self.hints.pending_peers()
        return out

    def _drain_hints_async(self, peer: str) -> None:
        """Kick a background replay of ``peer``'s hint backlog (no-op
        when empty or already draining)."""
        if self.hints is None or not self.hints.has_pending(peer) \
                or peer == self.node_id:
            return
        threading.Thread(target=self._drain_hints, args=(peer,),
                         name="pilosa-hint-drain", daemon=True).start()

    def _drain_hints(self, peer: str) -> None:
        """Replay ``peer``'s hint log in append order via the
        idempotent ``/internal/hints/replay`` endpoint, acking (and
        compacting) batch by batch.  Single-flight per peer; a failed
        batch aborts and the next heartbeat retries.  Writes landing
        mid-drain keep appending behind the cursor — the loop runs
        until the log is empty, and the write path only resumes direct
        sends once ``pending_peers`` no longer lists the peer."""
        hints = self.hints
        if hints is None:
            return
        lock = hints.drain_lock(peer)
        if not lock.acquire(blocking=False):
            return
        try:
            batch_n = max(1, int(getattr(self.cfg, "hint_replay_batch",
                                         256)))
            total = 0
            while True:
                batch = hints.peek(peer, batch_n)
                if not batch:
                    break
                resp = self._client(peer)._json(
                    "POST", "/internal/hints/replay",
                    {"ops": [rec for _seq, rec in batch]})
                hints.ack(peer, batch[-1][0])
                total += len(batch)
                self.stats.count("hint_replay_total", len(batch),
                                 peer=peer)
                if resp.get("dropped"):
                    self.stats.count("hint_replay_dropped_total",
                                     resp["dropped"], peer=peer)
            if total:
                self.logger.info(
                    "hints: drained %d op(s) to %s; direct writes "
                    "resume", total, peer)
        except Exception as e:  # noqa: BLE001 — retried next heartbeat
            self.logger.warning("hint replay to %s failed: %s", peer, e)
        finally:
            lock.release()

    def write_health_payload(self) -> dict:
        """The ``writeHealth`` block on ``/status``: hint backlog and
        age (total + per peer), the configured bound, and the
        cluster-wide hinted-peer view AAE gating acts on."""
        out: dict = {"hintedHandoff": self.hints is not None}
        if self.hints is None:
            return out
        out["hintMaxAgeSeconds"] = float(self.cfg.hint_max_age)
        out.update(self.hints.summary())
        out["hintedPeers"] = sorted(self.hinted_peers())
        return out

    # -- schema broadcast ---------------------------------------------------

    def _broadcast(self, path: str, payload: dict, what: str) -> None:
        """POST a cluster message to every peer, best-effort (the
        shared loop behind schema/status/delete broadcasts)."""
        for nid in self.member_ids():
            if nid == self.node_id:
                continue
            if fault.ACTIVE:
                spec = fault.fire("cluster.broadcast", peer=nid,
                                  path=path)
                if spec is not None and spec["action"] == "drop":
                    self.logger.warning("fault: %s broadcast to %s "
                                        "dropped", what, nid)
                    continue
            try:
                self._client(nid)._json("POST", path, payload)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("%s broadcast to %s failed: %s",
                                    what, nid, e)

    def broadcast_schema(self) -> None:
        """Push the full schema to every peer (reference: CreateIndex/
        Field broadcast messages)."""
        self._broadcast("/internal/schema",
                        {"schema": self.api.schema()}, "schema")

    def broadcast_delete(self, index: str, field: str | None) -> None:
        """Propagate index/field deletion to every peer, recording a
        tombstone so stale full-schema pushes cannot resurrect it
        (reference: DeleteIndex/DeleteField broadcast messages)."""
        ts = time.time()
        with self._lock:
            self._schema_tombstones[(index, field)] = ts
        self._broadcast("/internal/schema/delete",
                        {"index": index, "field": field, "ts": ts},
                        "delete")

    def record_schema_tombstone(self, index: str, field: str | None,
                                ts: float) -> None:
        with self._lock:
            cur = self._schema_tombstones.get((index, field), 0.0)
            self._schema_tombstones[(index, field)] = max(cur, ts)

    def schema_settled(self, index: str, field: str | None) -> bool:
        """True when a LOCALLY-missing index/field can be judged
        deleted (hint-replay receiver drops the op) rather than
        not-yet-learned (receiver answers 503 so the sender's drain
        retries): boot-time join with its schema pull has completed,
        or a tombstone explicitly records the deletion.  Without this
        a drain racing a rejoiner's schema pull would permanently drop
        an acked write for an index created while the node was down."""
        if self._schema_ready.is_set():
            return True
        with self._lock:
            return ((index, None) in self._schema_tombstones
                    or (field is not None
                        and (index, field) in self._schema_tombstones))

    def filter_schema(self, schema: list[dict]) -> list[dict]:
        """Drop schema entries deleted AFTER their creation: an entry
        whose created_at predates its tombstone is a stale resurrection;
        a genuine recreate carries a newer created_at and passes."""
        with self._lock:
            tombs = dict(self._schema_tombstones)
        if not tombs:
            return schema
        out = []
        for ispec in schema:
            its = tombs.get((ispec["name"], None), 0.0)
            if ispec.get("createdAt", 0.0) <= its:
                continue
            fields = [f for f in ispec.get("fields", [])
                      if f.get("createdAt", 0.0)
                      > tombs.get((ispec["name"], f["name"]), 0.0)]
            out.append({**ispec, "fields": fields})
        return out

    # -- placement / routing -------------------------------------------------

    def shard_owners(self, index: str, shard: int) -> list[str]:
        """Replica owner node ids, primary first — computed over the
        ACTIVE placement topology (NOT raw membership: a just-joined
        node owns nothing until its resize finishes and the new
        topology is activated + broadcast).  Callers fail over with
        ``alive_ids``."""
        with self._lock:
            plist = list(self.placement_ids)
        return shard_nodes(index, shard, plist, self.cfg.replicas)

    def group_shards_by_node(self, index: str, shards: tuple[int, ...],
                             exclude=frozenset()) -> dict[str, tuple]:
        """Route each shard to one alive owner, replicas in placement
        order.  Peers with a non-closed breaker are SKIPPED while a
        healthy replica exists (straight to the replica — no per-query
        connect-timeout tax on a sick peer), but remain a last resort:
        the breaker is an optimization, never a correctness gate.
        ``exclude`` drops nodes entirely — read failover passes the
        nodes that already failed the leg."""
        alive = set(self.alive_ids()) - set(exclude)
        healthy = alive - self.breakers.unhealthy_peers()
        sh = getattr(self.api.holder, "storage_health", None)
        groups: dict[str, list[int]] = {}
        for s in shards:
            owners = self.shard_owners(index, s)
            target = next((o for o in owners if o in healthy), None)
            if target is None:
                target = next((o for o in owners if o in alive), None)
            if target is None:
                raise RuntimeError(
                    f"no alive replica for shard {s} of {index!r} "
                    f"(owners {owners})")
            if (target == self.node_id and sh is not None
                    and sh.shard_quarantined(index, s)):
                # a LOCAL fragment of this shard is quarantined
                # (corrupt, r19): serve the shard from a replica
                # exactly as if it were remote.  Self remains the last
                # resort — with no live replica a loud quarantined
                # answer still beats a refused read.
                alt = next((o for o in owners
                            if o in healthy and o != self.node_id),
                           None)
                if alt is None:
                    alt = next((o for o in owners
                                if o in alive and o != self.node_id),
                               None)
                if alt is not None:
                    target = alt
            groups.setdefault(target, []).append(s)
        return {k: tuple(v) for k, v in groups.items()}

    def index_shards(self, index: str,
                     strict: bool = False) -> tuple[int, ...]:
        """Cluster-wide shard universe for an index (short-TTL cache).

        When an ALIVE peer's shard list can't be fetched (one retry),
        the universe is INCOMPLETE: with ``strict`` that raises — a
        query served over it silently undercounts, and a ClearRow/Store
        that misses the sick peer's exclusive shards would later be
        resurrected cluster-wide by union-merge AAE (r5 review).
        Non-strict callers (AAE sweeps, resize planning) get the
        degraded view, cached only for ``_SHARD_NEG_TTL`` so recovery
        is quick but a sick peer isn't hammered per query.

        Replica bound (r11): with ``replicas`` copies, every shard has
        ``replicas`` holders — as long as the unheard nodes (fetch
        failures plus suspect members, which are never polled) number
        fewer than the replica factor, at least one holder of every
        shard was polled, so the union is still the complete universe
        and reads keep serving through a dead node instead of 500ing
        until the suspect horizon drops it.  With zero fetch failures
        the suspect count alone never marks incompleteness (baseline
        semantics: a dead node's exclusive shards are unreachable
        whether their ids are known or not — refusing every strict
        read on a degraded replicas=1 cluster would brick it).
        (Caveat: an orphan fragment held only by the sick peer
        mid-resize can hide; AAE's handoff window is the same exposure
        the pre-r11 code had.)  Peers with an OPEN breaker are counted
        as failed without paying the connect attempts."""
        def raise_incomplete():
            raise RuntimeError(
                f"shard universe for {index!r} is incomplete (an alive "
                "peer's shard list is unreadable); refusing to serve a "
                "silent partial answer")

        now = time.monotonic()
        with self._lock:
            hit = self._shard_cache.get(index)
            if hit is not None and now - hit[0] < _SHARD_CACHE_TTL:
                if hit[2] and strict:
                    raise_incomplete()
                return hit[1]
        failed = 0
        shards: set[int] = set()
        idx = self.api.holder.index(index)
        if idx is not None:
            shards.update(idx.available_shards())

        def fetch(nid) -> bool:
            try:
                try:
                    resp = self._client(nid)._json(
                        "GET", f"/internal/shards?index={index}")
                except Exception:  # noqa: BLE001 — one retry
                    resp = self._client(nid)._json(
                        "GET", f"/internal/shards?index={index}")
                shards.update(resp["shards"])
                return True
            except Exception as e:  # noqa: BLE001
                self.logger.warning(
                    "shard list from %s failed: %r", nid, e)
                return False

        bound = max(1, int(self.cfg.replicas))
        alive = set(self.alive_ids())
        with self._lock:
            members = set(self.placement_ids) | set(self.nodes)
        # SUSPECT members are never polled; they count toward the
        # bound when PAIRED with a fetch failure — a dead owner plus a
        # transient failure on its co-replica can cover all holders of
        # a shard, and declaring that complete silently undercounts.
        # With no fetch failures the universe keeps baseline semantics:
        # a suspect node's exclusive shards are unreachable whether we
        # know their ids or not, and refusing every strict read on a
        # degraded replicas=1 cluster would brick it for no gain.
        suspect = len(members - alive - {self.node_id})
        deferred = []  # open-breaker peers: skip the connect tax...
        for nid in sorted(alive):
            if nid == self.node_id:
                continue
            if self.breakers.state(nid) == "open":
                deferred.append(nid)
                continue
            if not fetch(nid):
                failed += 1

        def at_risk(n_failed: int) -> bool:
            return n_failed >= 1 and n_failed + suspect >= bound

        if at_risk(failed + len(deferred)):
            # ... unless skipping them would make the universe
            # incomplete — the breaker is never a correctness gate, so
            # give the open peers their chance to answer
            failed += sum(not fetch(nid) for nid in deferred)
        else:
            failed += len(deferred)
        incomplete = at_risk(failed)
        out = tuple(sorted(shards)) if shards else (0,)
        with self._lock:
            if incomplete:
                # short negative TTL: retry soon, but don't let
                # non-strict callers hammer a sick peer in the meantime
                self._shard_cache[index] = (
                    now - _SHARD_CACHE_TTL + _SHARD_NEG_TTL, out, True)
            else:
                self._shard_cache[index] = (now, out, False)
        if incomplete and strict:
            raise_incomplete()
        return out

    def internal_query(self, node_id: str, index: str, pql: str,
                       shards, deadline: float | None = None,
                       map_unreachable: bool = True,
                       trace: dict | None = None) -> list:
        """Run ``pql`` on ``node_id`` via ``/internal/query``.

        ``trace`` (cross-node span fan-in, r9): a mutable dict whose
        ``headers`` carry the coordinator's ``Traceparent``; on return
        it gains ``profile`` (the peer's finished span subtree, JSON)
        and ``retried`` (the transport redelivered the request), which
        the dist layer grafts into the coordinator's span tree.

        Error mapping (ADVICE r4): every failure leaves here as an
        executor exception the API layer answers with 4xx/504 — except
        kind=="unreachable" when ``map_unreachable=False``, which write
        replication (`dist._run_on`) needs verbatim to distinguish
        "peer never saw the write" (safe to skip best-effort) from
        "peer may have applied it" (state unknown — never skippable).
        """
        from pilosa_tpu.api.client import ClientError
        from pilosa_tpu.exec.executor import (ExecutionError,
                                              QueryTimeoutError)
        path = f"/internal/query?index={index}"
        if shards:
            path += "&shards=" + ",".join(str(s) for s in shards)
        socket_timeout = None
        if deadline is not None:
            # ship the REMAINING budget: the peer re-anchors it on its
            # own monotonic clock (wall clocks may disagree; budgets
            # don't).  An already-expired budget fails here.  The
            # socket timeout follows the budget (+slack for transfer
            # and the peer's own 504 answer) — the Client default would
            # otherwise cap every remote leg at 60 s regardless of the
            # query's deadline.
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise QueryTimeoutError("query timeout exceeded")
            path += f"&timeout={remaining:.6f}"
            socket_timeout = remaining + 10.0
        client = self._client(node_id)
        try:
            resp = client._do(
                "POST", path, pql.encode(),
                headers=(trace or {}).get("headers"),
                timeout=socket_timeout)
            self.breakers.record_success(node_id)
            if trace is not None:
                trace["profile"] = resp.get("profile") or []
                trace["retried"] = client.last_retried()
            return resp["results"]
        except ClientError as e:
            # breaker accounting: only never-answered transport faults
            # count toward opening (an HTTP error means the peer is
            # alive; a post-send timeout may be the query's fault)
            if e.status == 0 and e.kind in ("unreachable", "transport"):
                self.breakers.record_failure(node_id)
            elif e.status != 0:
                self.breakers.record_success(node_id)
            if e.status in (408, 504):
                # peer's share of the budget expired (504 since r11;
                # 408 kept for mixed-version peers mid-upgrade)
                raise QueryTimeoutError(str(e)) from e
            if e.status == 400:
                # peer rejected the query itself: surface as a query
                # error (HTTP 400 at the public edge), not a node fault
                raise ExecutionError(str(e)) from e
            if e.kind == "timeout":
                # the request was SENT; the peer may still be working
                # (or may yet apply a write) — state unknown, never
                # classed as "node down"
                if deadline is not None:
                    raise QueryTimeoutError(
                        f"remote leg on {node_id} outran the query "
                        f"deadline: {e}") from e
                raise ExecutionError(
                    f"request to {node_id} timed out; state unknown "
                    f"on that node: {e}") from e
            if map_unreachable and e.kind != "http":
                raise ExecutionError(
                    f"node {node_id} unreachable: {e}") from e
            raise

    # -- key translation (coordinator-assigned, replicated logs) ------------

    def translate_keys(self, index: str, field: str | None,
                       keys: list[str], create: bool) -> list[int | None]:
        log = (self.api.executor.translate.columns(index) if field is None
               else self.api.executor.translate.rows(index, field))
        ids = log.translate(keys, create=False)
        if all(i is not None for i in ids) or not create:
            return ids
        if self.is_coordinator():
            ids = log.translate(keys, create=True)
            self._replicate_keys(index, field, log)
            return ids
        resp = self._client(self.coordinator_id())._json(
            "POST", "/internal/translate",
            {"index": index, "field": field, "keys": keys, "create": True})
        # the coordinator replicated synchronously; but don't rely on it
        self._sync_log_from_coordinator(index, field, log)
        return resp["ids"]

    def handle_translate(self, index: str, field: str | None,
                         keys: list[str], create: bool) -> list[int | None]:
        if not self.is_coordinator() and create:
            raise PermissionError("not the coordinator")
        log = (self.api.executor.translate.columns(index) if field is None
               else self.api.executor.translate.rows(index, field))
        ids = log.translate(keys, create=create)
        if create:
            self._replicate_keys(index, field, log)
        return ids

    # Keys per page when streaming translate tails between nodes — a
    # 10M-key store syncs as ~100 bounded responses, not one giant one.
    TRANSLATE_PAGE = 100_000

    def _replicate_keys(self, index: str, field: str | None, log) -> None:
        """Best-effort synchronous replication of the tail each batch,
        paged (logs are append-only; peers dedupe)."""
        f = field or ""
        for nid in self.alive_ids():
            if nid == self.node_id:
                continue
            try:
                peer_len = self._client(nid)._json(
                    "GET", f"/internal/translate/len?index={index}"
                    f"&field={f}")["len"]
                while True:
                    tail = log.tail(peer_len, limit=self.TRANSLATE_PAGE)
                    if not tail:
                        break
                    self._client(nid)._json(
                        "POST", "/internal/translate/replicate",
                        {"index": index, "field": field,
                         "start_id": peer_len + 1, "keys": tail})
                    peer_len += len(tail)
                    if len(tail) < self.TRANSLATE_PAGE:
                        break
            except Exception as e:  # noqa: BLE001 — repaired by pull later
                self.logger.warning("translate replicate to %s failed: %s",
                                    nid, e)

    def _tail_path(self, index: str, field: str | None, after: int) -> str:
        f = field or ""
        return (f"/internal/translate/tail?index={index}&field={f}"
                f"&after={after}&limit={self.TRANSLATE_PAGE}")

    def _pull_log_tail(self, source: str, index: str, field: str | None,
                       log) -> None:
        """Pull a peer's tail into ``log``, paged until caught up."""
        while True:
            resp = self._client(source)._json(
                "GET", self._tail_path(index, field, len(log)))
            if not resp["keys"]:
                break
            log.append_replicated(len(log) + 1, resp["keys"])
            if len(log) >= resp.get("len", 0):
                break

    def _sync_log_from_coordinator(self, index: str, field: str | None,
                                   log) -> None:
        coord = self.coordinator_id()
        if coord == self.node_id:
            return
        try:
            self._pull_log_tail(coord, index, field, log)
        except Exception as e:  # noqa: BLE001
            self.logger.warning("translate tail pull failed: %s", e)

    def _pull_translate_tails(self, seed: str) -> None:
        """On join: pull every key log the seed has."""
        try:
            listing = self._client(seed)._json("GET", "/internal/translate/logs")
        except Exception:  # noqa: BLE001
            return
        for entry in listing.get("logs", []):
            index, field = entry["index"], entry["field"]
            log = (self.api.executor.translate.columns(index)
                   if field is None
                   else self.api.executor.translate.rows(index, field))
            try:
                self._pull_log_tail(seed, index, field, log)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("translate pull %s/%s failed: %s",
                                    index, field, e)

    def keys_of(self, index: str, field: str | None, ids) -> list[str]:
        log = (self.api.executor.translate.columns(index) if field is None
               else self.api.executor.translate.rows(index, field))
        out, missing = [], False
        for i in ids:
            k = log.key_of(int(i))
            if k is None:
                missing = True
                break
            out.append(k)
        if not missing:
            return out
        self._sync_log_from_coordinator(index, field, log)
        return [log.key_of(int(i)) or f"<unknown:{i}>" for i in ids]

    # -- anti-entropy (reference: holder syncer, SURVEY.md §4.6) ------------

    def _aae_loop(self) -> None:
        while not self._stop.wait(self.cfg.anti_entropy_interval):
            try:
                self.sync_once()
            except Exception as e:  # noqa: BLE001
                self.logger.warning("anti-entropy round failed: %s", e)

    def sync_once(self) -> int:
        """One AAE round: for every local fragment replicated elsewhere,
        diff block checksums with each replica and union-merge
        differences both ways.  Returns blocks repaired.

        Hinted-handoff ordering rule (r13): any sync with a peer that
        has pending hinted writes anywhere in the cluster is DEFERRED
        — its copies are stale until the ordered replay lands, and a
        union-merge now could resurrect a Clear the replay is about to
        deliver.  A node finding ITSELF hinted sits the round out for
        the same reason."""
        repaired = 0
        deferred = 0
        hinted = self.hinted_peers()
        if self.node_id in hinted:
            self.logger.info("anti-entropy deferred: hinted writes "
                             "pending for this node (replay first)")
            self.stats.count("aae_hint_deferred_total", 1)
            return 0
        holder = self.api.holder
        storage_health = getattr(holder, "storage_health", None)
        for iname, idx in list(holder.indexes.items()):
            for fname, f in list(idx.fields.items()):
                for vname, v in list(f.views.items()):
                    for shard, frag in list(v.fragments.items()):
                        if storage_health is not None \
                                and storage_health.is_quarantined(
                                    frag.path):
                            # quarantined (r19): this copy is
                            # untrustworthy — pushing its blocks would
                            # spread the corruption; replica repair
                            # owns it, AAE resumes after un-quarantine
                            deferred += 1
                            continue
                        owners = self.shard_owners(iname, shard)
                        if self.node_id not in owners:
                            # ORPHAN: we hold a fragment the active
                            # topology doesn't assign us (e.g. a Set
                            # that landed here mid-resize, just before
                            # the placement flipped — r5 review).  Hand
                            # the bits to every alive owner, then drop
                            # our copy so the handoff is one-time.
                            if hinted & set(owners):
                                deferred += 1
                                continue
                            repaired += self._handoff_orphan(
                                iname, fname, vname, shard, frag, v,
                                owners)
                            continue
                        for peer in owners:
                            if peer == self.node_id:
                                continue
                            if peer in hinted:
                                deferred += 1
                                continue
                            repaired += self._sync_fragment(
                                peer, iname, fname, vname, shard, frag)
        repaired += self._sync_attrs(exclude=hinted)
        if deferred:
            self.stats.count("aae_hint_deferred_total", deferred)
        if repaired:
            self.logger.info("anti-entropy repaired %d blocks", repaired)
            self.stats.count("aae_blocks_repaired", repaired)
        return repaired

    def _handoff_orphan(self, index: str, field: str, view: str,
                        shard: int, frag, view_obj, owners) -> int:
        """Union-merge an un-owned local fragment into EVERY alive
        owner, then delete the local copy (only if all owners took it —
        a failed push keeps the orphan for the next round).

        Two ADVICE r5 fixes: (1) the fragment's generation is
        re-checked UNDER ITS LOCK before the delete — bits written
        between the push snapshot and the delete (a Set routed here by
        a peer with stale placement) trigger a re-push instead of
        being permanently lost; (2) EMPTY orphans are deleted instead
        of being re-scanned every AAE round forever.

        Deletion runs under the VIEW lock (then the fragment lock —
        the same view→fragment order the store uses): pop, close, AND
        unlink together, so a concurrent ``view.fragment(create=True)``
        cannot recreate the fragment at the same path between the pop
        and the unlink and have its fresh files unlinked from under it
        (that write would vanish on restart with no AAE record)."""
        import os

        def _delete_local(check) -> bool:
            """Atomically (view lock → frag lock) re-check ``check``,
            then pop + close + unlink.  False = re-check failed."""
            with view_obj._lock:
                with frag.lock:
                    if not check():
                        return False
                    view_obj.fragments.pop(shard, None)
                    path = frag.path
                    frag.close()
                    for suffix in ("", ".oplog"):
                        try:
                            os.remove(path + suffix)
                        except OSError:
                            pass
            return True

        if self.state != STATE_NORMAL:
            return 0  # mid-resize: the job itself is moving fragments
        if not frag.row_ids():
            # empty orphan: drop it now (emptiness re-checked under the
            # locks — a write may have landed since the check above)
            if _delete_local(lambda: not frag.row_ids()):
                self.logger.info(
                    "empty orphan fragment %s/%s/%s/%d deleted",
                    index, field, view, shard)
            return 0
        alive = set(self.alive_ids())
        if not all(o in alive for o in owners):
            return 0  # can't guarantee full handoff; retry next round
        for _attempt in range(3):
            gen = frag.generation
            try:
                for dest in owners:
                    self.push_fragment(index, field, view, shard, dest)
            except Exception as e:  # noqa: BLE001 — keep orphan, retry
                self.logger.warning("orphan handoff %s/%s/%s/%d: %s",
                                    index, field, view, shard, e)
                return 0
            if _delete_local(lambda: frag.generation == gen):
                self.logger.info(
                    "orphan fragment %s/%s/%s/%d handed to %s",
                    index, field, view, shard, owners)
                return 1
            # mutated during the push: those bits are not in the
            # snapshot we shipped — push again before deleting
        return 0  # kept hot by writers; next AAE round retries

    def _sync_attrs(self, exclude: set | frozenset = frozenset()) -> int:
        """AAE for attribute stores (reference: AttrStore block sync,
        SURVEY.md §4.6).  Attr stores are fully replicated: diff with
        every alive peer, merge differing blocks both ways.
        ``exclude``: hinted peers — their attr state is stale until
        the ordered replay lands (same deferral rule as fragments)."""
        repaired = 0
        holder = self.api.holder
        targets: list[tuple[str, str]] = []  # (index, field-or-"")
        for iname, idx in list(holder.indexes.items()):
            if os.path.exists(os.path.join(idx.path, "_attrs.db")):
                targets.append((iname, ""))
            for fname, f in list(idx.fields.items()):
                if os.path.exists(os.path.join(f.path, "_attrs.db")):
                    targets.append((iname, fname))
        for iname, fname in targets:
            idx = holder.index(iname)
            store = (idx.field(fname).row_attrs if fname
                     else idx.column_attrs)
            qs = f"index={iname}&field={fname}"
            for peer in self.alive_ids():
                if peer == self.node_id or peer in exclude:
                    continue
                try:
                    theirs = self._client(peer)._json(
                        "GET", f"/internal/attrs/blocks?{qs}")["blocks"]
                except Exception:  # noqa: BLE001 — peer down
                    continue
                theirs = {int(k): v for k, v in theirs.items()}
                ours = store.blocks()
                for block in sorted(b for b in set(ours) | set(theirs)
                                    if ours.get(b) != theirs.get(b)):
                    try:
                        items = self._client(peer)._json(
                            "GET", f"/internal/attrs/block?{qs}"
                            f"&block={block}")["items"]
                        store.merge_items({int(k): v
                                           for k, v in items.items()})
                        mine = store.block_items(block)
                        self._client(peer)._json(
                            "POST", f"/internal/attrs/merge?{qs}",
                            {"items": {str(k): v
                                       for k, v in mine.items()}})
                        repaired += 1
                    except Exception as e:  # noqa: BLE001
                        self.logger.warning("attr aae %s/%s block %d: %s",
                                            iname, fname, block, e)
        return repaired

    def _sync_fragment(self, peer: str, index: str, field: str, view: str,
                       shard: int, frag) -> int:
        from pilosa_tpu.api.client import ClientError
        from pilosa_tpu.store import roaring
        qs = f"index={index}&field={field}&view={view}&shard={shard}"
        try:
            theirs = self._client(peer)._json(
                "GET", f"/internal/fragment/blocks?{qs}")["blocks"]
        except ClientError as e:
            if e.status == 404:
                # peer lost the whole fragment (or never had it): that
                # is maximal divergence, not "peer down" — diff against
                # empty so every block streams over (config17 r5: the
                # swallowed 404 left deleted replicas unrepaired)
                theirs = {}
            else:
                return 0  # transport trouble; next round
        except Exception:  # noqa: BLE001 — peer down; next round
            return 0
        theirs = {int(k): v for k, v in theirs.items()}
        ours = frag.blocks()
        diff = [b for b in set(ours) | set(theirs)
                if ours.get(b) != theirs.get(b)]
        repaired = 0
        for block in sorted(diff):
            try:
                if block in theirs:
                    blob = self._client(peer)._do(
                        "GET",
                        f"/internal/fragment/data?{qs}&block={block}")
                    frag.merge_positions(roaring.deserialize(blob))
                mine = roaring.serialize(frag.block_positions(block))
                self._client(peer)._do(
                    "POST", f"/internal/fragment/merge?{qs}", mine,
                    content_type="application/octet-stream")
                repaired += 1
            except ClientError as e:
                if e.status == 409:
                    # hint-gated on the receiver (pending hinted
                    # writes cover the fragment): quietly defer the
                    # whole fragment to the post-drain round
                    return repaired
                self.logger.warning("aae %s/%s/%s/%d block %d: %s",
                                    index, field, view, shard, block, e)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("aae %s/%s/%s/%d block %d: %s",
                                    index, field, view, shard, block, e)
        return repaired

    # -- resize (reference: ResizeJob, SURVEY.md §3.3) ----------------------

    def trigger_resize(self) -> None:
        """Spawn a background rebalance (coordinator only).  Any
        in-flight job is ABORTED first (reference: ``ResizeJob`` abort on
        superseding node events) — it stops at the next fragment-copy
        boundary; the new job recomputes against current membership, so
        partial copies are never lost, only re-planned."""
        self._resize_abort.set()
        self._spawn(self._resize_job, "resize")

    def abort_resize(self) -> None:
        """Abort an in-flight rebalance at the next copy boundary."""
        self._resize_abort.set()

    # -- explicit removal (reference: remove-node resize, SURVEY.md §6) -----

    def remove_node(self, node_id: str) -> None:
        """Coordinator: remove a node from membership (dead or retiring),
        tombstone it, broadcast the removal, and rebalance so remaining
        replicas restore the replication factor."""
        if not self.is_coordinator():
            raise PermissionError(
                f"not the coordinator (coordinator is "
                f"{self.coordinator_id()})")
        if node_id == self.node_id:
            raise ValueError("coordinator cannot remove itself")
        with self._lock:
            if node_id not in self.nodes:
                raise KeyError(node_id)
            del self.nodes[node_id]
            self._last_seen.pop(node_id, None)
            self._removed[node_id] = time.time()
        payload = {"id": node_id, "ts": time.time()}
        for nid in self.member_ids():
            if nid == self.node_id:
                continue
            try:
                self._client(nid)._json("POST", "/internal/node/remove",
                                        payload)
            except Exception as e:  # noqa: BLE001
                self.logger.warning("remove broadcast to %s failed: %s",
                                    nid, e)
        self.logger.info("removed node %s; rebalancing", node_id)
        self.trigger_resize()

    def handle_node_remove(self, payload: dict) -> None:
        with self._lock:
            self.nodes.pop(payload["id"], None)
            self._last_seen.pop(payload["id"], None)
            self._removed[payload["id"]] = payload.get("ts", time.time())

    def _resize_job(self) -> None:
        """Coordinator: rebalance fragments onto the current membership.
        Gather inventories, compute transfers, instruct sources to push.
        Jobs serialize on ``_resize_lock``; the cluster always lands on
        NORMAL afterwards."""
        with self._resize_lock:
            self._resize_abort.clear()
            self._resize_once()

    def _resize_once(self) -> None:
        with self._lock:
            self.state = STATE_RESIZING
            target = self.member_ids()
        self._broadcast_status()
        completed = False
        try:
            inventory: dict[tuple, list[str]] = {}
            for nid in self.alive_ids():
                try:
                    frags = (self._local_inventory()
                             if nid == self.node_id else
                             self._client(nid)._json(
                                 "GET", "/internal/fragments")["fragments"])
                except Exception as e:  # noqa: BLE001
                    self.logger.warning("inventory from %s failed: %s",
                                        nid, e)
                    continue
                for fr in frags:
                    key = (fr["index"], fr["field"], fr["view"], fr["shard"])
                    inventory.setdefault(key, []).append(nid)
            moved = 0
            for (index, field, view, shard), holders in inventory.items():
                if self._resize_abort.is_set():
                    self.logger.info(
                        "resize aborted after %d copies (superseded)",
                        moved)
                    return
                owners = shard_nodes(index, shard, target,
                                     self.cfg.replicas)
                for dest in owners:
                    if dest in holders:
                        continue
                    src = holders[0]
                    try:
                        if src == self.node_id:
                            self.push_fragment(index, field, view, shard,
                                               dest)
                        else:
                            self._client(src)._json(
                                "POST", "/internal/resize/push",
                                {"index": index, "field": field,
                                 "view": view, "shard": shard,
                                 "dest": dest})
                        moved += 1
                    except Exception as e:  # noqa: BLE001
                        self.logger.warning("resize push %s -> %s: %s",
                                            (index, field, view, shard),
                                            dest, e)
            self.logger.info("resize complete: %d fragment copies moved",
                             moved)
            completed = True
        finally:
            with self._lock:
                self.state = STATE_NORMAL
                if completed:
                    # every copy for the target topology is streamed:
                    # activate it (and broadcast) so reads start
                    # routing to the new owners.  The version rides
                    # every heartbeat, so a peer that misses this
                    # broadcast still converges (pull-on-mismatch).
                    # max(now, prev+1): a coordinator whose wall clock
                    # trails the previous coordinator's must still mint
                    # a STRICTLY newer version, or peers would reject
                    # (and pull back over) the new topology
                    self.placement_ids = list(target)
                    self.placement_version = max(
                        time.time(), self.placement_version + 1.0)
                    self._save_placement()
            self._broadcast_status()

    def _local_inventory(self) -> list[dict]:
        out = []
        for iname, idx in self.api.holder.indexes.items():
            for fname, f in idx.fields.items():
                for vname, v in f.views.items():
                    for shard, frag in v.fragments.items():
                        if frag.row_ids():
                            out.append({"index": iname, "field": fname,
                                        "view": vname, "shard": shard})
        return out

    def push_fragment(self, index: str, field: str, view: str, shard: int,
                      dest: str) -> None:
        """Send one local fragment's bits to ``dest`` (union-merge
        there)."""
        from pilosa_tpu.store import roaring
        idx = self.api.holder.index(index)
        frag = idx.field(field).view(view).fragment(shard)
        sh = getattr(self.api.holder, "storage_health", None)
        if sh is not None and sh.is_quarantined(frag.path):
            # a resize/orphan push from a corrupt copy would spread
            # the corruption to the new owner — refuse loudly (the
            # resize job logs and retries after repair)
            raise RuntimeError(
                f"fragment {frag.path} is quarantined (storage "
                "corruption); not pushing until repaired")
        blob = roaring.serialize(frag.positions())
        qs = f"index={index}&field={field}&view={view}&shard={shard}"
        self._client(dest)._do(
            "POST", f"/internal/fragment/merge?{qs}", blob,
            content_type="application/octet-stream")

    # -- quarantine repair (r19 storage integrity) ---------------------------

    def repair_quarantined(self, entry: dict) -> bool:
        """Replica repair for one quarantined fragment (the scrubber's
        ``on_corrupt`` hook): pull a healthy replica's FULL position
        set over the AAE data path, rebuild the local fragment
        wholesale (fresh framed snapshot, truncated op-log), re-verify
        the new bytes, un-quarantine.  While this runs, reads keep
        serving from the replica (``group_shards_by_node`` routes
        around us) and local writes keep refusing — the replica's copy
        therefore includes every write accepted during quarantine, so
        the rebuild loses nothing.  Returns True when repaired; a
        False (no live replica, pull failed, disk still refusing)
        leaves the quarantine in place for the next scrub pass."""
        from pilosa_tpu.store import roaring as _roaring
        from pilosa_tpu.store import scrub as _scrub
        sh = getattr(self.api.holder, "storage_health", None)
        key = entry.get("key")
        if sh is None or key is None:
            return False  # not a fragment of this tree
        index, field, view, shard = key
        idx = self.api.holder.index(index)
        fld = idx.field(field) if idx is not None else None
        vw = fld.view(view) if fld is not None else None
        frag = vw.fragment(shard) if vw is not None else None
        if frag is None:
            # the fragment no longer exists (index/field deleted):
            # nothing to repair, drop the stale quarantine entry
            sh.unquarantine(entry["path"])
            return True
        alive = set(self.alive_ids())
        sources = [o for o in self.shard_owners(index, shard)
                   if o != self.node_id and o in alive]
        # breaker-closed replicas first; open peers stay a last resort
        sources.sort(key=lambda o: self.breakers.state(o) != "closed")
        qs = (f"index={index}&field={field}&view={view}"
              f"&shard={shard}")
        for src in sources:
            try:
                blob = self._client(src)._do(
                    "GET", f"/internal/fragment/data?{qs}")
                positions = _roaring.deserialize(blob)
            except Exception as e:  # noqa: BLE001 — try the next replica
                self.logger.warning(
                    "storage repair: pull %s/%s/%s/%d from %s failed: "
                    "%s", index, field, view, shard, src, e)
                continue
            try:
                frag.rebuild_from_positions(positions)
            except OSError as e:
                self.logger.error(
                    "storage repair: rebuild of %s failed on disk: %s "
                    "(quarantine stays; next scrub pass retries)",
                    frag.path, e)
                return False
            problems, _ = _scrub.verify_fragment(frag)
            if problems is None or problems:
                # None = no verdict (the scan raced a file change) —
                # un-quarantining on anything short of a VERIFIED
                # clean read would put unconfirmed bytes back into
                # service; the quarantine stays and the next scrub
                # pass retries the repair
                self.logger.error(
                    "storage repair: REBUILT fragment %s did not "
                    "verify clean (%s) — quarantine stays; next scrub "
                    "pass retries", frag.path,
                    "no verdict" if problems is None else problems)
                return False
            sh.unquarantine(frag.path)
            sh.note_repair(frag.path, source=src)
            self.logger.info(
                "storage repair: fragment %s/%s/%s/%d rebuilt from "
                "replica %s (%d positions) and re-verified",
                index, field, view, shard, src, len(positions))
            return True
        self.logger.warning(
            "storage repair: no live replica for quarantined "
            "%s/%s/%s/%d (owners %s); retrying next scrub pass",
            index, field, view, shard,
            self.shard_owners(index, shard))
        return False

    # -- observability fan-in (r14: the single-pane cluster view) ------------

    # per-peer budget for one observability fetch: a scrape of the
    # whole fleet must finish inside a Prometheus scrape interval even
    # when one peer is wedged mid-crash (the fetches run concurrently,
    # so this bounds the WHOLE fan-in, not N× it)
    OBS_FANIN_TIMEOUT = 2.0

    def _obs_fanin(self, fetch) -> tuple[dict[str, dict], list[str]]:
        """Breaker-aware concurrent fan-out of one observability fetch
        per peer; returns ``({node_id: payload}, [stale node ids])``.

        Partial-result contract: a suspect member, an open-breaker
        peer, a failed fetch, or a fetch still running at the overall
        deadline lands the node on the ``stale`` list — never an
        error, and never a probe.  Scrapes OBSERVE the fleet; they
        must not perturb routing, so outcomes here deliberately stay
        out of the breaker accounting (a monitoring burst against a
        half-open peer must not flap reads).

        Each fetch thread writes ONLY its own slot dict: the client
        timeout is per socket operation, not a deadline (connect +
        read + an idempotent-GET retry can outlive the join budget),
        so a thread may finish AFTER this method returned — a shared
        dict would then mutate under the caller's render iteration.
        Threads alive at the deadline are reported stale and their
        late result is simply never read."""
        alive = set(self.alive_ids())
        peers = [nid for nid in self.member_ids() if nid != self.node_id]

        def one(nid: str, slot: dict) -> None:
            if nid not in alive or self.breakers.state(nid) == "open":
                return  # empty slot = stale
            try:
                slot["payload"] = fetch(self._client(nid))
            except Exception:  # noqa: BLE001 — degraded, never an error
                pass

        slots = [(nid, {}) for nid in peers]
        threads = [threading.Thread(target=one, args=(nid, slot),
                                    name="pilosa-obs-fanin", daemon=True)
                   for nid, slot in slots]
        for t in threads:
            t.start()
        # one overall deadline (not per-thread): a scrape of the whole
        # fleet must finish inside a Prometheus scrape interval even
        # when several peers are wedged mid-crash
        deadline = time.monotonic() + self.OBS_FANIN_TIMEOUT + 1.0
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        results: dict[str, dict] = {}
        stale: list[str] = []
        for (nid, slot), t in zip(slots, threads):
            payload = None if t.is_alive() else slot.get("payload")
            if payload is None:
                stale.append(nid)
            else:
                results[nid] = payload
        return results, sorted(stale)

    def metrics_snapshots(self) -> tuple[dict[str, dict], list[str]]:
        """Per-peer :meth:`pilosa_tpu.obs.metrics.Stats.full_snapshot`
        payloads for the ``GET /metrics/cluster`` fan-in (the caller
        adds its own local snapshot after refreshing scrape-time
        gauges)."""
        return self._obs_fanin(
            lambda client: client._do(
                "GET", "/internal/metrics/snapshot",
                timeout=self.OBS_FANIN_TIMEOUT)["snapshot"])

    def status_snapshots(self) -> tuple[dict[str, dict], list[str]]:
        """Per-peer ``/status`` payloads for ``GET /status/cluster``."""
        return self._obs_fanin(
            lambda client: client._do("GET", "/status",
                                      timeout=self.OBS_FANIN_TIMEOUT))

    def flight_snapshots(self, limit: int = 0) \
            -> tuple[dict[str, dict], list[str]]:
        """Per-peer ``/debug/flight`` payloads (r19): one call pulls
        every node's dispatch-lifecycle ring so a fleet-wide incident
        timeline can be assembled without shelling into each box."""
        path = "/debug/flight" + (f"?limit={int(limit)}" if limit else "")
        return self._obs_fanin(
            lambda client: client._do("GET", path,
                                      timeout=self.OBS_FANIN_TIMEOUT))

    # -- introspection -------------------------------------------------------

    def health_payload(self) -> dict:
        """The ``clusterHealth`` block on ``/status``: per-peer
        last-seen age, suspect verdict, and breaker state — what an
        operator needs to see why reads are (or are not) detouring."""
        alive = set(self.alive_ids())
        now = time.monotonic()
        horizon = SUSPECT_AFTER * self.cfg.heartbeat_interval
        with self._lock:
            members = sorted(self.nodes)
            seen = dict(self._last_seen)
        peers = []
        for nid in members:
            if nid == self.node_id:
                continue
            age = (now - seen[nid]) if nid in seen else None
            peers.append({
                "id": nid,
                "lastSeenAgeSeconds": (round(age, 3)
                                       if age is not None else None),
                "suspect": nid not in alive,
                "breaker": self.breakers.state(nid)})
        return {"suspectAfterSeconds": horizon, "peers": peers}

    def nodes_status(self) -> list[dict]:
        alive = set(self.alive_ids())
        coord = self.coordinator_id()
        return [{"id": nid, "uri": n["uri"],
                 "state": (n.get("state", STATE_NORMAL)
                           if nid in alive else "DOWN"),
                 "isPrimary": nid == coord}
                for nid, n in sorted(self.nodes.items())]
