"""Durable hinted handoff: the write-path availability layer.

Reference shape: Cassandra/Riak hinted handoff, grafted onto this
repo's oplog discipline (CRC-framed records, clean-prefix crash
recovery, torn-write failpoint — :mod:`pilosa_tpu.store.oplog`).

When a write (strict or best-effort) finds a replica down — breaker
open, suspect, or transport-failed mid-apply — the coordinator appends
the already-translated op to a crash-safe on-disk **hint log** for
that peer and keeps serving on the live replicas.  On peer rejoin (or
breaker close) a replay worker drains the log to the peer through the
idempotent ``POST /internal/hints/replay`` endpoint (receiver dedup by
unique op id), in append order.

Ordering rules that make this exact for Clear-family ops (which have
no tombstones in bit data — a missed clear would otherwise be
resurrected by union-merge anti-entropy):

- a peer with pending hints is **not write-reachable**: new writes to
  it append behind the older hints (one ordered stream per peer)
  until the drain empties the log;
- **AAE defers union-merge** for any fragment sync with a peer that
  has pending hints anywhere in the cluster.  Pending-ness propagates
  on every heartbeat (``hintsFor``) and in the join response, so the
  rejoined stale peer and every up-to-date replica both stop syncing
  with each other before the first AAE tick can run — a replayed
  Clear can never be resurrected by a concurrent sync.

Boundedness: hints older than ``hint_max_age`` flip the op class back
to loud refusal (HTTP 503 + ``Retry-After``) — the log cannot grow
without bound and divergence cannot outlive the age cap + one AAE
round.

On-disk layout: ``<data-dir>/_hints/<peer-utf8-hex>.hints``, one log
per peer.  Record frame (little-endian)::

    u32 crc32 (of everything after this field)
    u64 seq   monotonic per peer (and therefore per (peer, fragment))
    f64 ts    wall-clock append time (drives hint_oldest_seconds)
    u32 len   payload byte length
    payload   JSON op: {"id", "index", "pql", "shards", "field", "op"}

Appends ride :func:`syswrap.checked_write` plus a record-relative
``hints.append`` failpoint (same contract as ``oplog.append``), so
chaos schedules can tear a hint at any byte offset; recovery yields
the clean prefix and truncates the tail.  Ack-compaction rewrites the
log atomically (tmp + rename): a crash mid-ack re-sends at most one
batch, which the receiver's op-id window dedups.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
import zlib

from pilosa_tpu import fault
from pilosa_tpu.store import syswrap

_FRAME = struct.Struct("<IQdI")


def _peer_filename(peer: str) -> str:
    return peer.encode().hex() + ".hints"


def _peer_of_filename(name: str) -> str | None:
    if not name.endswith(".hints"):
        return None
    try:
        return bytes.fromhex(name[: -len(".hints")]).decode()
    except ValueError:
        return None


class HintLog:
    """One peer's append-only hint log.  Callers (HintBoard) hold the
    per-peer lock; this class owns only file framing + recovery."""

    def __init__(self, path: str, fsync: bool = False):
        self.path = path
        self.fsync = fsync
        self._f = None
        self._poisoned = False
        # (seq, ts, payload-dict), append order; seq strictly increases
        self.records: list[tuple[int, float, dict]] = []
        self.next_seq = 1
        self._recover()

    def _recover(self) -> None:
        """Clean-prefix recovery, oplog-style: stop at the first torn/
        corrupt record and physically truncate it away."""
        if not os.path.exists(self.path):
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        pos = 0
        good_end = 0
        while pos + _FRAME.size <= len(buf):
            crc, seq, ts, plen = _FRAME.unpack_from(buf, pos)
            end = pos + _FRAME.size + plen
            if end > len(buf):
                break
            body = buf[pos + 4:end]
            if zlib.crc32(body) != crc:
                break
            try:
                payload = json.loads(buf[pos + _FRAME.size:end])
            except ValueError:
                break  # CRC passed but payload unparsable: treat as torn
            self.records.append((seq, ts, payload))
            self.next_seq = max(self.next_seq, seq + 1)
            pos = end
            good_end = end
        if good_end < len(buf):
            with open(self.path, "r+b") as f:
                f.truncate(good_end)

    def _file(self):
        if self._f is None:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._f = open(self.path, "ab")
        return self._f

    def append(self, payload: dict) -> int:
        """Durably append one op; returns its seq.  Raises (and
        persists nothing past the tear) on injected/real write faults —
        the caller must fail the write op, not ack it.

        A failed append TRUNCATES the file back to its pre-append
        length before re-raising: the process keeps serving after a
        torn write (fault injection, ENOSPC, transient I/O error), and
        a later GOOD append landing BEHIND torn bytes would be
        silently discarded — along with every acked hint after it — by
        clean-prefix recovery on the next boot.  If even the truncate
        fails the log is poisoned: every further append refuses until
        reopen (losing availability, never an acked hint)."""
        if self._poisoned:
            raise OSError(f"hint log {self.path} has a torn tail that "
                          "could not be truncated; refusing to append "
                          "behind it")
        seq = self.next_seq
        ts = time.time()
        body_payload = json.dumps(payload, separators=(",", ":")).encode()
        body = struct.pack("<QdI", seq, ts, len(body_payload)) + body_payload
        record = struct.pack("<I", zlib.crc32(body)) + body
        f = self._file()
        clean_len = f.tell()
        try:
            if fault.ACTIVE:
                # record-relative torn tail, same contract as
                # oplog.append: persist only args.offset bytes of THIS
                # record then crash
                spec = fault.fire("hints.append", path=self.path,
                                  peer=payload.get("peer", ""))
                if spec is not None and spec["action"] == "torn_write":
                    fault.torn_write(f, record, spec)
            syswrap.checked_write(f, record)
            f.flush()
        except BaseException:
            try:
                f.truncate(clean_len)
                f.seek(clean_len)
            except OSError:
                self._poisoned = True
                self.close()
            raise
        if self.fsync:
            syswrap.checked_fsync(f)
        self.records.append((seq, ts, payload))
        self.next_seq = seq + 1
        return seq

    def ack(self, through_seq: int) -> int:
        """Drop records with seq <= through_seq (delivered) and compact
        the file atomically.  A crash mid-compaction leaves either the
        old or the new file — re-sent records dedup on the receiver."""
        keep = [r for r in self.records if r[0] > through_seq]
        dropped = len(self.records) - len(keep)
        if not dropped:
            return 0
        self.close()
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            for seq, ts, payload in keep:
                pb = json.dumps(payload, separators=(",", ":")).encode()
                body = struct.pack("<QdI", seq, ts, len(pb)) + pb
                f.write(struct.pack("<I", zlib.crc32(body)) + body)
            f.flush()
            if self.fsync:
                syswrap.checked_fsync(f)
        os.replace(tmp, self.path)
        self.records = keep
        self._poisoned = False  # the rewrite replaced any torn tail
        return dropped

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class HintBoard:
    """Every peer's hint log plus the bookkeeping the write path, the
    replay worker, and AAE gating consult.  Thread-safe."""

    def __init__(self, directory: str, max_age: float = 300.0,
                 fsync: bool = False, stats=None, logger=None):
        self.dir = directory
        self.max_age = float(max_age)
        self.fsync = fsync
        self._stats = stats
        self._logger = logger
        self._lock = threading.Lock()          # guards the maps
        self._logs: dict[str, HintLog] = {}
        self._peer_locks: dict[str, threading.Lock] = {}
        self._drain_locks: dict[str, threading.Lock] = {}
        # fragment-coverage summary for gated_fragment, rebuilt lazily
        # after any add/ack (None = stale): an AAE round issues one
        # merge POST per differing block, and re-walking the full
        # backlog per request is O(backlog x merges) exactly when the
        # node is degraded
        self._coverage: dict[str, list] | None = None
        os.makedirs(self.dir, exist_ok=True)
        # boot recovery: reload every peer's surviving log (clean
        # prefix; torn tails truncate) so a crashed coordinator's
        # hints replay after restart
        for name in sorted(os.listdir(self.dir)):
            peer = _peer_of_filename(name)
            if peer is None:
                continue
            log = HintLog(os.path.join(self.dir, name), fsync=fsync)
            if log.records:
                self._logs[peer] = log
                if logger is not None:
                    logger.info("hints: recovered %d pending op(s) "
                                "for %s", len(log.records), peer)
            else:
                log.close()
        self._export()

    # -- internal ------------------------------------------------------------

    def _peer_lock(self, peer: str) -> threading.Lock:
        with self._lock:
            lock = self._peer_locks.get(peer)
            if lock is None:
                lock = self._peer_locks[peer] = threading.Lock()
            return lock

    def _log(self, peer: str, create: bool = False) -> HintLog | None:
        with self._lock:
            log = self._logs.get(peer)
            if log is None and create:
                log = self._logs[peer] = HintLog(
                    os.path.join(self.dir, _peer_filename(peer)),
                    fsync=self.fsync)
            return log

    def _export(self) -> None:
        if self._stats is None:
            return
        with self._lock:
            peers = list(self._logs)
        for peer in peers:
            self._export_peer(peer)

    def _export_peer(self, peer: str) -> None:
        """Refresh ONE peer's backlog gauges — the write path calls
        this per hinted op, and paying O(all peers) there would
        serialize exactly when hint volume peaks (failure windows)."""
        if self._stats is None:
            return
        log = self._log(peer)
        n = len(log.records) if log is not None else 0
        self._stats.gauge("hint_backlog_ops", n, peer=peer)
        self._stats.gauge("hint_oldest_seconds",
                          round(self.oldest_age(peer), 3), peer=peer)

    # -- write path ----------------------------------------------------------

    def add(self, peer: str, payload: dict) -> int:
        """Durably hint one op for ``peer`` (appended in write order).
        Raises on persistence failure — the caller must NOT ack the
        write if its hint could not be made durable."""
        with self._peer_lock(peer):
            seq = self._log(peer, create=True).append(payload)
        with self._lock:
            self._coverage = None
        if self._stats is not None:
            self._stats.count("hint_appended_total", 1, peer=peer)
        self._export_peer(peer)
        return seq

    def pending_peers(self) -> set[str]:
        with self._lock:
            return {p for p, lg in self._logs.items() if lg.records}

    def has_pending(self, peer: str) -> bool:
        log = self._log(peer)
        return log is not None and bool(log.records)

    def pending_ops(self, peer: str | None = None) -> int:
        with self._lock:
            logs = ([self._logs[peer]] if peer is not None
                    and peer in self._logs else
                    list(self._logs.values()) if peer is None else [])
        return sum(len(lg.records) for lg in logs)

    def oldest_age(self, peer: str | None = None,
                   now: float | None = None) -> float:
        """Age (seconds) of the oldest pending hint — 0.0 when none."""
        now = time.time() if now is None else now
        with self._lock:
            logs = ([self._logs[peer]] if peer is not None
                    and peer in self._logs else
                    list(self._logs.values()) if peer is None else [])
        ts = [lg.records[0][1] for lg in logs if lg.records]
        return max(0.0, now - min(ts)) if ts else 0.0

    def overflowed(self, peer: str) -> bool:
        """The boundedness rule: once this peer's oldest pending hint
        outlives ``hint_max_age``, strict writes flip back to loud
        refusal and best-effort writes stop hinting (legacy AAE
        repair), so the log can never grow without bound."""
        return self.max_age > 0 and self.oldest_age(peer) > self.max_age

    # -- AAE gating ----------------------------------------------------------

    def gated_fragment(self, index: str, field: str, shard: int) -> bool:
        """True when any peer's pending hints cover this fragment — a
        union-merge into it could resurrect a clear the hinted peer has
        not replayed yet (receiver-side defense; the sender-side skip
        is peer-level via Cluster.hinted_peers).  ``field`` is the
        fragment's field name (always a string at the merge endpoint);
        answered from a lazily-rebuilt coverage summary so one AAE
        round's many merge requests don't each re-walk the backlog."""
        with self._lock:
            cov = self._coverage
            if cov is None:
                cov = self._coverage = self._build_coverage()
        c = cov.get(index)
        if c is None:
            return False
        all_fields, anyfield_shards, field_all_shards, field_shards = c
        return (all_fields or shard in anyfield_shards
                or field in field_all_shards
                or (field, shard) in field_shards)

    def _build_coverage(self) -> dict[str, list]:
        """index -> [matches-every-fragment, {shard} (any field),
        {field} (any shard), {(field, shard)}] over every pending
        record, decomposing the record predicate: a hint with field
        None covers every field, shards None covers every shard —
        conservative, never unsound.  Caller holds ``_lock``."""
        cov: dict[str, list] = {}
        for lg in self._logs.values():
            for _seq, _ts, p in lg.records:
                idx = p.get("index")
                if idx is None:
                    continue
                c = cov.get(idx)
                if c is None:
                    c = cov[idx] = [False, set(), set(), set()]
                pf = p.get("field")
                shards = p.get("shards")
                if pf is None and shards is None:
                    c[0] = True
                elif pf is None:
                    c[1].update(shards)
                elif shards is None:
                    c[2].add(pf)
                else:
                    c[3].update((pf, s) for s in shards)
        return cov

    # -- replay --------------------------------------------------------------

    def peek(self, peer: str, limit: int) -> list[tuple[int, dict]]:
        with self._peer_lock(peer):
            log = self._log(peer)
            if log is None:
                return []
            return [(seq, payload)
                    for seq, _ts, payload in log.records[:limit]]

    def ack(self, peer: str, through_seq: int) -> int:
        with self._peer_lock(peer):
            log = self._log(peer)
            dropped = log.ack(through_seq) if log is not None else 0
        if dropped:
            with self._lock:
                self._coverage = None
        self._export_peer(peer)
        return dropped

    def drain_lock(self, peer: str) -> threading.Lock:
        """Single-flight lock per peer for the replay worker."""
        with self._lock:
            lock = self._drain_locks.get(peer)
            if lock is None:
                lock = self._drain_locks[peer] = threading.Lock()
            return lock

    # -- introspection -------------------------------------------------------

    def summary(self) -> dict:
        """The ``writeHealth`` body: total backlog, oldest age, and the
        per-peer breakdown an operator needs to see which peer a stuck
        drain is waiting on."""
        now = time.time()
        with self._lock:
            items = [(p, list(lg.records)) for p, lg in self._logs.items()
                     if lg.records]
        peers = []
        for peer, records in sorted(items):
            age = now - records[0][1]
            bulk = sum(1 for _seq, _ts, rec in records
                       if rec.get("kind") == "import")
            peers.append({"id": peer, "pendingOps": len(records),
                          "bulkOps": bulk,
                          "oldestSeconds": round(max(0.0, age), 3),
                          "overflowed": (self.max_age > 0
                                         and age > self.max_age)})
        self._export()
        return {"hintBacklogOps": sum(p["pendingOps"] for p in peers),
                "hintBulkOps": sum(p["bulkOps"] for p in peers),
                "hintOldestSeconds": (max(p["oldestSeconds"]
                                          for p in peers) if peers
                                      else 0.0),
                "peers": peers}

    def close(self) -> None:
        with self._lock:
            for log in self._logs.values():
                log.close()
