"""Cluster layer (L3 of SURVEY.md §2): membership, distribution,
anti-entropy, resize."""

from pilosa_tpu.cluster.cluster import (STATE_DEGRADED, STATE_NORMAL,
                                        STATE_RESIZING, STATE_STARTING,
                                        Cluster)
from pilosa_tpu.cluster.dist import DistributedExecutor, merge_results

__all__ = [
    "Cluster", "DistributedExecutor", "merge_results",
    "STATE_STARTING", "STATE_NORMAL", "STATE_RESIZING", "STATE_DEGRADED",
]
