"""Per-peer circuit breakers for the cluster client layer.

``breaker_threshold`` CONSECUTIVE transport failures (connection
refused/reset, TLS alert — the peer never answered a request) open a
peer's breaker.  An open peer is skipped at read-routing time: the
fan-out goes straight to a live replica instead of paying a
connect-timeout tax on every query that touches the sick peer's
shards.  Half-open probes ride the existing heartbeat loop — each
round, an OPEN breaker steps to HALF_OPEN for the duration of that
round's heartbeat to the peer; a successful heartbeat (or any
successful request) closes it, a failure re-opens it immediately.

State is exported per peer: a ``peer_breaker_state`` gauge (0 closed,
1 half-open, 2 open), a ``breaker_transitions_total{peer,from,to}``
counter, and the ``clusterHealth`` block on ``/status``.

Scope: the breaker is an AVAILABILITY optimization, never a
correctness gate — the router falls back to an open peer when no
healthy replica remains, and the write path's strict semantics
(``dist._write``) never consult it.
"""

from __future__ import annotations

import threading

CLOSED = "closed"
HALF_OPEN = "half_open"
OPEN = "open"

# gauge encoding for peer_breaker_state
STATE_CODES = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class BreakerBoard:
    """All peers' breakers behind one lock (membership is a handful of
    nodes; contention is nil next to the I/O the breaker guards)."""

    def __init__(self, threshold: int = 3, stats=None, logger=None):
        self.threshold = max(1, int(threshold))
        self._stats = stats
        self._logger = logger
        self._lock = threading.Lock()
        self._state: dict[str, str] = {}
        self._fails: dict[str, int] = {}

    # -- introspection -------------------------------------------------------

    def state(self, peer: str) -> str:
        with self._lock:
            return self._state.get(peer, CLOSED)

    def states(self) -> dict[str, str]:
        with self._lock:
            return dict(self._state)

    def unhealthy_peers(self) -> set[str]:
        """Peers the read router should avoid: open, or mid-probe
        (half-open lets exactly the heartbeat probe through — a query
        racing the probe must not pile onto a still-sick peer)."""
        with self._lock:
            return {p for p, s in self._state.items() if s != CLOSED}

    # -- outcome recording ---------------------------------------------------

    def record_success(self, peer: str) -> None:
        """Any answered request (HTTP errors included: the peer is
        alive) resets the failure streak and closes the breaker."""
        with self._lock:
            self._fails[peer] = 0
            old = self._state.get(peer, CLOSED)
            if old != CLOSED:
                self._state[peer] = CLOSED
        if old != CLOSED:
            self._transition(peer, old, CLOSED)

    def record_failure(self, peer: str) -> None:
        """One transport failure.  Opens from CLOSED at the threshold;
        a HALF_OPEN probe failure re-opens immediately."""
        with self._lock:
            n = self._fails.get(peer, 0) + 1
            self._fails[peer] = n
            old = self._state.get(peer, CLOSED)
            new = old
            if old == HALF_OPEN or (old == CLOSED and n >= self.threshold):
                new = self._state[peer] = OPEN
        if new != old:
            self._transition(peer, old, new)

    def begin_probe(self, peer: str) -> bool:
        """OPEN → HALF_OPEN for one probe (the heartbeat loop calls
        this just before heartbeating the peer).  Returns whether a
        probe was actually begun."""
        with self._lock:
            if self._state.get(peer, CLOSED) != OPEN:
                return False
            self._state[peer] = HALF_OPEN
        self._transition(peer, OPEN, HALF_OPEN)
        return True

    def reset(self, peer: str) -> None:
        """Forget a peer's history (explicit rejoin: the node came back
        through the membership path, which is stronger evidence than
        any probe — it must be immediately routable again)."""
        with self._lock:
            old = self._state.pop(peer, CLOSED)
            self._fails.pop(peer, None)
        if old != CLOSED:
            self._transition(peer, old, CLOSED)

    # -- export --------------------------------------------------------------

    def _transition(self, peer: str, old: str, new: str) -> None:
        if self._logger is not None:
            log = (self._logger.warning if new == OPEN
                   else self._logger.info)
            log("peer breaker %s: %s -> %s", peer, old, new)
        if self._stats is not None:
            self._stats.gauge("peer_breaker_state", STATE_CODES[new],
                              peer=peer)
            self._stats.count("breaker_transitions_total", 1, peer=peer,
                              **{"from": old, "to": new})
