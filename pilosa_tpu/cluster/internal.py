"""Node-to-node internal HTTP handlers.

Reference: the ``/internal/*`` surface of ``http/handler.go`` —
query fan-out, fragment block/data exchange for AAE + resize, translate
streaming, cluster messages (SURVEY.md §3.3).  Registered into the main
router; every handler 503s when the node is not clustered.

**Idempotency contract**: every POST endpoint on this surface MUST be
idempotent.  The cluster layer's internode :class:`~pilosa_tpu.api
.client.Client` is constructed with ``idempotent_posts=True``, which
re-sends a request whose response was lost after the peer may already
have processed it (stale keep-alive socket, connection reset) —
at-least-once delivery.  The current endpoints all qualify: fragment
``merge`` is a union (∪ is idempotent), translate ``replicate`` dedupes
by log offset, ``heartbeat``/``status``/``schema`` apply last-writer
state merges, ``resize/push`` re-streams a union-merge, and
``hints/replay`` dedupes by unique op id against a durable window
(the r13 request-ID pattern this docstring used to promise).  A future
non-idempotent endpoint must NOT ride this client — give it a dedicated
``Client()`` (default: no retry after a possibly-delivered request) or
add request IDs."""

from __future__ import annotations

from pilosa_tpu.api.api import ApiError
from pilosa_tpu.api.server import Handler, Router
from pilosa_tpu.store import roaring


def _cluster(handler: Handler):
    cluster = handler.server.api.cluster
    if cluster is None:
        raise ApiError("node is not clustered", 503)
    return cluster


def _qs(handler: Handler, name: str) -> str:
    vals = handler.query.get(name)
    if not vals:
        raise ApiError(f"missing query param {name!r}")
    return vals[0]


def _fragment(handler: Handler, create: bool = False):
    api = handler.server.api
    idx = api.holder.index(_qs(handler, "index"))
    if idx is None:
        raise ApiError("index not found", 404)
    field = idx.field(_qs(handler, "field"))
    if field is None:
        raise ApiError("field not found", 404)
    view = field.view(_qs(handler, "view"), create=create)
    if view is None:
        raise ApiError("view not found", 404)
    frag = view.fragment(int(_qs(handler, "shard")), create=create)
    if frag is None:
        raise ApiError("fragment not found", 404)
    return frag


# -- handlers ----------------------------------------------------------------


def h_join(self: Handler) -> None:
    self._reply(_cluster(self).handle_join(self._json_body()))


def h_heartbeat(self: Handler) -> None:
    b = self._json_body()
    self._reply(_cluster(self).handle_heartbeat(
        b["id"], b.get("state", "NORMAL"),
        float(b.get("placementVersion", 0.0)),
        hints_for=b.get("hintsFor")))


def h_cluster_state(self: Handler) -> None:
    """Full cluster-state snapshot (pull-on-mismatch convergence for
    peers whose placement version trails the sender's)."""
    self._reply(_cluster(self).status_payload())


def h_cluster_status(self: Handler) -> None:
    _cluster(self).handle_status(self._json_body())
    self._reply({"success": True})


def h_internal_query(self: Handler) -> None:
    """Execute locally only (no re-fan-out) with raw-ID results —
    reference: ``/internal/query`` remote execution.

    Cross-node span fan-in (r9): a ``Traceparent`` header opens a
    node-tagged continuation span around the local execution, and the
    finished subtree rides back in the response as ``profile`` — the
    coordinator grafts it under its ``cluster.*`` span, so one profile
    tree covers every node.  Requests without the header pay nothing."""
    from pilosa_tpu.exec import result_to_json
    from pilosa_tpu.exec.executor import (ExecutionError,
                                          ExecutorSaturatedError,
                                          PipelineStalledError,
                                          QueryTimeoutError,
                                          WriteUnavailableError)
    from pilosa_tpu.pql.parser import ParseError
    import time

    api = self.server.api
    index = _qs(self, "index")
    shards = None
    if "shards" in self.query:
        shards = [int(s) for s in self.query["shards"][0].split(",") if s]
    deadline = None
    budget = None
    if "timeout" in self.query:
        # remaining budget shipped by the coordinator, re-anchored on
        # THIS node's monotonic clock.  Validated exactly like the
        # public ?timeout= (ADVICE r4) — this endpoint is reachable by
        # any peer.
        from pilosa_tpu.api.server import parse_timeout_param
        budget = parse_timeout_param(self.query["timeout"][0])
        deadline = time.monotonic() + budget
    t0 = time.monotonic()
    # storage quarantine gate (r19): a leg covering a shard whose
    # local fragment is quarantined must not serve (possibly corrupt)
    # bits — 503 here is transport-class to the coordinator's fan-out,
    # which re-groups the shards onto the next live replica (the PR 6
    # failover path, exactly as if the shard were remote)
    sh = getattr(api.holder, "storage_health", None)
    if sh is not None and sh.gate_active:
        bad = [s for s in (shards or ())
               if sh.shard_quarantined(index, s)]
        if not shards and any(
                e.get("key") and e["key"][0] == index
                for e in sh.quarantined_entries()):
            bad = ["*"]
        if bad:
            raise ApiError(
                f"shard(s) {bad} of {index!r} quarantined on this "
                "node (storage corruption): retry a replica", 503,
                retry_after=2.0)
    pql = self._body().decode()
    from contextlib import nullcontext

    tracer = span = None
    retain = False
    parsed = None
    tp = self.headers.get("Traceparent")
    if tp:
        from pilosa_tpu.obs import parse_traceparent
        parsed = parse_traceparent(tp)
    if parsed is not None and parsed[2] in ("01", "02"):
        from pilosa_tpu.obs import Tracer
        # the coordinator materializes this trace: build the
        # node-tagged subtree, ship it back for grafting.  "01"
        # (sampled/profiled) also keeps a copy in THIS node's ring;
        # "02" (slow-hunt) ships the subtree — a slow capture needs
        # it — WITHOUT churning the local 128-slot ring for every
        # query at serving rate
        tracer = Tracer()
        retain = parsed[2] == "01"
    exec_tracer = tracer
    if tracer is None and parsed is not None:
        # flags "00" = the coordinator runs the LITE path and will
        # never materialize a tree — building one here would be pure
        # per-request waste on every fan-out leg (the r05 class of
        # hot-path cost); serve under the allocation-free tracer
        from pilosa_tpu.obs import NULL_TRACER
        exec_tracer = NULL_TRACER
    node = (api.cluster.node_id if api.cluster is not None else "local")
    ctx = (tracer.extract(self.headers, "internal.query",
                          node=node, index=index)
           if tracer is not None else nullcontext())
    # propagated legs carry the coordinator's trace id in the header
    # (flags "00" included — the lite path propagates identity): make
    # it this thread's ACTIVE id so the peer's log lines join the same
    # trace as the coordinator's exemplar and span tree
    from pilosa_tpu.obs.tracing import set_current_trace_id
    set_current_trace_id(parsed[0] if parsed is not None else None)
    try:
        with ctx as span:
            results = api.executor.execute(index, pql, shards=shards,
                                           translate_output=False,
                                           deadline=deadline,
                                           tracer=exec_tracer)
    except QueryTimeoutError as e:
        # same structured 504 as the public edge: the coordinator maps
        # it back to QueryTimeoutError, and an operator curling a node
        # directly sees elapsed-vs-budget
        raise ApiError.timeout(e, time.monotonic() - t0, budget)
    except PipelineStalledError as e:
        # same structured 500 as the public edge (r18): a quarantined
        # dispatch-pipeline window on THIS node names the stalled
        # stage — the coordinator's fan-out sees a server fault, not a
        # bad query, and an operator curling the peer sees the stage
        raise ApiError.pipeline_stall(e)
    except ExecutorSaturatedError as e:
        # a saturated PEER is overload, not a bad query: 503 so the
        # coordinator's fan-out classifies it like a busy node (and a
        # best-effort write may route around it), never 400
        raise ApiError(str(e), 503, retry_after=e.retry_after)
    except WriteUnavailableError as e:
        # same structured 503 as the public edge (r13): a replica-down
        # refusal names the down replica and why handoff could not
        # cover it — unavailability, never a generic 400
        raise ApiError.write_unavailable(e)
    except (ParseError, ExecutionError) as e:
        raise ApiError(str(e), 400)
    finally:
        # handler threads serve keep-alive connections: a stale id
        # must not bleed into the next request's log lines
        set_current_trace_id(None)
    out = {"results": [result_to_json(r) for r in results]}
    if span is not None:
        # ship the finished subtree back for coordinator-side grafting
        out["profile"] = [span.to_json()]
        if retain:
            from pilosa_tpu.obs import GLOBAL_TRACER
            GLOBAL_TRACER.record(span)
    self._reply(out)


def h_shards(self: Handler) -> None:
    idx = self.server.api.holder.index(_qs(self, "index"))
    self._reply({"shards": idx.available_shards() if idx else []})


def h_fragments(self: Handler) -> None:
    self._reply({"fragments": _cluster(self)._local_inventory()})


def h_schema_apply(self: Handler) -> None:
    schema = self._json_body()["schema"]
    cluster = self.server.api.cluster
    if cluster is not None:
        schema = cluster.filter_schema(schema)
    self.server.api.apply_schema(schema)
    self._reply({"success": True})


def h_schema_delete(self: Handler) -> None:
    b = self._json_body()
    api = self.server.api
    if api.cluster is not None:
        api.cluster.record_schema_tombstone(b["index"], b.get("field"),
                                            b.get("ts", 0.0))
    try:
        if b.get("field"):
            api.delete_field(b["index"], b["field"], direct=True)
        else:
            api.delete_index(b["index"], direct=True)
    except ApiError as e:
        if e.status != 404:  # already gone on this node is fine
            raise
    self._reply({"success": True})


def h_translate(self: Handler) -> None:
    b = self._json_body()
    try:
        ids = _cluster(self).handle_translate(
            b["index"], b.get("field"), b["keys"], b.get("create", False))
    except PermissionError as e:
        raise ApiError(str(e), 409)
    self._reply({"ids": ids})


def h_translate_replicate(self: Handler) -> None:
    b = self._json_body()
    cluster = _cluster(self)
    log = (cluster.api.executor.translate.columns(b["index"])
           if b.get("field") is None
           else cluster.api.executor.translate.rows(b["index"], b["field"]))
    try:
        log.append_replicated(b["start_id"], b["keys"])
    except KeyError as e:
        raise ApiError(str(e), 409)
    self._reply({"len": len(log)})


def _translate_log(self: Handler):
    api = self.server.api
    index = _qs(self, "index")
    field = self.query.get("field", [""])[0] or None
    return (api.executor.translate.columns(index) if field is None
            else api.executor.translate.rows(index, field))


def h_translate_tail(self: Handler) -> None:
    log = _translate_log(self)
    after = int(self.query.get("after", ["0"])[0])
    limit = self.query.get("limit", [None])[0]
    limit = int(limit) if limit else None
    self._reply({"keys": log.tail(after, limit=limit), "len": len(log)})


def h_translate_len(self: Handler) -> None:
    self._reply({"len": len(_translate_log(self))})


def h_translate_logs(self: Handler) -> None:
    stores = self.server.api.executor.translate.list_stores()
    self._reply({"logs": [{"index": i, "field": f} for i, f in stores]})


def h_hints_replay(self: Handler) -> None:
    """Drain-side receive path for durable hinted handoff (r13): apply
    a batch of hinted ops IN ORDER, deduping by unique op id against
    the node's durable :class:`~pilosa_tpu.store.oplog.IdWindow` —
    re-delivered batches (lost response, sender crash mid-ack) are
    no-ops, so the at-least-once internode retry is safe here.

    A hint that can no longer apply (index/field deleted since it was
    queued) is DROPPED with a warning rather than wedging the sender's
    drain forever — but only once this node's boot-time schema pull
    has settled (``Cluster.schema_settled``): a drain kicked by our
    own join request can arrive BEFORE the join response's
    ``apply_schema`` lands, and judging "deleted" then would
    permanently lose an acked write for an index created while this
    node was down.  A not-yet-settled miss (and a saturated executor)
    answers 503 so the sender retries the whole batch later (the
    applied prefix dedups)."""
    from pilosa_tpu.exec.executor import (ExecutionError,
                                          ExecutorSaturatedError)
    from pilosa_tpu.pql.parser import ParseError
    from pilosa_tpu.store.health import StorageFaultError

    cluster = _cluster(self)
    api = self.server.api
    applied = deduped = dropped = 0
    for op in self._json_body().get("ops", []):
        op_id = str(op.get("id", ""))
        if not op_id:
            raise ApiError("hint op missing id")
        if op_id in cluster.applied_ops:
            deduped += 1
            continue
        fld = op.get("field")
        fld = str(fld) if fld is not None else None
        idx_obj = api.holder.index(op["index"])
        if ((idx_obj is None
             or (fld is not None and idx_obj.field(fld) is None))
                and not cluster.schema_settled(op["index"], fld)):
            raise ApiError(
                f"hint replay deferred: index {op['index']!r}"
                + (f" field {fld!r}" if fld is not None else "")
                + " not known here yet (schema pull pending)",
                503, retry_after=1.0)
        shards = op.get("shards")
        try:
            if op.get("kind") == "import":
                # bulk-import hint (r15): apply the batch payload
                # straight into fragments — same dedup/order contract
                # as PQL hints, no PQL round trip.  Un-applyable
                # payloads (field gone → 404, malformed roaring/ids)
                # reclassify as ExecutionError so ONLY this leg takes
                # the drop path — PQL replay errors keep their pre-r15
                # classes (an unexpected ValueError there must stay a
                # retryable 500, not a permanent applied-marked drop)
                from pilosa_tpu.ingest import apply_import_hint
                try:
                    apply_import_hint(api, op)
                except ApiError as e:
                    if e.status == 503:
                        raise  # deferred: sender retries the batch
                    raise ExecutionError(str(e)) from e
                except (ValueError, KeyError) as e:
                    raise ExecutionError(str(e)) from e
            else:
                api.executor.execute(
                    op["index"], op["pql"],
                    shards=([int(s) for s in shards] if shards else None),
                    translate_output=False)
        except ExecutorSaturatedError as e:
            raise ApiError(str(e), 503, retry_after=e.retry_after)
        except StorageFaultError as e:
            # this node's storage is sick (read-only on disk-full, or
            # the target fragment quarantined, r19): the op is
            # RETRYABLE, never droppable — defer the whole batch (the
            # applied prefix dedups on the retry)
            raise ApiError.storage_fault(e)
        except (ParseError, ExecutionError) as e:
            cluster.logger.warning(
                "hint replay dropped %s on %s: %s",
                op.get("op", "?"), op.get("index", "?"), e)
            dropped += 1
            cluster.stats.count("hint_replay_dropped_total", 1)
            cluster.applied_ops.add(op_id)
            continue
        cluster.applied_ops.add(op_id)
        applied += 1
    self._reply({"applied": applied, "deduped": deduped,
                 "dropped": dropped})


def _check_fragment_health(handler: Handler, frag) -> None:
    """AAE exchange gate (r19): a quarantined fragment's bytes are
    untrustworthy — serving its blocks/data would spread the
    corruption to replicas.  503 defers the peer's sync until repair
    un-quarantines (the repair itself pulls FROM the healthy peer, so
    this gate never deadlocks a repair)."""
    sh = getattr(handler.server.api.holder, "storage_health", None)
    if sh is not None and sh.is_quarantined(frag.path):
        raise ApiError(
            f"fragment quarantined (storage corruption): {frag.path} "
            "— sync deferred until replica repair completes", 503,
            retry_after=2.0)


def h_fragment_blocks(self: Handler) -> None:
    cluster = self.server.api.cluster
    if cluster is not None and cluster.node_id in cluster.hinted_peers():
        # this node has hinted writes pending somewhere: its copies
        # are stale until the replay lands — a peer diffing against
        # them now could union a cleared bit back in.  409 defers the
        # sync (the peer retries after the drain).
        raise ApiError("fragment blocks deferred: hinted writes "
                       "pending for this node (replay first)", 409)
    frag = _fragment(self)
    _check_fragment_health(self, frag)
    self._reply({"blocks": {str(k): v for k, v in frag.blocks().items()}})


def h_fragment_data(self: Handler) -> None:
    frag = _fragment(self)
    _check_fragment_health(self, frag)
    if "block" in self.query:
        positions = frag.block_positions(int(_qs(self, "block")))
    else:
        positions = frag.positions()
    self._reply(roaring.serialize(positions),
                content_type="application/octet-stream")


def h_fragment_merge(self: Handler) -> None:
    cluster = self.server.api.cluster
    if (cluster is not None and cluster.hints is not None
            and cluster.hints.gated_fragment(
                _qs(self, "index"), _qs(self, "field"),
                int(_qs(self, "shard")))):
        # this node coordinated writes still hinted for a down peer
        # covering this fragment: a union-merge in could resurrect a
        # Clear the replay is about to deliver — defer until drained
        raise ApiError("fragment merge deferred: pending hinted "
                       "writes cover it (retry after drain)", 409)
    frag = _fragment(self, create=True)
    body = self._body()
    changed = frag.merge_positions(roaring.deserialize(body))
    stats = getattr(self.server, "stats", None)
    if stats is not None and self.headers.get("X-Pilosa-Restore") == "1":
        # restore pushes ride this union-merge path; tag their volume
        # separately from AAE repair traffic
        stats.count("restore_bytes_total", len(body))
    self._reply({"changed": changed})


def h_aae_run(self: Handler) -> None:
    """Force one anti-entropy round NOW (restore's convergence step —
    replicas a push missed must not wait out the periodic sweep)."""
    self._reply({"repaired": _cluster(self).sync_once()})


def _attr_store(self: Handler):
    api = self.server.api
    idx = api.holder.index(_qs(self, "index"))
    if idx is None:
        raise ApiError("index not found", 404)
    field = self.query.get("field", [""])[0]
    if field:
        f = idx.field(field)
        if f is None:
            raise ApiError("field not found", 404)
        return f.row_attrs
    return idx.column_attrs


def h_attr_blocks(self: Handler) -> None:
    store = _attr_store(self)
    self._reply({"blocks": {str(k): v for k, v in store.blocks().items()}})


def h_attr_block(self: Handler) -> None:
    store = _attr_store(self)
    items = store.block_items(int(_qs(self, "block")))
    self._reply({"items": {str(k): v for k, v in items.items()}})


def h_attr_merge(self: Handler) -> None:
    store = _attr_store(self)
    items = {int(k): v for k, v in self._json_body()["items"].items()}
    self._reply({"changed": store.merge_items(items)})


def h_resize_push(self: Handler) -> None:
    b = self._json_body()
    _cluster(self).push_fragment(b["index"], b["field"], b["view"],
                                 b["shard"], b["dest"])
    self._reply({"success": True})


def h_resize_trigger(self: Handler) -> None:
    cluster = _cluster(self)
    if not cluster.is_coordinator():
        raise ApiError("not the coordinator", 409)
    cluster.trigger_resize()
    self._reply({"success": True})


def h_resize_abort(self: Handler) -> None:
    """Abort an in-flight rebalance (reference: ResizeJob abort)."""
    cluster = _cluster(self)
    if not cluster.is_coordinator():
        raise ApiError("not the coordinator", 409)
    cluster.abort_resize()
    self._reply({"success": True})


def h_node_remove_internal(self: Handler) -> None:
    _cluster(self).handle_node_remove(self._json_body())
    self._reply({"success": True})


def h_node_remove(self: Handler, node: str) -> None:
    """Operator surface: remove a (dead or retiring) node.  Must be sent
    to the coordinator (reference: coordinator-driven remove-node
    resize)."""
    cluster = _cluster(self)
    try:
        cluster.remove_node(node)
    except PermissionError as e:
        raise ApiError(str(e), 409)
    except KeyError:
        raise ApiError(f"node {node!r} not in cluster", 404)
    except ValueError as e:
        raise ApiError(str(e), 400)
    self._reply({"success": True})


def register_internal_routes(router: Router) -> None:
    router.add("POST", "/internal/join", h_join)
    router.add("POST", "/internal/heartbeat", h_heartbeat)
    router.add("POST", "/internal/cluster/status", h_cluster_status)
    router.add("GET", "/internal/cluster/state", h_cluster_state)
    router.add("POST", "/internal/query", h_internal_query)
    router.add("GET", "/internal/shards", h_shards)
    router.add("GET", "/internal/fragments", h_fragments)
    router.add("POST", "/internal/schema", h_schema_apply)
    router.add("POST", "/internal/schema/delete", h_schema_delete)
    router.add("POST", "/internal/translate", h_translate)
    router.add("POST", "/internal/translate/replicate", h_translate_replicate)
    router.add("GET", "/internal/translate/tail", h_translate_tail)
    router.add("GET", "/internal/translate/len", h_translate_len)
    router.add("GET", "/internal/translate/logs", h_translate_logs)
    router.add("GET", "/internal/fragment/blocks", h_fragment_blocks)
    router.add("GET", "/internal/fragment/data", h_fragment_data)
    router.add("POST", "/internal/fragment/merge", h_fragment_merge)
    router.add("POST", "/internal/hints/replay", h_hints_replay)
    router.add("POST", "/internal/aae/run", h_aae_run)
    router.add("POST", "/internal/resize/push", h_resize_push)
    router.add("POST", "/internal/resize/trigger", h_resize_trigger)
    router.add("POST", "/internal/resize/abort", h_resize_abort)
    router.add("GET", "/internal/attrs/blocks", h_attr_blocks)
    router.add("GET", "/internal/attrs/block", h_attr_block)
    router.add("POST", "/internal/attrs/merge", h_attr_merge)
    router.add("POST", "/internal/node/remove", h_node_remove_internal)
    router.add("DELETE", "/cluster/node/{node}", h_node_remove)
